"""Compile secret rules into device-executable match programs.

The TPU secret path is a two-stage design (the reference itself stages a
keyword prefilter before the regex, ref: pkg/fanal/secret/scanner.go:174-186,
377-463): the device evaluates every rule against every chunk and returns a
per-(chunk, rule) *hit boolean*; the host then runs the exact regex engine
(`SecretScanner`) on just the flagged (file, rule) pairs. Device hits may
contain false positives (they only cost a cheap host confirmation) but must
never contain false negatives — that invariant is what makes the final
findings byte-identical to the CPU backend.

Each rule compiles into one of three lanes:

- **anchored lane**: the regex lowers to one or more *variants*, each an
  anchor literal (>= 3 bytes at a fixed offset from the match start) plus
  character-class window checks at fixed offsets. Constructs that won't
  lower (lookarounds, backrefs, optional/variable mid-pattern runs, anchors)
  are *truncated*: dropping a required suffix condition only weakens the
  predicate, which can only add false positives — soundness is preserved.
- **keyword lane**: rules that don't lower use their keyword prefilter
  (lowercased substring search, exactly the reference's `MatchKeywords`
  semantics) on device.
- **host lane**: rules with neither an anchored program nor keywords are
  evaluated host-side on every file (the reference also regex-scans every
  file for keyword-less rules).

The compiled output is a set of flat tables consumed by
`trivy_tpu.ops.match.build_match_fn`.
"""

from __future__ import annotations

import re

try:  # 3.11+ spelling
    import re._constants as sre_c
    import re._parser as sre_parse
except ImportError:  # 3.10 and earlier expose the same modules top-level
    import sre_constants as sre_c
    import sre_parse
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu.secret.rules import Rule

# Minimum anchor literal length: shorter literals are too common to be useful
# hash anchors and would flood the host-confirm stage.
MIN_ANCHOR = 3
# Cap on variants per rule (branch fan-out) before falling back to keywords.
MAX_VARIANTS = 48
# Cap on expanding fixed repeats into per-byte classes.
MAX_EXPAND = 64

_ALL_BYTES = frozenset(range(256))
_NL = ord("\n")
# Content is scanned as latin-1 text (1:1 byte<->char), so \d/\w/\s must use
# Python's *unicode* semantics restricted to the first 256 codepoints — the
# ASCII-only sets would silently drop matches like \xa0 for \s (a device
# false negative, breaking the no-FN contract for custom rules).
_DIGITS = frozenset(b for b in range(256) if re.match(r"\d", chr(b)))
_WORD = frozenset(b for b in range(256) if re.match(r"\w", chr(b)))
_SPACES = frozenset(b for b in range(256) if re.match(r"\s", chr(b)))
_ALNUM = frozenset(range(48, 58)) | frozenset(range(65, 91)) | frozenset(range(97, 123))


class _Truncate(Exception):
    """Lowering stopped at an un-lowerable construct; tokens accumulated so
    far (mutated in place) remain valid as a weaker predicate."""


@dataclass(frozen=True)
class Token:
    """A run of ``count`` mandatory chars drawn from ``chars``."""

    chars: frozenset
    count: int


@dataclass
class Check:
    """Window check: positions [anchor+delta, anchor+delta+count) all in class."""

    chars: frozenset
    count: int
    delta: int  # offset from the anchor's first byte (may be negative)
    class_id: int = -1


@dataclass
class Variant:
    anchor: bytes
    checks: list[Check] = field(default_factory=list)
    pre_len: int = 0  # fixed bytes between match start and anchor start
    boundary: bool = False  # require non-alnum (or pos 0) before match start

    @property
    def window(self) -> tuple[int, int]:
        """[lo, hi) byte range the program inspects, relative to the anchor."""
        lo = min(
            [0]
            + [c.delta for c in self.checks]
            + ([-self.pre_len - 1] if self.boundary else [])
        )
        hi = max([len(self.anchor)] + [c.delta + c.count for c in self.checks])
        return lo, hi


@dataclass
class CompiledRules:
    """Device tables for one effective ruleset.

    ``rule_ids`` indexes the output axis of the match kernel; a hit for rule
    ``i`` means "run exact rule ``rule_ids[i]`` on this file host-side".
    ``host_rule_ids`` must be evaluated host-side on every file.
    """

    rule_ids: list[str]
    classes: np.ndarray  # [n_classes, 256] bool
    variants: list[tuple[int, Variant]]  # (rule_index, variant)
    keywords: list[tuple[int, bytes]]  # (rule_index, lowercased keyword)
    host_rule_ids: list[str]
    margin: int  # max bytes a program inspects beyond/behind a position
    span: int = 8  # required chunk overlap (max device-window extent)
    anchored_rule_ids: list[str] = field(default_factory=list)
    # keyword-prefilter table: (rule_index, ascii-lowered keyword) for EVERY
    # device rule that declares keywords — the keyword lane's own entries
    # plus anchored-lane rules that also declare keywords. The on-device
    # prefilter (ops/prefilter.py) runs this table over every arena slab
    # first; rows with zero candidate rules skip the anchored/NFA dispatch
    # entirely and candidates gate host confirms at file level (the
    # reference's MatchKeywords is a whole-file test, scanner.go:174-186).
    prefilter_keywords: list[tuple[int, bytes]] = field(default_factory=list)

    @property
    def num_rules(self) -> int:
        return len(self.rule_ids)

    @property
    def guarded(self) -> np.ndarray:
        """[R] bool: rules whose keywords are in the prefilter table — a
        prefilter miss across a whole file means the rule cannot match it
        (keywords are a whole-file predicate in the exact engine)."""
        g = np.zeros(self.num_rules, dtype=bool)
        for ridx, _ in self.prefilter_keywords:
            g[ridx] = True
        return g

    def prefilter_fingerprint(self) -> bytes:
        """Digest of the prefilter table: any keyword add/remove/edit (or a
        rule-index renumbering) flips it. Mixed into the dedup-cache key so
        cached hit/candidate vectors can never survive a ruleset keyword
        edit."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        for ridx, kw in sorted(self.prefilter_keywords):
            h.update(ridx.to_bytes(4, "little"))
            h.update(len(kw).to_bytes(4, "little"))
            h.update(kw)
        return h.digest()


def _category_chars(cat) -> frozenset:
    if cat == sre_c.CATEGORY_DIGIT:
        return _DIGITS
    if cat == sre_c.CATEGORY_NOT_DIGIT:
        return _ALL_BYTES - _DIGITS
    if cat == sre_c.CATEGORY_WORD:
        return _WORD
    if cat == sre_c.CATEGORY_NOT_WORD:
        return _ALL_BYTES - _WORD
    if cat == sre_c.CATEGORY_SPACE:
        return _SPACES
    if cat == sre_c.CATEGORY_NOT_SPACE:
        return _ALL_BYTES - _SPACES
    raise _Truncate


def _in_chars(items) -> frozenset:
    negate = False
    chars: set[int] = set()
    for op, av in items:
        if op == sre_c.NEGATE:
            negate = True
        elif op == sre_c.LITERAL:
            if av < 256:
                chars.add(av)
        elif op == sre_c.RANGE:
            lo, hi = av
            chars.update(range(lo, min(hi, 255) + 1))
        elif op == sre_c.CATEGORY:
            chars.update(_category_chars(av))
        else:
            raise _Truncate
    return frozenset(_ALL_BYTES - chars) if negate else frozenset(chars)


def _single_chars(op, av, dotall: bool = False) -> frozenset:
    """Character set of a single-position node."""
    if op == sre_c.LITERAL:
        if av >= 256:
            raise _Truncate
        return frozenset({av})
    if op == sre_c.NOT_LITERAL:
        return _ALL_BYTES - {av}
    if op == sre_c.IN:
        return _in_chars(av)
    if op == sre_c.ANY:
        return _ALL_BYTES if dotall else _ALL_BYTES - {_NL}
    raise _Truncate


def _is_word_prefix_branch(op, av) -> frozenset | None:
    """Detect the leading ``(?:^|[^...])`` word-boundary idiom
    (ref: builtin-rules.go:81 startWord) and return its boundary class."""
    if op != sre_c.BRANCH:
        return None
    _, alts = av
    if len(alts) != 2:
        return None
    for a, b in ((list(alts[0]), list(alts[1])), (list(alts[1]), list(alts[0]))):
        if len(a) == 1 and a[0][0] == sre_c.AT and len(b) == 1:
            try:
                return _single_chars(*b[0])
            except _Truncate:
                return None
    return None


def _walk(nodes, streams: list[list[Token]], dotall: bool = False) -> None:
    """Lower an AST node sequence onto every open token stream, mutating
    ``streams`` in place so partial progress survives :class:`_Truncate`.
    """
    for op, av in nodes:
        if op in (sre_c.LITERAL, sre_c.NOT_LITERAL, sre_c.IN, sre_c.ANY):
            tok = Token(_single_chars(op, av, dotall), 1)
            for s in streams:
                s.append(tok)
        elif op in (sre_c.MAX_REPEAT, sre_c.MIN_REPEAT):
            lo, hi, sub = av
            sub = list(sub)
            if len(sub) == 1 and sub[0][0] in (
                sre_c.LITERAL,
                sre_c.NOT_LITERAL,
                sre_c.IN,
                sre_c.ANY,
            ):
                chars = _single_chars(*sub[0], dotall)
                if lo > 0:
                    for s in streams:
                        s.append(Token(chars, lo))
                if hi != lo:
                    # variable run: offsets beyond it are unknown
                    raise _Truncate
            else:
                if lo == 0:
                    raise _Truncate
                if lo * max(1, len(sub)) > MAX_EXPAND:
                    # check the first mandatory copy, then stop
                    _walk(sub, streams, dotall)
                    raise _Truncate
                for _ in range(lo):
                    _walk(sub, streams, dotall)
                if hi != lo:
                    raise _Truncate
        elif op == sre_c.SUBPATTERN:
            _g, add_f, _del_f, sub = av
            if add_f & re.IGNORECASE:
                raise _Truncate
            _walk(list(sub), streams, dotall or bool(add_f & re.DOTALL))
        elif op == sre_c.BRANCH:
            _, alts = av
            if len(streams) * len(alts) > MAX_VARIANTS:
                raise _Truncate
            forked: list[list[Token]] = []
            truncated = False
            for alt in alts:
                alt_streams = [list(s) for s in streams]
                try:
                    _walk(list(alt), alt_streams, dotall)
                except _Truncate:
                    truncated = True
                forked.extend(alt_streams)
            streams[:] = forked
            if truncated:
                raise _Truncate
        else:
            # AT, ASSERT, ASSERT_NOT, GROUPREF, ...: cannot lower
            raise _Truncate


def _compile_variant(tokens: list[Token], boundary: bool) -> Variant | None:
    # expand fixed tokens into per-byte classes (long runs keep run form)
    seq: list[frozenset] = []
    tail_runs: list[Token] = []  # runs too long to expand, kept as checks
    for t in tokens:
        if t.count > MAX_EXPAND:
            tail_runs.append(t)
            break  # positions after it are known, but keep it simple
        seq.extend([t.chars] * t.count)

    # anchor = longest run of singleton classes
    best: tuple[int, int] | None = None
    i = 0
    while i < len(seq):
        if len(seq[i]) == 1:
            j = i
            while j < len(seq) and len(seq[j]) == 1:
                j += 1
            if best is None or (j - i) > best[1]:
                best = (i, j - i)
            i = j
        else:
            i += 1
    if best is None or best[1] < MIN_ANCHOR:
        return None
    a_start, a_len = best
    anchor = bytes(next(iter(seq[k])) for k in range(a_start, a_start + a_len))
    v = Variant(anchor=anchor, pre_len=a_start, boundary=boundary)

    checks: list[Check] = []
    k = 0
    while k < len(seq):
        if a_start <= k < a_start + a_len:
            k += 1
            continue
        chars = seq[k]
        j = k
        while j < len(seq) and not (a_start <= j < a_start + a_len) and seq[j] == chars:
            j += 1
        if chars != _ALL_BYTES:
            checks.append(Check(chars=chars, count=j - k, delta=k - a_start))
        k = j
    for t in tail_runs:
        if t.chars != _ALL_BYTES:
            checks.append(Check(chars=t.chars, count=t.count, delta=len(seq) - a_start))
    v.checks = checks
    return v


def compile_rule(rule: Rule) -> list[Variant] | None:
    """Lower one rule to anchored variants, or None for keyword/host lane."""
    try:
        tree = sre_parse.parse(rule.regex)
    except Exception:
        return None
    if tree.state.flags & re.IGNORECASE:
        return None
    nodes = list(tree)
    boundary = False
    if nodes:
        bc = _is_word_prefix_branch(*nodes[0])
        if bc is not None:
            # only the standard non-alnum boundary is modeled; any other
            # boundary class is skipped (sound: weaker predicate)
            boundary = bc == (_ALL_BYTES - _ALNUM)
            nodes = nodes[1:]
    streams: list[list[Token]] = [[]]
    try:
        _walk(nodes, streams, dotall=bool(tree.state.flags & re.DOTALL))
    except _Truncate:
        pass
    variants = []
    for s in streams:
        v = _compile_variant(s, boundary)
        if v is None:
            return None  # every variant must be detectable, else no-FN breaks
        variants.append(v)
    return variants or None


def compile_rules(rules: list[Rule]) -> CompiledRules:
    """Compile an effective ruleset to device tables."""
    rule_ids: list[str] = []
    variants: list[tuple[int, Variant]] = []
    keywords: list[tuple[int, bytes]] = []
    host_rule_ids: list[str] = []
    anchored_rule_ids: list[str] = []
    class_index: dict[frozenset, int] = {}

    prefilter_keywords: list[tuple[int, bytes]] = []

    def kw_bytes(rule: Rule) -> list[bytes]:
        # a keyword with chars >255 can never be a substring of latin-1
        # scan content, so dropping it keeps the device keyword test
        # EXACTLY equal to the host's match_keywords, not merely sound
        out = []
        for kw in rule.lower_keywords:
            try:
                out.append(kw.encode("latin-1"))
            except UnicodeEncodeError:
                continue
        return out

    for rule in rules:
        prog = compile_rule(rule)
        if prog is not None:
            ridx = len(rule_ids)
            rule_ids.append(rule.id)
            anchored_rule_ids.append(rule.id)
            for v in prog:
                for c in v.checks:
                    if c.chars not in class_index:
                        class_index[c.chars] = len(class_index)
                    c.class_id = class_index[c.chars]
                variants.append((ridx, v))
            # anchored rules that also declare keywords join the prefilter
            # table: their confirms gate on a whole-file keyword candidate
            kb = kw_bytes(rule)
            if kb:
                prefilter_keywords.extend((ridx, k) for k in kb)
        elif rule.lower_keywords:
            kb = kw_bytes(rule)
            if not kb:
                # no representable keyword: nothing for the device to find
                host_rule_ids.append(rule.id)
                continue
            ridx = len(rule_ids)
            rule_ids.append(rule.id)
            for k in kb:
                keywords.append((ridx, k))
            prefilter_keywords.extend((ridx, k) for k in kb)
        else:
            host_rule_ids.append(rule.id)

    classes = np.zeros((max(1, len(class_index)), 256), dtype=bool)
    for chars, idx in class_index.items():
        classes[idx, list(chars)] = True

    # margin: array padding for shifted reads; span: required chunk overlap
    # so every device window lies fully inside at least one chunk's real data
    margin = 8
    span = 8
    for _, v in variants:
        lo, hi = v.window
        margin = max(margin, hi, -lo)
        span = max(span, hi - lo)
    for _, kw in keywords:
        margin = max(margin, len(kw))
        span = max(span, len(kw))
    for _, kw in prefilter_keywords:
        # anchored-lane keywords run only in the prefilter kernel, which
        # shares the padded-row layout — the overlap must cover them too
        margin = max(margin, len(kw))
        span = max(span, len(kw))

    return CompiledRules(
        rule_ids=rule_ids,
        classes=classes,
        variants=variants,
        keywords=keywords,
        host_rule_ids=host_rule_ids,
        margin=margin,
        span=span,
        anchored_rule_ids=anchored_rule_ids,
        prefilter_keywords=prefilter_keywords,
    )
