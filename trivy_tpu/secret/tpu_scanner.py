"""TPU-backed secret scanner: device prefilter + exact host confirmation.

Pipeline (replaces the reference's walk→goroutine→regexp chain, ref:
pkg/fanal/secret/scanner.go:377 and SURVEY.md §3.2):

  files → overlapping fixed-size chunks → [B, C] batches → device match
  kernel → per-(file, rule) candidates → exact `SecretScanner` restricted to
  candidate rules → findings (byte-identical to the CPU backend).

Chunk overlap equals the compiled ruleset's maximum device window, so every
device-checkable window lies fully inside at least one chunk. The host
confirm is window-restricted only where the flagged chunk provably bounds
the match start (anchored lane; keyword lane with the keyword inside every
match — see ``_windowed_ids``); other keyword-lane rules rescan the whole
file on flag, with unbounded-width regexes accelerated by their bounded
start-detector prefix (``Rule.start_detector``).

Batches are dispatched asynchronously (JAX dispatch is async by default)
through a depth-PIPELINE_DEPTH pipeline: the host packs batches N+1..N+k
while the device matches batch N — the TPU analog of the reference's
`parallel.Pipeline` feeder/worker split (ref: pkg/parallel/pipeline.go:14-115).
Dispatch shapes are drawn from a fixed bucket ladder (B, B/2, B/4, ...) so
every shape compiles exactly once; exact host confirmation runs in a small
thread pool that overlaps with the blocking device-result fetches (which
release the GIL).

The feed path sends link bytes ≪ corpus bytes (the host→device link, not
the kernel, is the e2e ceiling):

- **chunk-dedup hit cache**: every row is content-hashed (keyed blake2b,
  key = ruleset fingerprint so a rule add/remove/change flips every key)
  and duplicate rows — vendored deps, repeated OCI layer content, zero
  pages — reuse the cached per-rule hit vector with no upload and no
  kernel. Sound because the hit vector is a pure function of (row bytes,
  compiled tables); path-dependent filtering happens later, host-side.
  Bounded in-process LRU, optionally persisted through the trivy_tpu.cache
  layer (fs/redis) for cross-scan reuse — the same insight as the
  reference's layer cache: never re-scan content already seen.
- **small-file row packing**: files smaller than a row share one row,
  separated by ≥-span zero guard gaps. A real match's device program reads
  only match bytes (+1 boundary byte), so packing can never suppress a hit;
  cross-file windows only add false candidates that the exact host confirm
  discards.
- **round-robin multi-stream dispatch** (parallel.mesh.round_robin_match_fn)
  sends whole batches to each local device in turn so transfers overlap
  kernels across devices, multiplying effective link bandwidth.

Failure domains (README "Robustness"): a failed batch re-dispatches up to
``batch_retries`` times (OOM-shaped errors split the batch in half
instead), round-robin dispatch carries a per-device circuit breaker that
excludes a dying device and re-probes it on a backoff, and when nothing
device-side survives the scan completes on the exact host confirm path —
the parity oracle — with findings byte-identical and the scan flagged
degraded.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
from collections import OrderedDict, deque
from collections.abc import Iterable, Iterator
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu import faults, log, obs
from trivy_tpu.ops.match import build_match_fn
from trivy_tpu.secret.device_compile import CompiledRules, compile_rules
from trivy_tpu.secret.engine import ScannerConfig, SecretScanner
from trivy_tpu.types import Secret

logger = log.logger("secret:tpu")

DEFAULT_CHUNK_LEN = 65536
DEFAULT_BATCH = 64
# pallas path: small self-contained rows.
# 1024 x 8 KiB = 8 MiB batches: small enough that pack -> transfer ->
# kernel -> confirm overlap through the pipeline (a 32 MiB batch serializes
# the whole corpus behind one blocking device wait), big enough to amortize
# kernel launch; 8 KiB rows keep the kernel's VMEM working set off the
# spill cliff that 16 KiB rows hit
PALLAS_CHUNK_LEN = 8192
PALLAS_BATCH = 1024
# batches in flight before the oldest result is fetched
PIPELINE_DEPTH = 3
# workers for exact host confirmation (overlaps device-result waits)
CONFIRM_WORKERS = 4
# bounded in-process LRU for the chunk-dedup hit cache; most entries are an
# empty tuple (clean chunk), so 64k entries cost a few MB
HIT_CACHE_ENTRIES = 1 << 16
# bump when device-compile semantics change in a way that alters hit
# vectors for identical (rules, chunk) inputs — invalidates persisted caches
HIT_CACHE_VERSION = 1
# re-dispatches allowed per failed batch before the failure escalates to
# the scan-level fallback ladder (OOM-shaped splits don't consume this
# budget: halving strictly shrinks the batch, so it terminates on its own)
BATCH_RETRIES = 2

# error shapes that mean "the batch was too big", answered by halving the
# batch instead of retrying it whole (XLA/PJRT spellings + the injected one)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted", "out of memory",
                "Out of memory", "OOM")


def _is_oom(err: BaseException) -> bool:
    s = f"{type(err).__name__}: {err}"
    return any(m in s for m in _OOM_MARKERS)


class _DeviceFailed(Exception):
    """Internal marker the device loop posts when its retry ladder is
    exhausted; ``cause`` is the original device/tunnel error."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def chunk_spans(n: int, chunk_len: int, overlap: int) -> list[int]:
    """Chunk start offsets covering ``n`` bytes with the given overlap."""
    if n <= chunk_len:
        return [0]
    step = chunk_len - overlap
    starts = list(range(0, n - overlap, step))
    return starts


@dataclass
class _FileState:
    path: str
    data: bytes
    pending: int  # chunks not yet matched
    # candidate rule index -> chunk windows (byte spans) where it hit
    rules: dict[int, list[tuple[int, int]]] = field(default_factory=dict)


class ScanStats:
    """Cumulative link-traffic counters (thread-safe): bench snapshots
    before/after a scan to compute link_bytes_per_corpus_byte and the
    dedup hit rate. ``bytes_uploaded`` counts padded row bytes actually
    dispatched (real link traffic incl. bucket padding); ``bytes_dedup_hit``
    counts corpus bytes whose rows were served from the hit cache or
    coalesced onto an identical in-flight row."""

    FIELDS = (
        "bytes_in",          # corpus bytes fed to the device path
        "bytes_uploaded",    # padded row bytes dispatched over the link
        "bytes_dedup_hit",   # corpus bytes resolved without an upload
        "bytes_packed",      # corpus bytes sharing a row with another file
        "chunks",            # rows the corpus decomposed into
        "chunks_uploaded",   # rows actually dispatched
        "chunks_dedup_hit",  # rows served from the hit cache / coalesced
        "rows_packed",       # dispatched rows carrying >1 file segment
        "files_packed",      # files that rode a shared row
        "batch_retries",     # failed batches re-dispatched whole
        "batch_splits",      # OOM-shaped failures answered by halving
        "degraded",          # scans that fell back to the exact host path
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._v = dict.fromkeys(self.FIELDS, 0)

    def add(self, **kw: int) -> None:
        with self._lock:
            for k, n in kw.items():
                self._v[k] += n

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._v)


class TpuSecretScanner:
    """Drop-in equivalent of :class:`SecretScanner` batched over TPU.

    ``scan_files`` consumes an iterable of (path, bytes) and yields one
    :class:`Secret` per input file, in input order, with findings identical
    to ``SecretScanner.scan_bytes``.
    """

    def __init__(
        self,
        config: ScannerConfig | None = None,
        chunk_len: int | None = None,
        batch_size: int | None = None,
        mesh=None,
        backend: str = "auto",
        confirm_workers: int = 0,  # 0 = CONFIRM_WORKERS default
        dedup: bool = True,
        pack_small: bool = True,
        hit_cache_entries: int = HIT_CACHE_ENTRIES,
        hit_cache=None,  # trivy_tpu.cache backend for cross-scan persistence
        dispatch: str = "auto",  # 'auto' | 'single' | 'round_robin'
        devices=None,  # explicit device list for round-robin dispatch
        host_fallback: bool = True,  # degrade to the exact host path on
        # unrecoverable device failure instead of failing the scan
        batch_retries: int = BATCH_RETRIES,
    ):
        import jax

        self.exact = SecretScanner(config)
        self.compiled: CompiledRules = compile_rules(self.exact.rules)
        if backend == "auto":
            platform = jax.devices()[0].platform
            backend = "pallas" if platform not in ("cpu", "METAL") else "xla"
        self.backend = backend
        if backend == "pallas":
            from trivy_tpu.ops.match_pallas import BLOCK_ROWS, build_match_fn_pallas

            self.chunk_len = chunk_len or PALLAS_CHUNK_LEN
            self.batch_size = batch_size or PALLAS_BATCH
            rows_mult = BLOCK_ROWS
            match_fn = build_match_fn_pallas(self.compiled, self.chunk_len)
        else:
            self.chunk_len = chunk_len or DEFAULT_CHUNK_LEN
            self.batch_size = batch_size or DEFAULT_BATCH
            rows_mult = 1
            match_fn = build_match_fn(self.compiled, self.chunk_len)
        self.overlap = max(64, self.compiled.span + 1)
        if self.overlap > self.chunk_len // 2:
            raise ValueError(
                f"chunk_len={self.chunk_len} too small for ruleset: the widest "
                f"device window needs overlap {self.overlap}; use chunk_len "
                f">= {2 * self.overlap}"
            )
        self._rules_by_id = {r.id: r for r in self.exact.rules}
        # windowed confirmation is sound only when flagged chunks bound the
        # match START: always true on the anchored lane; true on the keyword
        # lane only for bounded-width rules whose keyword provably sits
        # inside every match (the keyword occurrence then pins the start
        # within max_match_width). Everything else full-scans on flag.
        anchored = set(self.compiled.anchored_rule_ids)
        self._windowed_ids = anchored | {
            r.id
            for r in self.exact.rules
            if r.id not in anchored
            and r.keywords
            and r.keyword_in_match
            and r.max_match_width is not None
            and r.max_match_width <= 8192
        }
        self.confirm_workers = confirm_workers or CONFIRM_WORKERS

        # -- dedup hit cache ------------------------------------------------
        # ruleset fingerprint: the hit vector is a pure function of
        # (row bytes, compiled tables); keying the row hash with this
        # fingerprint makes any rule add/remove/regex/keyword change — and
        # any reordering, which renumbers rule indices — flip every key
        fp = hashlib.blake2b(digest_size=16)
        fp.update(f"v{HIT_CACHE_VERSION}:{self.chunk_len}:".encode())
        for r in self.exact.rules:
            fp.update(repr((r.id, r.regex, r.keywords, r.path)).encode())
            fp.update(b"\x00")
        self.ruleset_fingerprint = fp.digest()
        self._dedup = dedup
        self._pack_small = pack_small
        self._hit_lru: OrderedDict[bytes, tuple[int, ...]] = OrderedDict()
        self._hit_lru_max = hit_cache_entries
        self._hit_lock = threading.Lock()
        self._hit_persist = hit_cache
        self._host_fallback = host_fallback
        self._batch_retries = batch_retries
        self.stats = ScanStats()

        from trivy_tpu.parallel.mesh import (
            pad_batch,
            round_robin_match_fn,
            sharded_match_fn,
        )

        if dispatch not in ("auto", "single", "round_robin"):
            raise ValueError(
                f"dispatch={dispatch!r}: use 'auto', 'single', or 'round_robin'"
            )
        self._pipeline_depth = PIPELINE_DEPTH
        rr_devices = None
        if mesh is None and dispatch != "single":
            devs = list(devices) if devices is not None else jax.local_devices()
            # 'auto' opts in only on real multi-accelerator hosts; the CPU
            # backend's virtual devices share one memory bus, so multi-stream
            # dispatch there only adds copies (tests opt in explicitly)
            if len(devs) > 1 and (
                dispatch == "round_robin" or devs[0].platform not in ("cpu",)
            ):
                rr_devices = devs

        if mesh is not None:
            inner = sharded_match_fn(match_fn, mesh, rows_multiple=rows_mult)
            dp = inner.data_parallelism
            self._match = lambda b: inner(pad_batch(b, dp))
            row_multiple = dp
        elif rr_devices is not None:
            self._match = round_robin_match_fn(
                match_fn, rr_devices, rows_multiple=rows_mult
            )
            row_multiple = rows_mult
            # keep every transfer stream busy: at least one batch in flight
            # per device plus the usual dispatch-ahead margin
            self._pipeline_depth = PIPELINE_DEPTH + len(rr_devices) - 1
        elif rows_mult > 1:
            self._match = lambda b: match_fn(pad_batch(b, rows_mult))
            row_multiple = rows_mult
        else:
            self._match = match_fn
            row_multiple = 1
        # dispatch-shape bucket ladder: every shape compiles exactly once
        # (variable trailing-batch shapes would recompile per distinct size).
        # The ladder stops at B/4: each extra rung costs a full Mosaic
        # compile of every kernel (~minutes through a remote-compile
        # tunnel), while padding a short trailing batch up to B/4 rows
        # costs microseconds of device time
        buckets = [self.batch_size]
        while (
            buckets[-1] // 2 >= max(8, row_multiple, self.batch_size // 4)
        ):
            buckets.append(buckets[-1] // 2)
        self._buckets = sorted(buckets)

    # -- dedup hit cache ----------------------------------------------------

    def _persist_key(self, key: bytes) -> str:
        return f"secret-hitv:{self.ruleset_fingerprint.hex()}:{key.hex()}"

    def _hit_get(self, key: bytes) -> tuple[int, ...] | None:
        """Cached per-rule hit vector for a row digest, or None."""
        with self._hit_lock:
            v = self._hit_lru.get(key)
            if v is not None:
                self._hit_lru.move_to_end(key)
                return v
        if self._hit_persist is not None:
            blob = self._hit_persist.get_blob(self._persist_key(key))
            if blob is not None:
                v = tuple(blob["r"])
                self._lru_insert(key, v)
                return v
        return None

    def clear_hit_cache(self) -> None:
        """Drop the in-process hit LRU (persisted entries are untouched) —
        used by bench to measure the cold feed path."""
        with self._hit_lock:
            self._hit_lru.clear()

    def _lru_insert(self, key: bytes, hit_rules: tuple[int, ...]) -> None:
        """Insert under the entry bound — every LRU write path must evict,
        or persisted-cache re-scans of large corpora grow RSS unboundedly."""
        with self._hit_lock:
            self._hit_lru[key] = hit_rules
            self._hit_lru.move_to_end(key)
            while len(self._hit_lru) > self._hit_lru_max:
                self._hit_lru.popitem(last=False)

    def _hit_put(self, key: bytes, hit_rules: tuple[int, ...]) -> None:
        self._lru_insert(key, hit_rules)
        if self._hit_persist is not None:
            self._hit_persist.put_blob(
                self._persist_key(key), {"r": list(hit_rules)}
            )

    # -- core batching loop -------------------------------------------------

    def _device_loop(self, in_q, out_q, ctx) -> None:
        """Single device thread: dispatch batches asynchronously, defer the
        blocking result fetch until the pipeline is full.

        One thread does BOTH dispatch and fetch on purpose: jax dispatch is
        async, so batch N+1's host→device transfer proceeds while batch N's
        kernel runs — full overlap from one thread — and keeping dispatch
        and fetch off separate threads matters under the axon tunnel, whose
        transfer journal only reclaims per-transfer buffers when transfers
        and fetches don't interleave across threads (measured: the
        two-thread pipeline retains ~0.9 byte/byte scanned; this loop with
        identical depth is flat).

        Stall instrumentation (all on ``ctx``, the spawning scan's trace
        context — this thread outlives the contextvar scope):
        ``secret.feed_wait`` is time blocked on the host feed (feed-starved),
        ``secret.dispatch`` the enqueue/transfer handoff (upload-bound),
        ``secret.device_wait`` the blocking result fetch (device-bound).

        Failure domain (the per-batch rung of the ladder): a failed
        dispatch or fetch re-dispatches that batch up to ``batch_retries``
        times — under round-robin dispatch the retry lands on the next
        healthy device, and the breaker's failure/success feedback is
        recorded here. OOM-shaped errors split the batch in half instead
        of retrying it whole (halving terminates on its own, so splits
        don't consume the retry budget). Only when the ladder is exhausted
        — or every device is circuit-broken — does the failure escalate to
        ``scan_files``'s host fallback.
        """
        from trivy_tpu.parallel.mesh import DevicesUnavailable

        pending: deque = deque()  # (dev, meta, batch, device_idx, retries)
        match = self._match
        dispatch_fn = getattr(match, "dispatch", None)
        record = getattr(match, "record_result", None)
        stats = self.stats
        chunk_len = self.chunk_len
        prof = ctx.profile() if ctx.enabled else None

        def rebatch(batch: np.ndarray, meta: list) -> np.ndarray:
            """Fresh bucket-padded copy of a failed batch's live rows — the
            original may be a ring-buffer view whose slot the feeder is
            about to refill, so retries never alias it."""
            n = next(b for b in self._buckets if b >= len(meta))
            out = np.zeros((n, chunk_len), dtype=np.uint8)
            out[: len(meta)] = batch[: len(meta)]
            return out

        def recover(batch, meta, retries, err) -> list:
            """Ladder decision for one failed batch: work items to
            re-dispatch, or raise when the ladder is exhausted."""
            if isinstance(err, DevicesUnavailable):
                raise err  # no device left to retry on
            if _is_oom(err) and len(meta) > 1:
                stats.add(batch_splits=1)
                ctx.count("secret.batch_splits")
                logger.warning(
                    "device OOM on a %d-row batch (%s); splitting and "
                    "re-dispatching the halves", len(meta), err,
                )
                mid = (len(meta) + 1) // 2
                return [
                    (rebatch(batch[:mid], meta[:mid]), meta[:mid], retries),
                    (rebatch(batch[mid:], meta[mid:]), meta[mid:], retries),
                ]
            if retries < self._batch_retries:
                stats.add(batch_retries=1)
                ctx.count("secret.batch_retries")
                logger.warning(
                    "device error on a %d-row batch (retry %d/%d): %s",
                    len(meta), retries + 1, self._batch_retries, err,
                )
                return [(rebatch(batch, meta), meta, retries + 1)]
            raise err

        def dispatch_batch(batch, meta, retries) -> None:
            work = [(batch, meta, retries)]
            while work:
                b, m, r = work.pop()
                try:
                    with ctx.span("secret.dispatch"):
                        if dispatch_fn is not None:
                            dev, didx = dispatch_fn(b)
                        else:
                            faults.check("device.dispatch", key="d0")
                            dev, didx = match(b), None
                except Exception as e:
                    # dispatch-time failure (breaker already notified by
                    # the round-robin wrapper); walk the ladder
                    work.extend(recover(b, m, r, e))
                    continue
                pending.append((dev, m, b, didx, r))

        def fetch_oldest():
            dev, meta, batch, didx, retries = pending.popleft()
            try:
                faults.check(
                    "device.fetch", key=f"d{didx if didx is not None else 0}"
                )
                t0 = time.perf_counter()
                with ctx.span("secret.device_wait"):
                    arr = np.asarray(dev)
                if prof is not None:
                    # per-bucket dispatch cost: the bucket is the padded
                    # batch shape (the compile-once ladder rung), rows are
                    # the live rows it carried
                    prof.bucket_dispatch(
                        batch.shape[0], len(meta), time.perf_counter() - t0
                    )
            except Exception as e:
                if record is not None and didx is not None:
                    record(didx, False)
                for item in recover(batch, meta, retries, e):
                    dispatch_batch(*item)
                return
            if record is not None and didx is not None:
                record(didx, True)
            out_q.put((arr, meta))

        with obs.activate(ctx):
            try:
                while True:
                    with ctx.span("secret.feed_wait"):
                        item = in_q.get()
                    if item is None:
                        break
                    batch, meta = item
                    dispatch_batch(batch, meta, 0)
                    if len(pending) >= self._pipeline_depth:
                        fetch_oldest()
                while pending:
                    fetch_oldest()
            except BaseException as e:  # retry ladder exhausted: surface it
                # the feeder sees the exception on its next drain and raises;
                # empty the queue first so a feeder blocked on a full in_q
                # wakes up (its batches are lost — either the scan is failing
                # or the host fallback rescans every unresolved file anyway)
                while True:
                    try:
                        in_q.get_nowait()
                    except queue.Empty:
                        break
                out_q.put(_DeviceFailed(e) if isinstance(e, Exception) else e)
                return
            out_q.put(None)

    def scan_files(self, files: Iterable[tuple[str, bytes]]) -> Iterator[Secret]:
        """Scan many files; yields per-file results in input order."""
        # order-preserving result store; files resolve once all chunks
        # matched; values are Secrets or in-flight confirmation Futures
        results: dict[int, Secret | Future] = {}
        states: dict[int, _FileState] = {}
        next_emit = 0
        total = 0
        stats = self.stats
        # capture the caller's trace context once: the device thread and
        # confirm pool record into it via obs.activate (worker threads do
        # not inherit the contextvar)
        ctx = obs.current()
        # per-rule cost profile (gate hits here; confirm timing in the
        # confirm pool); same enabled gate as spans
        prof = ctx.profile() if ctx.enabled else None
        rule_ids = self.compiled.rule_ids
        chunk_len = self.chunk_len
        dedup = self._dedup
        fp_key = self.ruleset_fingerprint
        # row digest -> waiting segment lists: identical rows already
        # dispatched but not yet resolved are coalesced here instead of
        # being uploaded again (zero pages recur within a single batch)
        inflight: dict[bytes, list[list[tuple[int, int, int]]]] = {}

        # ring of host batch buffers sized for every stage a batch can be
        # in at once: queued to the device thread (pipeline depth), being
        # dispatched (1), dispatched-but-unfetched (pipeline depth, matters
        # on the CPU backend where jax may alias the numpy buffer
        # zero-copy), plus the one being packed — refilling a ring slot
        # can then never touch a batch still in any of those stages
        bufs = [
            np.zeros((self.batch_size, chunk_len), dtype=np.uint8)
            for _ in range(2 * self._pipeline_depth + 2)
        ]
        buf_i = 0
        buf = bufs[0]
        # per-row feed metadata: (digest | None, [(fidx, win_start, win_end)])
        meta: list[tuple[bytes | None, list[tuple[int, int, int]]]] = []
        pool = ThreadPoolExecutor(max_workers=self.confirm_workers)
        # the single device thread (see _device_loop); in_q's bound is the
        # feeder backpressure, out_q carries fetched hit matrices back
        in_q: queue.Queue = queue.Queue(maxsize=self._pipeline_depth)
        out_q: queue.Queue = queue.Queue()
        device_thread = threading.Thread(
            target=self._device_loop, args=(in_q, out_q, ctx), daemon=True
        )
        device_thread.start()
        # backpressure: bounds queued+running confirms so a slow confirm
        # pool cannot accumulate unbounded _FileState.data on a large
        # streaming scan (file bytes are released once its confirm runs)
        confirm_slots = threading.Semaphore(self.confirm_workers * 4)

        def confirm_task(st: _FileState) -> Secret:
            try:
                with obs.activate(ctx), ctx.span("secret.confirm"):
                    return self._confirm(st, prof)
            finally:
                confirm_slots.release()

        def apply_hits(
            segs: list[tuple[int, int, int]], hit_rules: tuple[int, ...]
        ) -> None:
            """Credit one resolved row to its file segments: record candidate
            windows (every row hit applies to every segment — cross-segment
            false candidates are discarded by the exact confirm), then
            retire each segment's pending count."""
            if prof is not None and hit_rules:
                # one logical device hit per (row, rule) — dedup-cache and
                # coalesced rows count too: they cost a confirm all the same
                for r in hit_rules:
                    prof.gate_hit(rule_ids[r])
            for fidx, ws, we in segs:
                st = states[fidx]
                for r in hit_rules:
                    st.rules.setdefault(r, []).append((ws, we))
            for fidx, _, _ in segs:
                st = states[fidx]
                st.pending -= 1
                if st.pending == 0:
                    confirm_slots.acquire()
                    results[fidx] = pool.submit(confirm_task, st)
                    del states[fidx]

        def resolve(batch_hits: np.ndarray, batch_meta: list) -> None:
            # one vectorized nonzero per batch, not one per row
            rows, ridx = np.nonzero(batch_hits[: len(batch_meta)])
            by_row: dict[int, list[int]] = {}
            for row, r in zip(rows.tolist(), ridx.tolist()):
                by_row.setdefault(row, []).append(r)
            for row, (key, segs) in enumerate(batch_meta):
                hit_rules = tuple(by_row.get(row, ()))
                apply_hits(segs, hit_rules)
                if key is not None:
                    self._hit_put(key, hit_rules)
                    for waiting in inflight.pop(key, ()):
                        apply_hits(waiting, hit_rules)

        def drain_results(block: bool = False) -> bool:
            """Resolve fetched batches; returns False once the device
            thread signalled completion; re-raises a device failure."""
            while True:
                try:
                    item = out_q.get(block=block)
                except queue.Empty:
                    return True
                if item is None:
                    return False
                if isinstance(item, BaseException):
                    raise item
                resolve(*item)
                block = False

        def flush():
            nonlocal meta, buf, buf_i
            if not meta:
                return
            n = next(b for b in self._buckets if b >= len(meta))
            stats.add(bytes_uploaded=n * chunk_len)
            ctx.count("secret.bytes_uploaded", n * chunk_len)
            ctx.sample("secret.queue_depth", in_q.qsize())
            in_q.put((buf[:n], meta))
            meta = []
            # rotate to the next ring buffer; full rows are overwritten on
            # fill and partial rows zero their own tails (stale rows past
            # len(meta) are sliced off in resolve), so no re-zeroing of the
            # whole batch is needed
            buf_i = (buf_i + 1) % len(bufs)
            buf = bufs[buf_i]
            drain_results()
            # bound pack-row staleness to one batch: a lone small file must
            # not sit in pack_pending while big files stream past it — its
            # unresolved state would stall in-order emission and let results
            # accumulate unbounded on a streaming scan. The partial pack row
            # rides the next batch instead (re-entry is shallow: the fresh
            # meta holds one row, far below batch_size, so no second flush)
            if pack_pending:
                emit_pack()

        def feed_row(
            key: bytes | None,
            segs: list[tuple[int, int, int]],
            parts: list[tuple[int, np.ndarray]],
            nbytes: int,
            packed: bool,
        ) -> None:
            """Resolve a row from the hit cache, coalesce onto an identical
            in-flight row, or pack it into the current batch buffer."""
            stats.add(chunks=1)
            if key is not None:
                cached = self._hit_get(key)
                if cached is not None:
                    stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                    ctx.count("secret.bytes_dedup_hit", nbytes)
                    apply_hits(segs, cached)
                    return
                waiting = inflight.get(key)
                if waiting is not None:
                    waiting.append(segs)
                    stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                    ctx.count("secret.bytes_dedup_hit", nbytes)
                    return
                inflight[key] = []
            row = buf[len(meta)]
            if packed:
                row[:] = 0  # zero guard gaps + tail (ring rows hold stale data)
                for off, piece in parts:
                    row[off : off + len(piece)] = piece
                if len(segs) > 1:
                    stats.add(
                        rows_packed=1, files_packed=len(segs), bytes_packed=nbytes
                    )
                    ctx.count("secret.bytes_packed", nbytes)
            else:
                piece = parts[0][1]
                row[: len(piece)] = piece
                if len(piece) < chunk_len:
                    row[len(piece):] = 0  # clear stale tail
            stats.add(chunks_uploaded=1)
            meta.append((key, segs))
            if len(meta) == self.batch_size:
                flush()

        # small-file packing: files below a row's size accumulate here and
        # share one row, separated by >=span zero gaps (see module docstring
        # for why packing cannot suppress a real match)
        gap = self.overlap
        pack_max = chunk_len - gap
        pack_pending: list[tuple[int, bytes]] = []
        pack_len = 0

        def emit_pack() -> None:
            nonlocal pack_len
            if not pack_pending:
                return
            items = list(pack_pending)
            pack_pending.clear()
            pack_len = 0
            key = None
            if dedup:
                if len(items) == 1:
                    # single-segment row == plain chunk-row layout: share the
                    # plain digest domain so it dedups across both paths
                    key = hashlib.blake2b(
                        items[0][1], digest_size=16, key=fp_key
                    ).digest()
                else:
                    h = hashlib.blake2b(
                        digest_size=16, key=fp_key, person=b"packed-row"
                    )
                    for _, d in items:
                        h.update(len(d).to_bytes(4, "little"))
                        h.update(d)
                    key = h.digest()
            segs = []
            parts = []
            off = 0
            for fidx, d in items:
                segs.append((fidx, 0, len(d)))
                parts.append((off, np.frombuffer(d, dtype=np.uint8)))
                off += len(d) + gap
            feed_row(key, segs, parts, sum(len(d) for _, d in items), True)

        def add_small(fidx: int, data: bytes) -> None:
            nonlocal pack_len
            if pack_len and pack_len + gap + len(data) > chunk_len:
                emit_pack()
            pack_pending.append((fidx, data))
            pack_len += (gap if pack_len else 0) + len(data)

        def drain() -> None:
            in_q.put(None)
            while drain_results(block=True):
                pass
            device_thread.join()

        def host_task(path: str, data: bytes) -> Secret:
            # degraded-mode rung: the exact host engine IS the parity
            # oracle, so fallback findings are byte-identical by definition
            try:
                with obs.activate(ctx), ctx.span("secret.host_fallback"):
                    return self.exact.scan_bytes(path, data)
            finally:
                confirm_slots.release()

        files_it = enumerate(files)
        try:
            try:
                for fidx, (path, data) in files_it:
                    total += 1
                    # path-level global allowlist: skip the whole file (ref:
                    # scanner.go:388-392) — no device work either
                    if self.exact.allow_path(path):
                        results[fidx] = Secret(file_path=path)
                    elif not data:
                        # empty file: nothing for the device to match —
                        # resolve host-side immediately (host-lane rules
                        # still run there)
                        st = _FileState(path=path, data=data, pending=0)
                        confirm_slots.acquire()
                        results[fidx] = pool.submit(confirm_task, st)
                    else:
                        stats.add(bytes_in=len(data))
                        if self._pack_small and len(data) <= pack_max:
                            states[fidx] = _FileState(
                                path=path, data=data, pending=1
                            )
                            add_small(fidx, data)
                        else:
                            starts = chunk_spans(
                                len(data), chunk_len, self.overlap
                            )
                            states[fidx] = _FileState(
                                path=path, data=data, pending=len(starts)
                            )
                            arr = np.frombuffer(data, dtype=np.uint8)
                            for s in starts:
                                piece = arr[s : s + chunk_len]
                                key = (
                                    hashlib.blake2b(
                                        piece, digest_size=16, key=fp_key
                                    ).digest()
                                    if dedup
                                    else None
                                )
                                feed_row(
                                    key,
                                    [(fidx, s, s + chunk_len)],
                                    [(0, piece)],
                                    len(piece),
                                    False,
                                )
                    # emit in order as soon as the contiguous prefix is done;
                    # block on a confirmation only when it is next in line
                    while next_emit in results:
                        r = results.pop(next_emit)
                        yield r.result() if isinstance(r, Future) else r
                        next_emit += 1
                emit_pack()  # flush the partial pack row
                flush()  # dispatch the final partial batch
                drain()  # resolve whatever is still in flight
            except _DeviceFailed as e:
                # the device loop's retry ladder is exhausted (or every
                # device is circuit-broken): last rung — finish the scan on
                # the exact host path instead of failing it
                if not self._host_fallback:
                    raise e.cause from None
                self._note_degraded(ctx, e.cause)
                inflight.clear()
                pack_pending.clear()
                # every file with unresolved device work rescans host-side
                # (partial device results for it are discarded); already-
                # submitted confirms keep completing on the same pool
                for fidx in sorted(states):
                    st = states.pop(fidx)
                    confirm_slots.acquire()
                    results[fidx] = pool.submit(host_task, st.path, st.data)
                # files not yet pulled from the input stream go straight to
                # the host path, same backpressure bound
                for fidx, (path, data) in files_it:
                    total += 1
                    confirm_slots.acquire()
                    results[fidx] = pool.submit(host_task, path, data)
                    while next_emit in results:
                        r = results.pop(next_emit)
                        yield r.result() if isinstance(r, Future) else r
                        next_emit += 1
            while next_emit < total:
                r = results.pop(next_emit)
                yield r.result() if isinstance(r, Future) else r
                next_emit += 1
        finally:
            pool.shutdown(wait=False)
            if device_thread.is_alive():
                # generator closed early: make room if the queue is full,
                # then deliver the shutdown sentinel (dropping it would
                # leave the device thread blocked on in_q.get() forever)
                while True:
                    try:
                        in_q.put_nowait(None)
                        break
                    except queue.Full:
                        try:
                            in_q.get_nowait()
                        except queue.Empty:
                            pass

    def scan_bytes(self, path: str, data: bytes) -> Secret:
        """Single-file convenience (still device-prefiltered)."""
        return next(iter(self.scan_files([(path, data)])))

    def _note_degraded(self, ctx, err: BaseException) -> None:
        logger.warning(
            "device pipeline failed (%s); completing the scan on the exact "
            "host confirm path — slower, findings identical", err,
        )
        self.stats.add(degraded=1)
        ctx.count("secret.degraded")
        obs.note_scan_degraded()

    # -- host confirmation --------------------------------------------------

    def _confirm(self, st: _FileState, prof=None) -> Secret:
        # span recording happens in scan_files' confirm_task (which holds
        # the scan's trace context); direct callers time themselves
        return self._confirm_inner(st, prof)

    def _confirm_inner(self, st: _FileState, prof=None) -> Secret:
        windows_by_id = {
            self.compiled.rule_ids[i]: w for i, w in st.rules.items()
        }
        host_ids = set(self.compiled.host_rule_ids)
        if not windows_by_id and not host_ids:
            return Secret(file_path=st.path)
        content = st.data.decode("latin-1")
        lower = content.lower()
        global_blocks = self.exact.global_block_spans(content)
        hits = []
        for rule in self.exact.rules_for_path(st.path):
            t0 = time.perf_counter() if prof is not None else 0.0
            if rule.id in windows_by_id:
                if rule.id in self._windowed_ids:
                    # regex runs only around the device-flagged chunk windows
                    locs = self.exact.find_rule_locations_in_windows(
                        rule, content, lower, windows_by_id[rule.id], global_blocks
                    )
                else:
                    # keyword lane without a start bound: the flagged chunk
                    # locates the keyword, not the match — full-content scan
                    # (detector-accelerated for unbounded-width rules)
                    locs = self.exact.find_rule_locations_fullscan(
                        rule, content, lower, global_blocks
                    )
            elif rule.id in host_ids:
                locs = self.exact.find_rule_locations(
                    rule, content, lower, global_blocks
                )
            else:
                continue
            if prof is not None:
                prof.confirm(rule.id, time.perf_counter() - t0, len(locs))
            hits.extend((rule, loc) for loc in locs)
        return self.exact.build_findings(st.path, content, hits)
