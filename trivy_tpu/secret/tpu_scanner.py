"""TPU-backed secret scanner: device prefilter + exact host confirmation.

Pipeline (replaces the reference's walk→goroutine→regexp chain, ref:
pkg/fanal/secret/scanner.go:377 and SURVEY.md §3.2):

  files → overlapping fixed-size chunks → [B, C] batches → device match
  kernel → per-(file, rule) candidates → exact `SecretScanner` restricted to
  candidate rules → findings (byte-identical to the CPU backend).

Chunk overlap equals the compiled ruleset's maximum device window, so every
device-checkable window lies fully inside at least one chunk. The host
confirm is window-restricted only where the flagged chunk provably bounds
the match start (anchored lane; keyword lane with the keyword inside every
match — see ``_windowed_ids``); other keyword-lane rules rescan the whole
file on flag, with unbounded-width regexes accelerated by their bounded
start-detector prefix (``Rule.start_detector``).

Batches are dispatched asynchronously (JAX dispatch is async by default)
through a depth-PIPELINE_DEPTH pipeline: the host packs batches N+1..N+k
while the device matches batch N — the TPU analog of the reference's
`parallel.Pipeline` feeder/worker split (ref: pkg/parallel/pipeline.go:14-115).
Dispatch shapes are drawn from a fixed bucket ladder (B, B/2, B/4, ...) so
every shape compiles exactly once; exact host confirmation runs in a small
thread pool that overlaps with the blocking device-result fetches (which
release the GIL).
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from collections.abc import Iterable, Iterator
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu import log, trace
from trivy_tpu.ops.match import build_match_fn
from trivy_tpu.secret.device_compile import CompiledRules, compile_rules
from trivy_tpu.secret.engine import ScannerConfig, SecretScanner
from trivy_tpu.types import Secret

logger = log.logger("secret:tpu")

DEFAULT_CHUNK_LEN = 65536
DEFAULT_BATCH = 64
# pallas path: small self-contained rows.
# 1024 x 8 KiB = 8 MiB batches: small enough that pack -> transfer ->
# kernel -> confirm overlap through the pipeline (a 32 MiB batch serializes
# the whole corpus behind one blocking device wait), big enough to amortize
# kernel launch; 8 KiB rows keep the kernel's VMEM working set off the
# spill cliff that 16 KiB rows hit
PALLAS_CHUNK_LEN = 8192
PALLAS_BATCH = 1024
# batches in flight before the oldest result is fetched
PIPELINE_DEPTH = 3
# workers for exact host confirmation (overlaps device-result waits)
CONFIRM_WORKERS = 4


def chunk_spans(n: int, chunk_len: int, overlap: int) -> list[int]:
    """Chunk start offsets covering ``n`` bytes with the given overlap."""
    if n <= chunk_len:
        return [0]
    step = chunk_len - overlap
    starts = list(range(0, n - overlap, step))
    return starts


@dataclass
class _FileState:
    path: str
    data: bytes
    pending: int  # chunks not yet matched
    # candidate rule index -> chunk windows (byte spans) where it hit
    rules: dict[int, list[tuple[int, int]]] = field(default_factory=dict)


class TpuSecretScanner:
    """Drop-in equivalent of :class:`SecretScanner` batched over TPU.

    ``scan_files`` consumes an iterable of (path, bytes) and yields one
    :class:`Secret` per input file, in input order, with findings identical
    to ``SecretScanner.scan_bytes``.
    """

    def __init__(
        self,
        config: ScannerConfig | None = None,
        chunk_len: int | None = None,
        batch_size: int | None = None,
        mesh=None,
        backend: str = "auto",
        confirm_workers: int = 0,  # 0 = CONFIRM_WORKERS default
    ):
        import jax

        self.exact = SecretScanner(config)
        self.compiled: CompiledRules = compile_rules(self.exact.rules)
        if backend == "auto":
            platform = jax.devices()[0].platform
            backend = "pallas" if platform not in ("cpu", "METAL") else "xla"
        self.backend = backend
        if backend == "pallas":
            from trivy_tpu.ops.match_pallas import BLOCK_ROWS, build_match_fn_pallas

            self.chunk_len = chunk_len or PALLAS_CHUNK_LEN
            self.batch_size = batch_size or PALLAS_BATCH
            rows_mult = BLOCK_ROWS
            match_fn = build_match_fn_pallas(self.compiled, self.chunk_len)
        else:
            self.chunk_len = chunk_len or DEFAULT_CHUNK_LEN
            self.batch_size = batch_size or DEFAULT_BATCH
            rows_mult = 1
            match_fn = build_match_fn(self.compiled, self.chunk_len)
        self.overlap = max(64, self.compiled.span + 1)
        if self.overlap > self.chunk_len // 2:
            raise ValueError(
                f"chunk_len={self.chunk_len} too small for ruleset: the widest "
                f"device window needs overlap {self.overlap}; use chunk_len "
                f">= {2 * self.overlap}"
            )
        self._rules_by_id = {r.id: r for r in self.exact.rules}
        # windowed confirmation is sound only when flagged chunks bound the
        # match START: always true on the anchored lane; true on the keyword
        # lane only for bounded-width rules whose keyword provably sits
        # inside every match (the keyword occurrence then pins the start
        # within max_match_width). Everything else full-scans on flag.
        anchored = set(self.compiled.anchored_rule_ids)
        self._windowed_ids = anchored | {
            r.id
            for r in self.exact.rules
            if r.id not in anchored
            and r.keywords
            and r.keyword_in_match
            and r.max_match_width is not None
            and r.max_match_width <= 8192
        }
        self.confirm_workers = confirm_workers or CONFIRM_WORKERS

        from trivy_tpu.parallel.mesh import pad_batch, sharded_match_fn

        if mesh is not None:
            inner = sharded_match_fn(match_fn, mesh, rows_multiple=rows_mult)
            dp = inner.data_parallelism
            self._match = lambda b: inner(pad_batch(b, dp))
            row_multiple = dp
        elif rows_mult > 1:
            self._match = lambda b: match_fn(pad_batch(b, rows_mult))
            row_multiple = rows_mult
        else:
            self._match = match_fn
            row_multiple = 1
        # dispatch-shape bucket ladder: every shape compiles exactly once
        # (variable trailing-batch shapes would recompile per distinct size).
        # The ladder stops at B/4: each extra rung costs a full Mosaic
        # compile of every kernel (~minutes through a remote-compile
        # tunnel), while padding a short trailing batch up to B/4 rows
        # costs microseconds of device time
        buckets = [self.batch_size]
        while (
            buckets[-1] // 2 >= max(8, row_multiple, self.batch_size // 4)
        ):
            buckets.append(buckets[-1] // 2)
        self._buckets = sorted(buckets)

    # -- core batching loop -------------------------------------------------

    def _device_loop(self, in_q, out_q) -> None:
        """Single device thread: dispatch batches asynchronously, defer the
        blocking result fetch until the pipeline is full.

        One thread does BOTH dispatch and fetch on purpose: jax dispatch is
        async, so batch N+1's host→device transfer proceeds while batch N's
        kernel runs — full overlap from one thread — and keeping dispatch
        and fetch off separate threads matters under the axon tunnel, whose
        transfer journal only reclaims per-transfer buffers when transfers
        and fetches don't interleave across threads (measured: the
        two-thread pipeline retains ~0.9 byte/byte scanned; this loop with
        identical depth is flat).
        """
        pending: deque = deque()

        def fetch_oldest():
            dev, meta = pending.popleft()
            with trace.span("secret.device_wait"):
                out_q.put((np.asarray(dev), meta))

        try:
            while True:
                item = in_q.get()
                if item is None:
                    break
                batch, meta = item
                with trace.span("secret.dispatch"):
                    pending.append((self._match(batch), meta))
                if len(pending) >= PIPELINE_DEPTH:
                    fetch_oldest()
            while pending:
                fetch_oldest()
        except BaseException as e:  # device/tunnel failure: surface it
            # the feeder sees the exception on its next drain and raises;
            # empty the queue first so a feeder blocked on a full in_q
            # wakes up (its batches are lost — the scan is failing anyway)
            while True:
                try:
                    in_q.get_nowait()
                except queue.Empty:
                    break
            out_q.put(e)
            return
        out_q.put(None)

    def scan_files(self, files: Iterable[tuple[str, bytes]]) -> Iterator[Secret]:
        """Scan many files; yields per-file results in input order."""
        # order-preserving result store; files resolve once all chunks
        # matched; values are Secrets or in-flight confirmation Futures
        results: dict[int, Secret | Future] = {}
        states: dict[int, _FileState] = {}
        next_emit = 0
        total = 0

        # ring of host batch buffers sized for every stage a batch can be
        # in at once: queued to the device thread (PIPELINE_DEPTH), being
        # dispatched (1), dispatched-but-unfetched (PIPELINE_DEPTH, matters
        # on the CPU backend where jax may alias the numpy buffer
        # zero-copy), plus the one being packed — refilling a ring slot
        # can then never touch a batch still in any of those stages
        bufs = [
            np.zeros((self.batch_size, self.chunk_len), dtype=np.uint8)
            for _ in range(2 * PIPELINE_DEPTH + 2)
        ]
        buf_i = 0
        buf = bufs[0]
        meta: list[int] = []  # file index per buffered chunk
        pool = ThreadPoolExecutor(max_workers=self.confirm_workers)
        # the single device thread (see _device_loop); in_q's bound is the
        # feeder backpressure, out_q carries fetched hit matrices back
        in_q: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
        out_q: queue.Queue = queue.Queue()
        device_thread = threading.Thread(
            target=self._device_loop, args=(in_q, out_q), daemon=True
        )
        device_thread.start()
        # backpressure: bounds queued+running confirms so a slow confirm
        # pool cannot accumulate unbounded _FileState.data on a large
        # streaming scan (file bytes are released once its confirm runs)
        confirm_slots = threading.Semaphore(self.confirm_workers * 4)

        def confirm_task(st: _FileState) -> Secret:
            try:
                return self._confirm(st)
            finally:
                confirm_slots.release()

        def resolve(batch_hits: np.ndarray, batch_meta: list) -> None:
            # one vectorized nonzero per batch, not one per row
            rows, ridx = np.nonzero(batch_hits[: len(batch_meta)])
            for row, r in zip(rows.tolist(), ridx.tolist()):
                fidx, start = batch_meta[row]
                states[fidx].rules.setdefault(r, []).append(
                    (start, start + self.chunk_len)
                )
            for fidx, _ in batch_meta:
                st = states[fidx]
                st.pending -= 1
                if st.pending == 0:
                    confirm_slots.acquire()
                    results[fidx] = pool.submit(confirm_task, st)
                    del states[fidx]

        def drain_results(block: bool = False) -> bool:
            """Resolve fetched batches; returns False once the device
            thread signalled completion; re-raises a device failure."""
            while True:
                try:
                    item = out_q.get(block=block)
                except queue.Empty:
                    return True
                if item is None:
                    return False
                if isinstance(item, BaseException):
                    raise item
                resolve(*item)
                block = False

        def flush():
            nonlocal meta, buf, buf_i
            if not meta:
                return
            n = next(b for b in self._buckets if b >= len(meta))
            in_q.put((buf[:n], meta))
            meta = []
            # rotate to the next ring buffer; full rows are overwritten on
            # fill and partial rows zero their own tails (stale rows past
            # len(meta) are sliced off in resolve), so no re-zeroing of the
            # whole batch is needed
            buf_i = (buf_i + 1) % len(bufs)
            buf = bufs[buf_i]
            drain_results()

        def drain() -> None:
            in_q.put(None)
            while drain_results(block=True):
                pass
            device_thread.join()

        try:
            for fidx, (path, data) in enumerate(files):
                total += 1
                # path-level global allowlist: skip the whole file (ref:
                # scanner.go:388-392) — no device work either
                if self.exact.allow_path(path):
                    results[fidx] = Secret(file_path=path)
                else:
                    starts = chunk_spans(len(data), self.chunk_len, self.overlap)
                    states[fidx] = _FileState(path=path, data=data, pending=len(starts))
                    arr = np.frombuffer(data, dtype=np.uint8)
                    for s in starts:
                        piece = arr[s : s + self.chunk_len]
                        row = len(meta)
                        buf[row, : len(piece)] = piece
                        if len(piece) < self.chunk_len:
                            buf[row, len(piece):] = 0  # clear stale tail
                        meta.append((fidx, s))
                        if len(meta) == self.batch_size:
                            flush()
                # emit in order as soon as the contiguous prefix is done;
                # block on a confirmation only when it is next in line
                while next_emit in results:
                    r = results.pop(next_emit)
                    yield r.result() if isinstance(r, Future) else r
                    next_emit += 1
            flush()  # dispatch the final partial batch
            drain()  # resolve whatever is still in flight
            while next_emit < total:
                r = results.pop(next_emit)
                yield r.result() if isinstance(r, Future) else r
                next_emit += 1
        finally:
            pool.shutdown(wait=False)
            if device_thread.is_alive():
                # generator closed early: make room if the queue is full,
                # then deliver the shutdown sentinel (dropping it would
                # leave the device thread blocked on in_q.get() forever)
                while True:
                    try:
                        in_q.put_nowait(None)
                        break
                    except queue.Full:
                        try:
                            in_q.get_nowait()
                        except queue.Empty:
                            pass

    def scan_bytes(self, path: str, data: bytes) -> Secret:
        """Single-file convenience (still device-prefiltered)."""
        return next(iter(self.scan_files([(path, data)])))

    # -- host confirmation --------------------------------------------------

    def _confirm(self, st: _FileState) -> Secret:
        with trace.span("secret.confirm"):
            return self._confirm_inner(st)

    def _confirm_inner(self, st: _FileState) -> Secret:
        windows_by_id = {
            self.compiled.rule_ids[i]: w for i, w in st.rules.items()
        }
        host_ids = set(self.compiled.host_rule_ids)
        if not windows_by_id and not host_ids:
            return Secret(file_path=st.path)
        content = st.data.decode("latin-1")
        lower = content.lower()
        global_blocks = self.exact.global_block_spans(content)
        hits = []
        for rule in self.exact.rules_for_path(st.path):
            if rule.id in windows_by_id:
                if rule.id in self._windowed_ids:
                    # regex runs only around the device-flagged chunk windows
                    locs = self.exact.find_rule_locations_in_windows(
                        rule, content, lower, windows_by_id[rule.id], global_blocks
                    )
                else:
                    # keyword lane without a start bound: the flagged chunk
                    # locates the keyword, not the match — full-content scan
                    # (detector-accelerated for unbounded-width rules)
                    locs = self.exact.find_rule_locations_fullscan(
                        rule, content, lower, global_blocks
                    )
            elif rule.id in host_ids:
                locs = self.exact.find_rule_locations(
                    rule, content, lower, global_blocks
                )
            else:
                continue
            hits.extend((rule, loc) for loc in locs)
        return self.exact.build_findings(st.path, content, hits)
