"""TPU-backed secret scanner: device prefilter + exact host confirmation.

Pipeline (replaces the reference's walk→goroutine→regexp chain, ref:
pkg/fanal/secret/scanner.go:377 and SURVEY.md §3.2):

  files → overlapping fixed-size chunks → [B, C] batches → device match
  kernel → per-(file, rule) candidates → exact `SecretScanner` restricted to
  candidate rules → findings (byte-identical to the CPU backend).

Chunk overlap equals the compiled ruleset's maximum device window, so every
device-checkable window lies fully inside at least one chunk. The host
confirm is window-restricted only where the flagged chunk provably bounds
the match start (anchored lane; keyword lane with the keyword inside every
match — see ``_windowed_ids``); other keyword-lane rules rescan the whole
file on flag, with unbounded-width regexes accelerated by their bounded
start-detector prefix (``Rule.start_detector``).

The feed path is a fully asynchronous pipeline — the TPU analog of the
reference's walker-goroutine fan-out into a bounded channel
(`parallel.Pipeline`, ref: pkg/parallel/pipeline.go:14-115,
scan_flags.go:79-84):

  input thread (feeder): chunk / hash / dedup / pack into a fixed
  **chunk arena** of preallocated reusable row slabs
  (:class:`trivy_tpu.secret.feed.ChunkArena`) — large files gather all
  their full rows into a slab with ONE vectorized strided copy, counters
  accumulate per file, not per row
    → bounded dispatch queue
  **transfer streams** (N worker threads, one per round-robin device, ≥2
  on a single device): each keeps a bounded in-flight window of
  double-buffered dispatches (`jax.device_put` + kernel enqueue are
  async), so batch N+1's host→device transfer overlaps batch N's kernel
  AND the per-stream transfers overlap each other — on a serialized
  tunnel link this multiplies effective feed bandwidth by the stream
  count; slabs release back to the arena only after the blocking fetch,
  then results resolve inline
    → confirm pool (bounded by a semaphore)
    → **reorder buffer**: the generator emits per-file results in input
  order from a completion map, so a slow head-of-line confirmation never
  stalls the feeder — readers keep filling the arena while emission
  waits.

Dispatch shapes are drawn from a fixed bucket ladder (B, B/2, B/4, ...) so
every shape compiles exactly once. Arena slots bound host memory (slabs in
the dispatch queue + per-stream windows + assembly margin); the confirm
semaphore bounds retained file bytes; together they are the streaming-RSS
guarantee the bench gate enforces.

The feed path sends link bytes ≪ corpus bytes (the host→device link, not
the kernel, is the e2e ceiling):

- **chunk-dedup hit cache**: every row is content-hashed (keyed blake2b,
  key = ruleset fingerprint so a rule add/remove/change flips every key)
  and duplicate rows — vendored deps, repeated OCI layer content, zero
  pages — reuse the cached per-rule hit vector with no upload and no
  kernel. Sound because the hit vector is a pure function of (row bytes,
  compiled tables); path-dependent filtering happens later, host-side.
  Bounded in-process LRU, optionally persisted through the trivy_tpu.cache
  layer (fs/redis) for cross-scan reuse — the same insight as the
  reference's layer cache: never re-scan content already seen.
- **small-file row packing**: files smaller than a row share one row,
  separated by ≥-span zero guard gaps. A real match's device program reads
  only match bytes (+1 boundary byte), so packing can never suppress a hit;
  cross-file windows only add false candidates that the exact host confirm
  discards.
- **round-robin multi-stream dispatch** (parallel.mesh.round_robin_match_fn)
  sends whole batches to each local device in turn so transfers overlap
  kernels across devices, multiplying effective link bandwidth.

Failure domains (README "Robustness"): a failed batch re-dispatches up to
``batch_retries`` times (OOM-shaped errors split the batch in half
instead), round-robin dispatch carries a per-device circuit breaker that
excludes a dying device and re-probes it on a backoff, and when nothing
device-side survives the scan completes on the exact host confirm path —
the parity oracle — with findings byte-identical and the scan flagged
degraded.
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
from collections import OrderedDict, deque
from collections.abc import Iterable, Iterator
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu import faults, log, obs
from trivy_tpu.ops.match import build_match_fn
from trivy_tpu.secret.device_compile import CompiledRules, compile_rules
from trivy_tpu.secret.feed import ChunkArena, row_windows
from trivy_tpu.secret.engine import ScannerConfig, SecretScanner
from trivy_tpu.types import Secret

logger = log.logger("secret:tpu")

DEFAULT_CHUNK_LEN = 65536
DEFAULT_BATCH = 64
# pallas path: small self-contained rows.
# 1024 x 8 KiB = 8 MiB batches: small enough that pack -> transfer ->
# kernel -> confirm overlap through the pipeline (a 32 MiB batch serializes
# the whole corpus behind one blocking device wait), big enough to amortize
# kernel launch; 8 KiB rows keep the kernel's VMEM working set off the
# spill cliff that 16 KiB rows hit
PALLAS_CHUNK_LEN = 8192
PALLAS_BATCH = 1024
# per-transfer-stream in-flight window: 2 = double buffering (batch N+1's
# transfer overlaps batch N's kernel on the same stream)
FEED_INFLIGHT = 2
# assembled slabs queued between the feeder and the transfer streams
FEED_QUEUE_DEPTH = 2
# arena slack beyond queue + windows: the slab being assembled + one spare
ARENA_MARGIN = 2
# transfer streams on a single non-CPU device when nothing else decides it:
# the axon-tunnel link serializes per transfer, so concurrent device_put
# calls from separate threads are the only way past the one-stream ceiling.
# Known tradeoff: the old single-thread loop existed because the axon
# tunnel's replay journal was measured to retain ~0.9 byte/byte scanned
# when transfers and fetches interleave across threads — multi-stream
# dispatch re-accepts that interleaving to buy link bandwidth. It is
# guarded rather than hidden: the bench streaming child runs with
# AXON_JOURNAL_COMPACT=1 (journal stays flat) and its RSS gate fails loud;
# TRIVY_TPU_FEED_STREAMS=1 restores the serialized behavior if a
# deployment hits journal growth
SINGLE_DEVICE_STREAMS = 4
# workers for exact host confirmation (overlaps device-result waits)
CONFIRM_WORKERS = 4
# bounded in-process LRU for the chunk-dedup hit cache; most entries are an
# empty tuple (clean chunk), so 64k entries cost a few MB
HIT_CACHE_ENTRIES = 1 << 16
# bump when device-compile semantics change in a way that alters hit
# vectors for identical (rules, chunk) inputs — invalidates persisted caches
HIT_CACHE_VERSION = 1
# re-dispatches allowed per failed batch before the failure escalates to
# the scan-level fallback ladder (OOM-shaped splits don't consume this
# budget: halving strictly shrinks the batch, so it terminates on its own)
BATCH_RETRIES = 2

# error shapes that mean "the batch was too big", answered by halving the
# batch instead of retrying it whole (XLA/PJRT spellings + the injected one)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted", "out of memory",
                "Out of memory", "OOM")


def _is_oom(err: BaseException) -> bool:
    s = f"{type(err).__name__}: {err}"
    return any(m in s for m in _OOM_MARKERS)


class _DeviceFailed(Exception):
    """Internal marker the device loop posts when its retry ladder is
    exhausted; ``cause`` is the original device/tunnel error."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def chunk_spans(n: int, chunk_len: int, overlap: int) -> list[int]:
    """Chunk start offsets covering ``n`` bytes with the given overlap."""
    if n <= chunk_len:
        return [0]
    step = chunk_len - overlap
    starts = list(range(0, n - overlap, step))
    return starts


@dataclass
class _FileState:
    path: str
    data: bytes
    pending: int  # chunks not yet matched
    # candidate rule index -> chunk windows (byte spans) where it hit
    rules: dict[int, list[tuple[int, int]]] = field(default_factory=dict)


class ScanStats:
    """Cumulative link-traffic counters (thread-safe): bench snapshots
    before/after a scan to compute link_bytes_per_corpus_byte and the
    dedup hit rate. ``bytes_uploaded`` counts padded row bytes actually
    dispatched (real link traffic incl. bucket padding); ``bytes_dedup_hit``
    counts corpus bytes whose rows were served from the hit cache or
    coalesced onto an identical in-flight row."""

    FIELDS = (
        "bytes_in",          # corpus bytes fed to the device path
        "bytes_uploaded",    # padded row bytes dispatched over the link
        "bytes_dedup_hit",   # corpus bytes resolved without an upload
        "bytes_packed",      # corpus bytes sharing a row with another file
        "chunks",            # rows the corpus decomposed into
        "chunks_uploaded",   # rows actually dispatched
        "chunks_dedup_hit",  # rows served from the hit cache / coalesced
        "rows_packed",       # dispatched rows carrying >1 file segment
        "files_packed",      # files that rode a shared row
        "batch_retries",     # failed batches re-dispatched whole
        "batch_splits",      # OOM-shaped failures answered by halving
        "degraded",          # scans that fell back to the exact host path
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._v = dict.fromkeys(self.FIELDS, 0)

    def add(self, **kw: int) -> None:
        with self._lock:
            for k, n in kw.items():
                self._v[k] += n

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._v)


class TpuSecretScanner:
    """Drop-in equivalent of :class:`SecretScanner` batched over TPU.

    ``scan_files`` consumes an iterable of (path, bytes) and yields one
    :class:`Secret` per input file, in input order, with findings identical
    to ``SecretScanner.scan_bytes``.
    """

    def __init__(
        self,
        config: ScannerConfig | None = None,
        chunk_len: int | None = None,
        batch_size: int | None = None,
        mesh=None,
        backend: str = "auto",
        confirm_workers: int = 0,  # 0 = CONFIRM_WORKERS default
        dedup: bool = True,
        pack_small: bool = True,
        hit_cache_entries: int = HIT_CACHE_ENTRIES,
        hit_cache=None,  # trivy_tpu.cache backend for cross-scan persistence
        dispatch: str = "auto",  # 'auto' | 'single' | 'round_robin'
        devices=None,  # explicit device list for round-robin dispatch
        host_fallback: bool = True,  # degrade to the exact host path on
        # unrecoverable device failure instead of failing the scan
        batch_retries: int = BATCH_RETRIES,
        feed_streams: int = 0,  # transfer-stream worker threads; 0 = auto
        # (one per round-robin device; SINGLE_DEVICE_STREAMS on one
        # accelerator; 2 on the CPU backend)
        inflight: int = 0,  # in-flight batches per stream; 0 = FEED_INFLIGHT
    ):
        import jax

        self.exact = SecretScanner(config)
        self.compiled: CompiledRules = compile_rules(self.exact.rules)
        if backend == "auto":
            platform = jax.devices()[0].platform
            backend = "pallas" if platform not in ("cpu", "METAL") else "xla"
        self.backend = backend
        if backend == "pallas":
            from trivy_tpu.ops.match_pallas import BLOCK_ROWS, build_match_fn_pallas

            self.chunk_len = chunk_len or PALLAS_CHUNK_LEN
            self.batch_size = batch_size or PALLAS_BATCH
            rows_mult = BLOCK_ROWS
            match_fn = build_match_fn_pallas(self.compiled, self.chunk_len)
        else:
            self.chunk_len = chunk_len or DEFAULT_CHUNK_LEN
            self.batch_size = batch_size or DEFAULT_BATCH
            rows_mult = 1
            match_fn = build_match_fn(self.compiled, self.chunk_len)
        self.overlap = max(64, self.compiled.span + 1)
        if self.overlap > self.chunk_len // 2:
            raise ValueError(
                f"chunk_len={self.chunk_len} too small for ruleset: the widest "
                f"device window needs overlap {self.overlap}; use chunk_len "
                f">= {2 * self.overlap}"
            )
        self._rules_by_id = {r.id: r for r in self.exact.rules}
        # windowed confirmation is sound only when flagged chunks bound the
        # match START: always true on the anchored lane; true on the keyword
        # lane only for bounded-width rules whose keyword provably sits
        # inside every match (the keyword occurrence then pins the start
        # within max_match_width). Everything else full-scans on flag.
        anchored = set(self.compiled.anchored_rule_ids)
        self._windowed_ids = anchored | {
            r.id
            for r in self.exact.rules
            if r.id not in anchored
            and r.keywords
            and r.keyword_in_match
            and r.max_match_width is not None
            and r.max_match_width <= 8192
        }
        self.confirm_workers = confirm_workers or CONFIRM_WORKERS

        # -- dedup hit cache ------------------------------------------------
        # ruleset fingerprint: the hit vector is a pure function of
        # (row bytes, compiled tables); keying the row hash with this
        # fingerprint makes any rule add/remove/regex/keyword change — and
        # any reordering, which renumbers rule indices — flip every key
        fp = hashlib.blake2b(digest_size=16)
        fp.update(f"v{HIT_CACHE_VERSION}:{self.chunk_len}:".encode())
        for r in self.exact.rules:
            fp.update(repr((r.id, r.regex, r.keywords, r.path)).encode())
            fp.update(b"\x00")
        self.ruleset_fingerprint = fp.digest()
        self._dedup = dedup
        self._pack_small = pack_small
        self._hit_lru: OrderedDict[bytes, tuple[int, ...]] = OrderedDict()
        self._hit_lru_max = hit_cache_entries
        self._hit_lock = threading.Lock()
        self._hit_persist = hit_cache
        self._host_fallback = host_fallback
        self._batch_retries = batch_retries
        self.stats = ScanStats()

        from trivy_tpu.parallel.mesh import (
            pad_batch,
            round_robin_match_fn,
            sharded_match_fn,
            single_stream_match_fn,
        )

        if dispatch not in ("auto", "single", "round_robin"):
            raise ValueError(
                f"dispatch={dispatch!r}: use 'auto', 'single', or 'round_robin'"
            )
        rr_devices = None
        local = list(devices) if devices is not None else jax.local_devices()
        platform = local[0].platform if local else "cpu"
        if mesh is None and dispatch != "single":
            # 'auto' opts in only on real multi-accelerator hosts; the CPU
            # backend's virtual devices share one memory bus, so multi-stream
            # dispatch there only adds copies (tests opt in explicitly)
            if len(local) > 1 and (
                dispatch == "round_robin" or platform not in ("cpu",)
            ):
                rr_devices = local

        if mesh is not None:
            inner = sharded_match_fn(match_fn, mesh, rows_multiple=rows_mult)
            dp = inner.data_parallelism
            self._match = single_stream_match_fn(
                lambda b: inner(pad_batch(b, dp))
            )
            row_multiple = dp
        elif rr_devices is not None:
            self._match = round_robin_match_fn(
                match_fn, rr_devices, rows_multiple=rows_mult
            )
            row_multiple = rows_mult
        elif rows_mult > 1:
            self._match = single_stream_match_fn(
                lambda b: match_fn(pad_batch(b, rows_mult))
            )
            row_multiple = rows_mult
        else:
            self._match = single_stream_match_fn(match_fn)
            row_multiple = 1

        # transfer-stream sizing: one worker thread per round-robin device
        # (per-device copies overlap each other), several streams on one
        # accelerator (concurrent device_puts are the only way past a
        # serialized tunnel link), two on the CPU backend (keeps the async
        # machinery exercised in tests without thrashing one memory bus)
        if feed_streams <= 0:
            feed_streams = int(
                os.environ.get("TRIVY_TPU_FEED_STREAMS", "0") or 0
            )
        if feed_streams <= 0:
            if rr_devices is not None:
                feed_streams = len(rr_devices)
            elif platform in ("cpu", "METAL"):
                feed_streams = 2
            else:
                feed_streams = SINGLE_DEVICE_STREAMS
        self.feed_streams = max(1, feed_streams)
        if inflight <= 0:
            inflight = int(
                os.environ.get("TRIVY_TPU_FEED_INFLIGHT", "0") or 0
            )
        self.inflight = max(1, inflight or FEED_INFLIGHT)
        # dispatch-shape bucket ladder: every shape compiles exactly once
        # (variable trailing-batch shapes would recompile per distinct size).
        # The ladder stops at B/4: each extra rung costs a full Mosaic
        # compile of every kernel (~minutes through a remote-compile
        # tunnel), while padding a short trailing batch up to B/4 rows
        # costs microseconds of device time
        buckets = [self.batch_size]
        while (
            buckets[-1] // 2 >= max(8, row_multiple, self.batch_size // 4)
        ):
            buckets.append(buckets[-1] // 2)
        self._buckets = sorted(buckets)

    # -- dedup hit cache ----------------------------------------------------

    def _persist_key(self, key: bytes) -> str:
        return f"secret-hitv:{self.ruleset_fingerprint.hex()}:{key.hex()}"

    def _hit_get(self, key: bytes) -> tuple[int, ...] | None:
        """Cached per-rule hit vector for a row digest, or None."""
        with self._hit_lock:
            v = self._hit_lru.get(key)
            if v is not None:
                self._hit_lru.move_to_end(key)
                return v
        if self._hit_persist is not None:
            blob = self._hit_persist.get_blob(self._persist_key(key))
            if blob is not None:
                v = tuple(blob["r"])
                self._lru_insert(key, v)
                return v
        return None

    def clear_hit_cache(self) -> None:
        """Drop the in-process hit LRU (persisted entries are untouched) —
        used by bench to measure the cold feed path."""
        with self._hit_lock:
            self._hit_lru.clear()

    def _lru_insert(self, key: bytes, hit_rules: tuple[int, ...]) -> None:
        """Insert under the entry bound — every LRU write path must evict,
        or persisted-cache re-scans of large corpora grow RSS unboundedly."""
        with self._hit_lock:
            self._hit_lru[key] = hit_rules
            self._hit_lru.move_to_end(key)
            while len(self._hit_lru) > self._hit_lru_max:
                self._hit_lru.popitem(last=False)

    def _hit_put(self, key: bytes, hit_rules: tuple[int, ...]) -> None:
        self._lru_insert(key, hit_rules)
        if self._hit_persist is not None:
            self._hit_persist.put_blob(
                self._persist_key(key), {"r": list(hit_rules)}
            )

    # -- async feed pipeline ------------------------------------------------

    def scan_files(self, files: Iterable[tuple[str, bytes]]) -> Iterator[Secret]:
        """Scan many files; yields per-file results in input order.

        The input iterable is consumed on a dedicated feeder thread, so a
        slow consumer of this generator (or a slow head-of-line
        confirmation) never stalls chunking, hashing, or device transfers
        — backpressure comes only from the bounded arena, dispatch queue,
        and confirm semaphore. See :class:`_ScanRun` for the pipeline.
        """
        run = _ScanRun(self, files, obs.current())
        run.start()
        try:
            next_emit = 0
            while True:
                with run.cond:
                    while True:
                        if run.error is not None:
                            raise run.error
                        if next_emit in run.results:
                            r = run.results.pop(next_emit)
                            break
                        if run.total is not None and next_emit >= run.total:
                            return
                        run.cond.wait(0.2)
                yield r.result() if isinstance(r, Future) else r
                next_emit += 1
        finally:
            run.close()

    def scan_bytes(self, path: str, data: bytes) -> Secret:
        """Single-file convenience (still device-prefiltered)."""
        return next(iter(self.scan_files([(path, data)])))

    def _note_degraded(self, ctx, err: BaseException) -> None:
        logger.warning(
            "device pipeline failed (%s); completing the scan on the exact "
            "host confirm path — slower, findings identical", err,
        )
        self.stats.add(degraded=1)
        ctx.count("secret.degraded")
        obs.note_scan_degraded()

    # -- host confirmation --------------------------------------------------

    def _confirm(self, st: _FileState, prof=None) -> Secret:
        # span recording happens in scan_files' confirm_task (which holds
        # the scan's trace context); direct callers time themselves
        return self._confirm_inner(st, prof)

    def _confirm_inner(self, st: _FileState, prof=None) -> Secret:
        windows_by_id = {
            self.compiled.rule_ids[i]: w for i, w in st.rules.items()
        }
        host_ids = set(self.compiled.host_rule_ids)
        if not windows_by_id and not host_ids:
            return Secret(file_path=st.path)
        content = st.data.decode("latin-1")
        lower = content.lower()
        global_blocks = self.exact.global_block_spans(content)
        hits = []
        for rule in self.exact.rules_for_path(st.path):
            t0 = time.perf_counter() if prof is not None else 0.0
            if rule.id in windows_by_id:
                if rule.id in self._windowed_ids:
                    # regex runs only around the device-flagged chunk windows
                    locs = self.exact.find_rule_locations_in_windows(
                        rule, content, lower, windows_by_id[rule.id], global_blocks
                    )
                else:
                    # keyword lane without a start bound: the flagged chunk
                    # locates the keyword, not the match — full-content scan
                    # (detector-accelerated for unbounded-width rules)
                    locs = self.exact.find_rule_locations_fullscan(
                        rule, content, lower, global_blocks
                    )
            elif rule.id in host_ids:
                locs = self.exact.find_rule_locations(
                    rule, content, lower, global_blocks
                )
            else:
                continue
            if prof is not None:
                prof.confirm(rule.id, time.perf_counter() - t0, len(locs))
            hits.extend((rule, loc) for loc in locs)
        return self.exact.build_findings(st.path, content, hits)


# sentinel a worker receives when the pipeline is shutting down or has
# switched to the host fallback (distinct from the end-of-input None)
_ABORT = object()


class _ScanRun:
    """One ``scan_files`` invocation's async pipeline.

    Threads (all daemon, all scoped to this run):

    - **feeder**: consumes the caller's file iterable; chunks, hashes
      (dedup keys), packs small files, and assembles rows into
      :class:`~trivy_tpu.secret.feed.ChunkArena` slabs — large files via
      one vectorized strided gather per slab run, not per-row Python —
      then hands full slabs to the bounded dispatch queue.
    - **transfer streams** (``scanner.feed_streams`` workers): each pulls
      slabs, dispatches through ``scanner._match.dispatch`` (round-robin
      across devices or concurrent streams into one device), keeps a
      bounded in-flight window (double buffering: transfer N+1 overlaps
      kernel N), fetches the oldest result, releases the slab, and
      resolves hits inline. The per-batch retry ladder (re-dispatch,
      OOM halving, circuit-breaker feedback) runs here, per stream.
    - **confirm pool**: exact host confirmation, bounded by a semaphore
      so retained file bytes stay flat on streaming scans.

    The generator side of ``scan_files`` only emits: completed results
    land in ``results`` (the reorder buffer) keyed by input index and are
    yielded in order. Emission never blocks the feeder.

    Failure ladder: a stream that exhausts its retries calls
    :meth:`_degrade` (host fallback: every unresolved and unread file is
    rescanned by the exact host engine — the parity oracle) or, with
    ``host_fallback=False``, :meth:`_fail` so the generator re-raises.
    """

    def __init__(self, sc: TpuSecretScanner, files, ctx):
        self.sc = sc
        self.files = files
        self.ctx = ctx
        self.enabled = ctx.enabled
        self.prof = ctx.profile() if ctx.enabled else None
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.states: dict[int, _FileState] = {}
        # reorder buffer: input index -> Secret | in-flight Future
        self.results: dict[int, Secret | Future] = {}
        # row digest -> waiting segment lists: identical rows already
        # dispatched but not yet resolved coalesce here instead of being
        # uploaded again (zero pages recur within a single batch)
        self.row_waiters: dict[bytes, list] = {}
        self.total: int | None = None  # set once the input is exhausted
        self.error: BaseException | None = None
        self.degraded = False
        self.stop = threading.Event()
        streams = sc.feed_streams
        self.in_q: queue.Queue = queue.Queue(maxsize=FEED_QUEUE_DEPTH)
        self.arena = ChunkArena(
            FEED_QUEUE_DEPTH + streams * sc.inflight + ARENA_MARGIN,
            sc.batch_size,
            sc.chunk_len,
        )
        self.pool = ThreadPoolExecutor(max_workers=sc.confirm_workers)
        # backpressure: bounds queued+running confirms so a slow confirm
        # pool cannot accumulate unbounded _FileState.data on a large
        # streaming scan (file bytes are released once its confirm runs)
        self.confirm_slots = threading.Semaphore(sc.confirm_workers * 4)
        self.workers = [
            threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"secret-xfer-{i}",
            )
            for i in range(streams)
        ]
        self.feeder = threading.Thread(
            target=self._feed_guarded, daemon=True, name="secret-feeder"
        )

    def start(self) -> None:
        for w in self.workers:
            w.start()
        self.feeder.start()

    def close(self) -> None:
        self.stop.set()
        self.feeder.join(timeout=10.0)
        for w in self.workers:
            w.join(timeout=10.0)
        self.pool.shutdown(wait=False)
        # slabs still parked in the dispatch queue after an early close
        while True:
            try:
                item = self.in_q.get_nowait()
            except queue.Empty:
                break
            if item is not None and item is not _ABORT:
                self.arena.release(item[0])
        # feed-path introspection for tests and bench debugging: on a
        # clean scan every slab is back in the arena (no leak into the
        # streaming-RSS budget) and acquires ≫ slabs proves reuse
        self.sc._last_feed_stats = {
            "arena_slabs": self.arena.n_slabs,
            "arena_free": self.arena.free_slabs,
            "arena_acquires": self.arena.acquires,
            "streams": len(self.workers),
        }

    # -- shared control -----------------------------------------------------

    def _aborted(self) -> bool:
        return (
            self.stop.is_set() or self.error is not None or self.degraded
        )

    def _put_slab(self, item) -> bool:
        while not self._aborted():
            try:
                self.in_q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _put_sentinel(self) -> None:
        while not self._aborted():
            try:
                self.in_q.put(None, timeout=0.2)
                return
            except queue.Full:
                continue

    def _get_work(self):
        while True:
            if self._aborted():
                return _ABORT
            try:
                return self.in_q.get(timeout=0.2)
            except queue.Empty:
                continue

    def _fail(self, err: BaseException) -> None:
        with self.cond:
            if self.error is None:
                self.error = err
            self.cond.notify_all()
        self.stop.set()

    def _degrade(self, cause: BaseException) -> None:
        """Last rung: move every file with unresolved device work onto the
        exact host confirm path (partial device results are discarded),
        once. The feeder notices ``degraded`` and routes the rest of the
        input stream straight to the host engine."""
        with self.lock:
            if self.degraded or self.error is not None:
                return
            self.degraded = True
            moved = [(i, self.states.pop(i)) for i in sorted(self.states)]
            self.row_waiters.clear()
        self.sc._note_degraded(self.ctx, cause)
        for fidx, st in moved:
            self._submit_host(fidx, st.path, st.data)
        with self.cond:
            self.cond.notify_all()

    # -- result plumbing ----------------------------------------------------

    def _acquire_slot(self) -> bool:
        while not (self.stop.is_set() or self.error is not None):
            if self.confirm_slots.acquire(timeout=0.2):
                return True
        return False

    def _set_result(self, fidx: int, value) -> None:
        with self.cond:
            self.results[fidx] = value
            self.cond.notify_all()

    def _confirm_task(self, st: _FileState) -> Secret:
        try:
            with obs.activate(self.ctx), self.ctx.span("secret.confirm"):
                return self.sc._confirm(st, self.prof)
        finally:
            self.confirm_slots.release()

    def _host_task(self, path: str, data: bytes) -> Secret:
        # degraded-mode rung: the exact host engine IS the parity oracle,
        # so fallback findings are byte-identical by definition
        try:
            with obs.activate(self.ctx), self.ctx.span("secret.host_fallback"):
                return self.sc.exact.scan_bytes(path, data)
        finally:
            self.confirm_slots.release()

    def _submit_confirm(self, fidx: int, st: _FileState) -> None:
        if not self._acquire_slot():
            return  # shutting down; nobody will wait on this result
        self._set_result(fidx, self.pool.submit(self._confirm_task, st))

    def _submit_host(self, fidx: int, path: str, data: bytes) -> None:
        if not self._acquire_slot():
            return
        self._set_result(fidx, self.pool.submit(self._host_task, path, data))

    def _apply_hits(self, batch: list) -> None:
        """Credit resolved rows to their file segments; ``batch`` is
        ``[(segs, hit_rules)]``. Every row hit applies to every segment —
        cross-segment false candidates are discarded by the exact confirm.
        Files whose last pending row resolved here go to the confirm pool
        (the semaphore is taken OUTSIDE the pipeline lock so a full
        confirm queue stalls only the calling thread, not resolution
        bookkeeping on other streams)."""
        prof = self.prof
        if prof is not None:
            rule_ids = self.sc.compiled.rule_ids
            for _, hit_rules in batch:
                # one logical device hit per (row, rule) — dedup-cache and
                # coalesced rows count too: they cost a confirm all the same
                for r in hit_rules:
                    prof.gate_hit(rule_ids[r])
        ready: list[tuple[int, _FileState]] = []
        with self.lock:
            for segs, hit_rules in batch:
                for fidx, ws, we in segs:
                    st = self.states.get(fidx)
                    if st is None:
                        continue  # already moved to the host path
                    for r in hit_rules:
                        st.rules.setdefault(r, []).append((ws, we))
                for fidx, _, _ in segs:
                    st = self.states.get(fidx)
                    if st is None:
                        continue
                    st.pending -= 1
                    if st.pending == 0:
                        del self.states[fidx]
                        ready.append((fidx, st))
        for fidx, st in ready:
            self._submit_confirm(fidx, st)

    def _resolve(self, batch_hits: np.ndarray, batch_meta: list) -> None:
        # one vectorized nonzero per batch, not one per row; rows past
        # len(batch_meta) are bucket padding and are sliced off here
        rows, ridx = np.nonzero(batch_hits[: len(batch_meta)])
        by_row: dict[int, list[int]] = {}
        for row, r in zip(rows.tolist(), ridx.tolist()):
            by_row.setdefault(row, []).append(r)
        apply: list = []
        for row, (key, segs) in enumerate(batch_meta):
            hit_rules = tuple(by_row.get(row, ()))
            apply.append((segs, hit_rules))
            if key is not None:
                self.sc._hit_put(key, hit_rules)
                with self.lock:
                    waiting = self.row_waiters.pop(key, ())
                for w in waiting:
                    apply.append((w, hit_rules))
        self._apply_hits(apply)

    # -- transfer-stream workers --------------------------------------------

    def _worker(self, wid: int) -> None:
        """One transfer stream: dispatch slabs asynchronously, keep a
        bounded in-flight window (double buffering), fetch the oldest,
        resolve inline. Per-batch failure ladder as in README
        "Robustness": re-dispatch up to ``batch_retries`` times (under
        round-robin the retry lands on the next healthy device and the
        breaker hears about it), OOM-shaped errors split the batch in
        half, and only an exhausted ladder (or every device
        circuit-broken) escalates to the scan-level host fallback.

        Stall instrumentation (all on the spawning scan's context):
        ``secret.feed_wait`` is time blocked on the host feed
        (feed-starved), ``secret.dispatch`` the enqueue/transfer handoff
        (upload-bound), ``secret.device_wait`` the blocking result fetch
        (device-bound)."""
        from trivy_tpu.parallel.mesh import DevicesUnavailable

        sc = self.sc
        ctx = self.ctx
        match = sc._match
        dispatch_fn = match.dispatch
        record = getattr(match, "record_result", None)
        prof = self.prof
        stats = sc.stats
        chunk_len = sc.chunk_len
        # (dev, meta, batch, slab_id, device_idx, retries); slab_id is None
        # for retry copies, which own their arrays outright
        pending: deque = deque()

        def rebatch(batch: np.ndarray, meta: list) -> np.ndarray:
            """Fresh bucket-padded copy of a failed batch's live rows —
            the source slab is released right after, so retries never
            alias arena memory the feeder may refill."""
            n = next(b for b in sc._buckets if b >= len(meta))
            out = np.zeros((n, chunk_len), dtype=np.uint8)
            out[: len(meta)] = batch[: len(meta)]
            return out

        def recover(batch, meta, slab_id, retries, err) -> list:
            """Ladder decision for one failed batch: work items to
            re-dispatch, or raise when the ladder is exhausted. Always
            ends the source slab's ownership."""
            if isinstance(err, DevicesUnavailable):
                if slab_id is not None:
                    self.arena.release(slab_id)
                raise _DeviceFailed(err)  # no device left to retry on
            if _is_oom(err) and len(meta) > 1:
                stats.add(batch_splits=1)
                if self.enabled:
                    ctx.count("secret.batch_splits")
                logger.warning(
                    "device OOM on a %d-row batch (%s); splitting and "
                    "re-dispatching the halves", len(meta), err,
                )
                mid = (len(meta) + 1) // 2
                halves = [
                    (rebatch(batch[:mid], meta[:mid]), meta[:mid], None, retries),
                    (rebatch(batch[mid:], meta[mid:]), meta[mid:], None, retries),
                ]
                if slab_id is not None:
                    self.arena.release(slab_id)
                return halves
            if retries < sc._batch_retries:
                stats.add(batch_retries=1)
                if self.enabled:
                    ctx.count("secret.batch_retries")
                logger.warning(
                    "device error on a %d-row batch (retry %d/%d): %s",
                    len(meta), retries + 1, sc._batch_retries, err,
                )
                fresh = rebatch(batch, meta)
                if slab_id is not None:
                    self.arena.release(slab_id)
                return [(fresh, meta, None, retries + 1)]
            if slab_id is not None:
                self.arena.release(slab_id)
            raise _DeviceFailed(err)

        def dispatch_batch(batch, meta, slab_id, retries) -> None:
            work = [(batch, meta, slab_id, retries)]
            while work:
                b, m, sid, r = work.pop()
                try:
                    with ctx.span("secret.dispatch"):
                        dev, didx = dispatch_fn(b)
                except Exception as e:
                    # dispatch-time failure (breaker already notified by
                    # the round-robin wrapper); walk the ladder
                    work.extend(recover(b, m, sid, r, e))
                    continue
                pending.append((dev, m, b, sid, didx, r))

        def fetch_oldest() -> None:
            dev, meta, batch, sid, didx, retries = pending.popleft()
            try:
                faults.check(
                    "device.fetch", key=f"d{didx if didx is not None else 0}"
                )
                t0 = time.perf_counter() if prof is not None else 0.0
                with ctx.span("secret.device_wait"):
                    arr = np.asarray(dev)
                if prof is not None:
                    # per-bucket dispatch cost: the bucket is the padded
                    # batch shape (the compile-once ladder rung), rows are
                    # the live rows it carried
                    prof.bucket_dispatch(
                        batch.shape[0], len(meta), time.perf_counter() - t0
                    )
            except Exception as e:
                if record is not None and didx is not None:
                    record(didx, False)
                for item in recover(batch, meta, sid, retries, e):
                    dispatch_batch(*item)
                return
            if record is not None and didx is not None:
                record(didx, True)
            if sid is not None:
                # the fetch proves the transfer finished: the slab can be
                # refilled without aliasing a zero-copy device view
                self.arena.release(sid)
            if not self.degraded:
                self._resolve(arr, meta)

        def release_pending() -> None:
            while pending:
                _, _, _, sid, _, _ = pending.popleft()
                if sid is not None:
                    self.arena.release(sid)

        with obs.activate(ctx):
            try:
                while True:
                    with ctx.span("secret.feed_wait"):
                        item = self._get_work()
                    if item is None or item is _ABORT:
                        break
                    slab_id, batch, meta = item
                    dispatch_batch(batch, meta, slab_id, 0)
                    while len(pending) >= sc.inflight:
                        fetch_oldest()
                while pending and not self._aborted():
                    fetch_oldest()
            except _DeviceFailed as e:
                release_pending()
                if sc._host_fallback:
                    self._degrade(e.cause)
                else:
                    self._fail(e.cause)
            except BaseException as e:  # unexpected: surface it loudly
                release_pending()
                self._fail(e)
            finally:
                release_pending()
                if self.degraded:
                    # return whatever the feeder parked before it noticed
                    while True:
                        try:
                            item = self.in_q.get_nowait()
                        except queue.Empty:
                            break
                        if item is not None and item is not _ABORT:
                            self.arena.release(item[0])

    # -- feeder -------------------------------------------------------------

    def _feed_guarded(self) -> None:
        with obs.activate(self.ctx):
            try:
                self._feed()
            except BaseException as e:
                self._fail(e)

    def _feed(self) -> None:
        sc = self.sc
        ctx = self.ctx
        enabled = self.enabled
        stats = sc.stats
        chunk_len = sc.chunk_len
        B = sc.batch_size
        dedup = sc._dedup
        fp_key = sc.ruleset_fingerprint
        gap = sc.overlap
        pack_max = chunk_len - gap
        blake2b = hashlib.blake2b

        slab_id: int | None = None
        slab: np.ndarray | None = None
        used = 0
        # per-row feed metadata: (digest | None, [(fidx, win_start, win_end)])
        meta: list[tuple[bytes | None, list[tuple[int, int, int]]]] = []
        # slab rows awaiting the bulk strided gather from the current file
        copy_rows: list[int] = []
        copy_starts: list[int] = []
        copy_win = None  # row_windows view over the current file's bytes
        pack_pending: list[tuple[int, bytes]] = []
        pack_len = 0
        total = 0

        class _FeedAbort(Exception):
            pass

        def flush_copies() -> None:
            nonlocal copy_rows, copy_starts
            if copy_rows:
                # ONE vectorized gather for every full row the current
                # file placed in this slab
                slab[np.asarray(copy_rows)] = copy_win[np.asarray(copy_starts)]
                copy_rows = []
                copy_starts = []

        def ensure_slab() -> None:
            nonlocal slab_id, slab, used
            if slab is None:
                with ctx.span("secret.arena_wait"):
                    got = self.arena.acquire(self._aborted)
                if got is None:
                    raise _FeedAbort
                slab_id, slab = got
                used = 0

        def register_state(fidx: int, st: _FileState) -> bool:
            """False when the scan degraded concurrently — the caller
            must route the file to the host path instead (a state added
            after :meth:`_degrade` swept the table would never resolve)."""
            with self.lock:
                if self.degraded:
                    return False
                self.states[fidx] = st
                return True

        def route_row(key, segs, nbytes) -> bool:
            """True when the row resolved without an upload: served from
            the hit cache or coalesced onto an identical in-flight row."""
            if key is None:
                return False
            cached = sc._hit_get(key)
            if cached is not None:
                stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                if enabled:
                    ctx.count("secret.bytes_dedup_hit", nbytes)
                self._apply_hits([(segs, cached)])
                return True
            with self.lock:
                waiting = self.row_waiters.get(key)
                if waiting is not None:
                    waiting.append(segs)
                    coalesced = True
                else:
                    self.row_waiters[key] = []
                    coalesced = False
            if coalesced:
                stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                if enabled:
                    ctx.count("secret.bytes_dedup_hit", nbytes)
            return coalesced

        def flush() -> None:
            nonlocal slab_id, slab, used, meta
            flush_copies()
            if not meta:
                return  # empty slab: padding-only batches are never sent
            n = next(b for b in sc._buckets if b >= len(meta))
            stats.add(bytes_uploaded=n * chunk_len)
            if enabled:
                ctx.count("secret.bytes_uploaded", n * chunk_len)
                ctx.sample("secret.queue_depth", self.in_q.qsize())
            ok = self._put_slab((slab_id, slab[:n], meta))
            if not ok:
                self.arena.release(slab_id)
            slab_id = None
            slab = None
            used = 0
            meta = []
            if not ok:
                raise _FeedAbort
            # bound pack-row staleness to one batch: a lone small file must
            # not sit in pack_pending while big files stream past it — its
            # unresolved state would stall in-order emission and let results
            # accumulate unbounded on a streaming scan. The partial pack row
            # rides the next batch instead (re-entry is shallow: the fresh
            # meta holds one row, far below batch_size, so no second flush)
            if pack_pending:
                emit_pack()

        def emit_pack() -> None:
            nonlocal pack_len, used
            if not pack_pending:
                return
            items = list(pack_pending)
            pack_pending.clear()
            pack_len = 0
            key = None
            if dedup:
                if len(items) == 1:
                    # single-segment row == plain chunk-row layout: share the
                    # plain digest domain so it dedups across both paths
                    key = blake2b(
                        items[0][1], digest_size=16, key=fp_key
                    ).digest()
                else:
                    h = blake2b(
                        digest_size=16, key=fp_key, person=b"packed-row"
                    )
                    for _, d in items:
                        h.update(len(d).to_bytes(4, "little"))
                        h.update(d)
                    key = h.digest()
            segs = [(fidx, 0, len(d)) for fidx, d in items]
            nbytes = sum(len(d) for _, d in items)
            stats.add(chunks=1)
            if route_row(key, segs, nbytes):
                return
            ensure_slab()
            row = slab[used]
            row[:] = 0  # zero guard gaps + stale tail (slabs are reused)
            off = 0
            for _, d in items:
                row[off : off + len(d)] = np.frombuffer(d, dtype=np.uint8)
                off += len(d) + gap
            meta.append((key, segs))
            used += 1
            stats.add(chunks_uploaded=1)
            if len(segs) > 1:
                stats.add(
                    rows_packed=1, files_packed=len(segs), bytes_packed=nbytes
                )
                if enabled:
                    ctx.count("secret.bytes_packed", nbytes)
            if used == B:
                flush()

        def add_small(fidx: int, data: bytes) -> None:
            # small-file packing: files below a row's size accumulate and
            # share one row, separated by >=span zero gaps (see module
            # docstring for why packing cannot suppress a real match)
            nonlocal pack_len
            if pack_len and pack_len + gap + len(data) > chunk_len:
                emit_pack()
            pack_pending.append((fidx, data))
            pack_len += (gap if pack_len else 0) + len(data)

        def feed_big(fidx: int, path: str, data: bytes) -> None:
            nonlocal used, copy_win
            starts = chunk_spans(len(data), chunk_len, sc.overlap)
            if not register_state(
                fidx, _FileState(path=path, data=data, pending=len(starts))
            ):
                self._submit_host(fidx, path, data)
                return
            arr = np.frombuffer(data, dtype=np.uint8)
            n = arr.size
            stats.add(bytes_in=len(data), chunks=len(starts))
            copy_win = row_windows(arr, chunk_len)
            uploaded = 0
            for s in starts:
                end = min(s + chunk_len, n)
                key = (
                    blake2b(arr[s:end], digest_size=16, key=fp_key).digest()
                    if dedup
                    else None
                )
                segs = [(fidx, s, s + chunk_len)]
                if route_row(key, segs, end - s):
                    continue
                ensure_slab()
                if end - s == chunk_len:
                    copy_rows.append(used)
                    copy_starts.append(s)
                else:
                    # short tail row: copy, then zero the stale remainder
                    slab[used, : end - s] = arr[s:end]
                    slab[used, end - s :] = 0
                meta.append((key, segs))
                used += 1
                uploaded += 1
                if used == B:
                    flush()
            flush_copies()  # the view dies with this file's scope
            copy_win = None
            if uploaded:
                stats.add(chunks_uploaded=uploaded)

        feed_ok = True
        try:
            for fidx, (path, data) in enumerate(self.files):
                total = fidx + 1
                if self.stop.is_set() or self.error is not None:
                    total -= 1  # not processed; the generator is closing
                    break
                if self.degraded:
                    # device path is gone: route straight to the exact host
                    # engine under the same confirm backpressure (files
                    # already swept by _degrade keep their host results)
                    pack_pending.clear()
                    self._submit_host(fidx, path, data)
                    continue
                try:
                    with ctx.span("secret.assemble"):
                        if sc.exact.allow_path(path):
                            # path-level global allowlist: skip the whole
                            # file (ref: scanner.go:388-392) — no device work
                            self._set_result(fidx, Secret(file_path=path))
                        elif not data:
                            # empty file: nothing for the device to match —
                            # resolve host-side immediately (host-lane rules
                            # still run there)
                            self._submit_confirm(
                                fidx,
                                _FileState(path=path, data=data, pending=0),
                            )
                        elif sc._pack_small and len(data) <= pack_max:
                            stats.add(bytes_in=len(data))
                            if register_state(
                                fidx,
                                _FileState(path=path, data=data, pending=1),
                            ):
                                add_small(fidx, data)
                            else:
                                self._submit_host(fidx, path, data)
                        else:
                            feed_big(fidx, path, data)
                except _FeedAbort:
                    # mid-file abort: a registered state was already swept
                    # onto the host path by _degrade; on plain shutdown the
                    # generator is closing and nobody waits on this file
                    if not self.degraded:
                        break
            if not self._aborted():
                try:
                    emit_pack()  # flush the partial pack row
                    flush()  # dispatch the final partial slab
                except _FeedAbort:
                    pass
        except BaseException:
            # do NOT publish `total` on a failed feed: emission must see
            # the error (set by _feed_guarded), not a truncated-but-
            # "complete" input count that would silently swallow it
            feed_ok = False
            raise
        finally:
            if slab is not None:
                # an unflushed (empty or aborted) slab goes straight back:
                # padding rows never reach the dispatch queue or dedup keys
                self.arena.release(slab_id)
            with self.cond:
                if feed_ok:
                    self.total = total
                self.cond.notify_all()
            for _ in range(len(self.workers)):
                self._put_sentinel()
