"""TPU-backed secret scanner: device prefilter + exact host confirmation.

Pipeline (replaces the reference's walk→goroutine→regexp chain, ref:
pkg/fanal/secret/scanner.go:377 and SURVEY.md §3.2):

  files → overlapping fixed-size chunks → [B, C] batches → device match
  kernel → per-(file, rule) candidates → exact `SecretScanner` restricted to
  candidate rules → findings (byte-identical to the CPU backend).

Chunk overlap equals the compiled ruleset's maximum device window, so every
device-checkable window lies fully inside at least one chunk. The host
confirm is window-restricted only where the flagged chunk provably bounds
the match start (anchored lane; keyword lane with the keyword inside every
match — see ``_windowed_ids``); other keyword-lane rules rescan the whole
file on flag, with unbounded-width regexes accelerated by their bounded
start-detector prefix (``Rule.start_detector``).

The feed path is a fully asynchronous pipeline — the TPU analog of the
reference's walker-goroutine fan-out into a bounded channel
(`parallel.Pipeline`, ref: pkg/parallel/pipeline.go:14-115,
scan_flags.go:79-84):

  input thread (feeder): chunk / hash / dedup / pack into a fixed
  **chunk arena** of preallocated reusable row slabs
  (:class:`trivy_tpu.secret.feed.ChunkArena`) — large files gather all
  their full rows into a slab with ONE vectorized strided copy, counters
  accumulate per file, not per row
    → bounded dispatch queue
  **transfer streams** (N worker threads, one per round-robin device, ≥2
  on a single device): each keeps a bounded in-flight window of
  double-buffered dispatches (`jax.device_put` + kernel enqueue are
  async), so batch N+1's host→device transfer overlaps batch N's kernel
  AND the per-stream transfers overlap each other — on a serialized
  tunnel link this multiplies effective feed bandwidth by the stream
  count; slabs release back to the arena only after the blocking fetch,
  then results resolve inline
    → confirm pool (bounded by a semaphore)
    → **reorder buffer**: the generator emits per-file results in input
  order from a completion map, so a slow head-of-line confirmation never
  stalls the feeder — readers keep filling the arena while emission
  waits.

Dispatch shapes are drawn from a fixed bucket ladder (B, B/2, B/4, ...) so
every shape compiles exactly once. Arena slots bound host memory (slabs in
the dispatch queue + per-stream windows + assembly margin); the confirm
semaphore bounds retained file bytes; together they are the streaming-RSS
guarantee the bench gate enforces.

The device side is a FUSED pass (README "Fused device pass"): each batch
is placed once (`parallel.mesh.StagedDispatch`) and every detector reads
the resident rows — the keyword prefilter first (its candidate mask gates
whether the anchored matcher dispatches at all, feeds keyword-lane hits
directly, and accumulates per-file candidates that gate host confirms at
whole-file MatchKeywords semantics), then the anchored matcher when
needed, then (with ``--scanners secret,license``) the license gram gate
(`licensing/fused.py`) so license candidacy costs zero extra link bytes.

The feed path sends link bytes ≪ corpus bytes (the host→device link, not
the kernel, is the e2e ceiling):

- **chunk-dedup hit cache**: every row is content-hashed (keyed blake2b,
  key = ruleset fingerprint so a rule add/remove/change flips every key)
  and duplicate rows — vendored deps, repeated OCI layer content, zero
  pages — reuse the cached per-rule hit vector with no upload and no
  kernel. Sound because the hit vector is a pure function of (row bytes,
  compiled tables); path-dependent filtering happens later, host-side.
  Bounded in-process LRU, optionally persisted through the trivy_tpu.cache
  layer (fs/redis) for cross-scan reuse — the same insight as the
  reference's layer cache: never re-scan content already seen.
- **small-file row packing**: files smaller than a row share one row,
  separated by ≥-span zero guard gaps. A real match's device program reads
  only match bytes (+1 boundary byte), so packing can never suppress a hit;
  cross-file windows only add false candidates that the exact host confirm
  discards.
- **round-robin multi-stream dispatch** (parallel.mesh.round_robin_match_fn)
  sends whole batches to each local device in turn so transfers overlap
  kernels across devices, multiplying effective link bandwidth.

Failure domains (README "Robustness"): a failed batch re-dispatches up to
``batch_retries`` times (OOM-shaped errors split the batch in half
instead), round-robin dispatch carries a per-device circuit breaker that
excludes a dying device and re-probes it on a backoff, and when nothing
device-side survives the scan completes on the exact host confirm path —
the parity oracle — with findings byte-identical and the scan flagged
degraded.
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
from collections import deque
from collections.abc import Iterable, Iterator
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from trivy_tpu import faults, log, obs
from trivy_tpu.obs import recorder as flight
from trivy_tpu.ops.match import build_match_fn
from trivy_tpu.secret.compress import COMPRESS_MIN_RATIO, CompressedSlab
from trivy_tpu.secret.device_compile import CompiledRules, compile_rules
from trivy_tpu.secret.feed import ChunkArena, row_windows
from trivy_tpu.secret.engine import ScannerConfig, SecretScanner
from trivy_tpu.types import Secret

logger = log.logger("secret:tpu")

DEFAULT_CHUNK_LEN = 65536
DEFAULT_BATCH = 64
# pallas path: small self-contained rows.
# 1024 x 8 KiB = 8 MiB batches: small enough that pack -> transfer ->
# kernel -> confirm overlap through the pipeline (a 32 MiB batch serializes
# the whole corpus behind one blocking device wait), big enough to amortize
# kernel launch; 8 KiB rows keep the kernel's VMEM working set off the
# spill cliff that 16 KiB rows hit
PALLAS_CHUNK_LEN = 8192
PALLAS_BATCH = 1024
# per-transfer-stream in-flight window: 2 = double buffering (batch N+1's
# transfer overlaps batch N's kernel on the same stream)
FEED_INFLIGHT = 2
# assembled slabs queued between the feeder and the transfer streams
FEED_QUEUE_DEPTH = 2
# arena slack beyond queue + windows: the slab being assembled + one spare
ARENA_MARGIN = 2
# transfer streams on a single non-CPU device when nothing else decides it:
# the axon-tunnel link serializes per transfer, so concurrent device_put
# calls from separate threads are the only way past the one-stream ceiling.
# Known tradeoff: the old single-thread loop existed because the axon
# tunnel's replay journal was measured to retain ~0.9 byte/byte scanned
# when transfers and fetches interleave across threads — multi-stream
# dispatch re-accepts that interleaving to buy link bandwidth. It is
# guarded rather than hidden: the bench streaming child runs with
# AXON_JOURNAL_COMPACT=1 (journal stays flat) and its RSS gate fails loud;
# TRIVY_TPU_FEED_STREAMS=1 restores the serialized behavior if a
# deployment hits journal growth
SINGLE_DEVICE_STREAMS = 4
# workers for exact host confirmation (overlaps device-result waits)
CONFIRM_WORKERS = 4
# bounded in-process LRU for the chunk-dedup hit cache; most entries are an
# empty tuple (clean chunk), so 64k entries cost a few MB. The REAL bound
# is now the byte budget (hitstore.DEFAULT_STORE_MB / --secret-dedup-mb);
# this entry count stays as a backstop
HIT_CACHE_ENTRIES = 1 << 16
# bump when device-compile semantics change in a way that alters hit
# vectors for identical (rules, chunk) inputs — invalidates persisted caches
# (v2: values grew prefilter candidate masks + nfa/license flags;
# v3: the fingerprint folds the --secret-config file content and persisted
# lookups/writes are batched through secret/hitstore.py;
# v4: compressed slab wire format — rows may now reach the kernels through
# the device decompressor, whose output must be byte-identical to a raw
# upload; the bump invalidates stores written by builds without that
# parity guarantee. Keys still hash UNCOMPRESSED row content, so entries
# stay codec-invariant: toggling --secret-compress never flips a key)
HIT_CACHE_VERSION = 4
# re-dispatches allowed per failed batch before the failure escalates to
# the scan-level fallback ladder (OOM-shaped splits don't consume this
# budget: halving strictly shrinks the batch, so it terminates on its own)
BATCH_RETRIES = 2

# error shapes that mean "the batch was too big", answered by halving the
# batch instead of retrying it whole (XLA/PJRT spellings + the injected one)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted", "out of memory",
                "Out of memory", "OOM")


def _is_oom(err: BaseException) -> bool:
    s = f"{type(err).__name__}: {err}"
    return any(m in s for m in _OOM_MARKERS)


class _DeviceFailed(Exception):
    """Internal marker the device loop posts when its retry ladder is
    exhausted; ``cause`` is the original device/tunnel error."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def chunk_spans(n: int, chunk_len: int, overlap: int) -> list[int]:
    """Chunk start offsets covering ``n`` bytes with the given overlap."""
    if n <= chunk_len:
        return [0]
    step = chunk_len - overlap
    starts = list(range(0, n - overlap, step))
    return starts


@dataclass
class _FileState:
    path: str
    data: bytes
    pending: int  # chunks not yet matched
    # candidate rule index -> chunk windows (byte spans) where it hit
    rules: dict[int, list[tuple[int, int]]] = field(default_factory=dict)
    # prefilter candidate rules accumulated over EVERY chunk of the file
    # (None when the prefilter pass is off): guarded rules are confirmed
    # only when present here — the reference's whole-file MatchKeywords
    # gate, applied from device data instead of a host lowercase scan
    cand: set[int] | None = None
    # guarded anchored rules whose kernel was SKIPPED for >=1 chunk of this
    # file (batch had no candidates): their windows may be incomplete, so a
    # candidate among them confirms via full scan instead of windows
    unchecked: set[int] = field(default_factory=set)


class ScanStats:
    """Cumulative link-traffic counters (thread-safe): bench snapshots
    before/after a scan to compute link_bytes_per_corpus_byte and the
    dedup hit rate. ``bytes_uploaded`` counts padded row bytes actually
    dispatched (real link traffic incl. bucket padding); ``bytes_dedup_hit``
    counts corpus bytes whose rows were served from the hit cache or
    coalesced onto an identical in-flight row."""

    FIELDS = (
        "bytes_in",          # corpus bytes fed to the device path
        "bytes_uploaded",    # padded row bytes dispatched over the link
        "bytes_dedup_hit",   # corpus bytes resolved without an upload
        "bytes_packed",      # corpus bytes sharing a row with another file
        "chunks",            # rows the corpus decomposed into
        "chunks_uploaded",   # rows actually dispatched
        "chunks_dedup_hit",  # rows served from the hit cache / coalesced
        "rows_packed",       # dispatched rows carrying >1 file segment
        "files_packed",      # files that rode a shared row
        "batch_retries",     # failed batches re-dispatched whole
        "batch_splits",      # OOM-shaped failures answered by halving
        "degraded",          # scans that fell back to the exact host path
        "chunks_warm_hit",   # rows served from the PERSISTENT store
        "bytes_warm_hit",    # corpus bytes those rows covered
        "rows_prefiltered",  # rows the keyword prefilter pass inspected
        "rows_prefilter_hit",  # rows with >=1 candidate rule
        "rows_nfa_skipped",  # rows whose batch skipped the anchored kernel
        "batches_nfa_skipped",  # batches resolved by the prefilter alone
        "license_rows_gated",    # arena rows the license gram gate read
        "license_rows_flagged",  # rows that flagged a license candidate
        # compressed wire format (secret/compress.py): bytes_uploaded above
        # always counts ACTUAL link traffic (compressed wire + framing when
        # a batch ships compressed); bytes_raw_equiv is what those batches
        # would have cost raw, so ratio = uploaded/raw_equiv-side math
        # needs no second bookkeeping path
        "bytes_compressed",      # wire+framing bytes of compressed batches
        "bytes_raw_equiv",       # raw padded bytes those batches replaced
        "bytes_raw_fallback",    # padded bytes shipped raw (didn't pay /
                                 # codec error / binary-heavy batch)
        "bytes_gated",           # corpus bytes the zero gate kept off the
                                 # link (all-zero rows resolve via dedup)
        "bytes_gated_binary",    # raw bytes of binary rows shipped RAW
                                 # inside compressed frames
        "chunks_gated_zero",     # rows the zero gate resolved
        "batches_compressed",    # batches that shipped compressed
        "batches_raw_fallback",  # batches that fell back to raw slabs
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._v = dict.fromkeys(self.FIELDS, 0)

    def add(self, **kw: int) -> None:
        with self._lock:
            for k, n in kw.items():
                self._v[k] += n

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._v)


class TpuSecretScanner:
    """Drop-in equivalent of :class:`SecretScanner` batched over TPU.

    ``scan_files`` consumes an iterable of (path, bytes) and yields one
    :class:`Secret` per input file, in input order, with findings identical
    to ``SecretScanner.scan_bytes``.
    """

    def __init__(
        self,
        config: ScannerConfig | None = None,
        chunk_len: int | None = None,
        batch_size: int | None = None,
        mesh=None,
        backend: str = "auto",
        confirm_workers: int = 0,  # 0 = CONFIRM_WORKERS default
        dedup: bool = True,
        pack_small: bool = True,
        hit_cache_entries: int = HIT_CACHE_ENTRIES,
        hit_cache=None,  # trivy_tpu.cache backend for cross-scan persistence
        dispatch: str = "auto",  # 'auto' | 'single' | 'round_robin'
        devices=None,  # explicit device list for round-robin dispatch
        host_fallback: bool = True,  # degrade to the exact host path on
        # unrecoverable device failure instead of failing the scan
        batch_retries: int = BATCH_RETRIES,
        feed_streams: int = 0,  # transfer-stream worker threads; 0 = auto
        # (one per round-robin device; SINGLE_DEVICE_STREAMS on one
        # accelerator; 2 on the CPU backend)
        inflight: int = 0,  # in-flight batches per stream; 0 = FEED_INFLIGHT
        prefilter: bool = True,  # on-device keyword prefilter first pass
        # (--no-secret-prefilter); auto-disabled when no rule has keywords
        tuning=None,  # trivy_tpu.tuning.TuningConfig; None = env-resolved
        # defaults (no implicit AUTOTUNE.json discovery — the CLI layer
        # resolves the full CLI > env > autotune > topology chain and
        # passes the result here; library callers stay hermetic)
        arena_slabs: int = 0,  # chunk-arena slab override; 0 = derived
        bucket_rungs: int = 0,  # dispatch bucket-ladder depth; 0 = default
        hit_cache_bytes: int = 0,  # dedup LRU byte budget; 0 = tuning's
        # dedup_store_mb (default hitstore.DEFAULT_STORE_MB)
        compress: str = "",  # compressed slab wire format: 'auto' (on for
        # real accelerator links, off on the host backend and under a
        # mesh), 'on', 'off'; "" = tuning's --secret-compress resolution
        compress_min_ratio: float = 0.0,  # per-batch wire budget as a
        # fraction of the raw slab — a batch that can't compress below
        # this ships raw; 0 = tuning / COMPRESS_MIN_RATIO default
    ):
        import jax

        from trivy_tpu.tuning import resolve_tuning, topology_fingerprint

        # the consolidated knob config (ROADMAP item 4): explicit ctor args
        # are the strongest layer (tests/bench pass them directly), then the
        # TuningConfig's own CLI > env > autotune > topology-default chain.
        # Fingerprinting here is free — this ctor initializes jax anyway
        if tuning is None:
            tuning = resolve_tuning(
                autotune_path="", topology=topology_fingerprint()
            )
        elif not tuning.topology:
            tuning.topology = topology_fingerprint()
        self.tuning = tuning

        self.exact = SecretScanner(config)
        self.compiled: CompiledRules = compile_rules(self.exact.rules)
        if backend == "auto":
            platform = jax.devices()[0].platform
            backend = "pallas" if platform not in ("cpu", "METAL") else "xla"
        self.backend = backend
        # fused-pass prefilter: a cheap keyword-only kernel runs over every
        # slab first; the full matcher drops its keyword lane and batches
        # with no anchored candidates skip it entirely (ops/prefilter.py)
        self.prefilter_on = bool(prefilter) and bool(
            self.compiled.prefilter_keywords
        )
        if backend == "pallas":
            from trivy_tpu.ops.match_pallas import BLOCK_ROWS, build_match_fn_pallas

            self.chunk_len = chunk_len or PALLAS_CHUNK_LEN
            self.batch_size = batch_size or PALLAS_BATCH
            rows_mult = BLOCK_ROWS
            match_fn = build_match_fn_pallas(
                self.compiled, self.chunk_len,
                include_keywords=not self.prefilter_on,
            )
        else:
            self.chunk_len = chunk_len or DEFAULT_CHUNK_LEN
            self.batch_size = batch_size or DEFAULT_BATCH
            rows_mult = 1
            match_fn = build_match_fn(
                self.compiled, self.chunk_len,
                include_keywords=not self.prefilter_on,
            )
        if self.prefilter_on:
            from trivy_tpu.ops.prefilter import build_prefilter_fn

            self._prefilter_fn = build_prefilter_fn(
                self.compiled, self.chunk_len, backend=backend
            )
        else:
            self._prefilter_fn = None
        # rule-axis index tables the fused pass resolves against
        g = self.compiled.guarded
        anchored_idx = {i for i, _ in self.compiled.variants}
        self._kw_lane_cols = np.asarray(
            sorted({i for i, _ in self.compiled.keywords}), dtype=np.int64
        )
        self._guarded_anchored = frozenset(
            i for i in anchored_idx if g[i]
        )
        self._guarded_anchored_cols = np.asarray(
            sorted(self._guarded_anchored), dtype=np.int64
        )
        # anchored rules with no keywords are never prefilter-gated: their
        # presence forces the anchored kernel on every batch
        self._has_unguarded_anchored = any(not g[i] for i in anchored_idx)
        self._guarded_ids = frozenset(
            self.compiled.rule_ids[i] for i in np.nonzero(g)[0]
        )
        self.overlap = max(64, self.compiled.span + 1)
        if self.overlap > self.chunk_len // 2:
            raise ValueError(
                f"chunk_len={self.chunk_len} too small for ruleset: the widest "
                f"device window needs overlap {self.overlap}; use chunk_len "
                f">= {2 * self.overlap}"
            )
        self._rules_by_id = {r.id: r for r in self.exact.rules}
        # windowed confirmation is sound only when flagged chunks bound the
        # match START: always true on the anchored lane; true on the keyword
        # lane only for bounded-width rules whose keyword provably sits
        # inside every match (the keyword occurrence then pins the start
        # within max_match_width). Everything else full-scans on flag.
        anchored = set(self.compiled.anchored_rule_ids)
        self._windowed_ids = anchored | {
            r.id
            for r in self.exact.rules
            if r.id not in anchored
            and r.keywords
            and r.keyword_in_match
            and r.max_match_width is not None
            and r.max_match_width <= 8192
        }
        self.confirm_workers = confirm_workers or CONFIRM_WORKERS

        # -- dedup hit cache ------------------------------------------------
        # ruleset fingerprint: the hit vector is a pure function of
        # (row bytes, compiled tables); keying the row hash with this
        # fingerprint makes any rule add/remove/regex/keyword change — and
        # any reordering, which renumbers rule indices — flip every key
        fp = hashlib.blake2b(digest_size=16)
        fp.update(f"v{HIT_CACHE_VERSION}:{self.chunk_len}:".encode())
        for r in self.exact.rules:
            fp.update(repr((r.id, r.regex, r.keywords, r.path)).encode())
            fp.update(b"\x00")
        # the FULL effective config: the --secret-config file's content
        # digest (allow rules / exclude blocks / disables change findings
        # without changing hit vectors, and a persisted manifest keyed on
        # this fingerprint caches findings) — a changed rule file flips
        # every persisted namespace, loudly (hitstore namespace marker)
        fp.update(b"cfg:")
        fp.update(getattr(config, "source_digest", "").encode() or b"-")
        # prefilter table fingerprint: cached vectors now carry candidate
        # masks derived from the keyword table, so a keyword add/remove/edit
        # — or toggling the prefilter itself, which changes the cached value
        # semantics (nfa_ran bookkeeping) — must flip every key
        if self.prefilter_on:
            fp.update(b"pf:")
            fp.update(self.compiled.prefilter_fingerprint())
        else:
            fp.update(b"pf-off")
        self.ruleset_fingerprint = fp.digest()
        self._dedup = dedup
        self._pack_small = pack_small
        # persistent cross-scan dedup store (secret/hitstore.py): the
        # in-process LRU is BYTE-bounded (--secret-dedup-mb, entry count
        # as a backstop) so streaming multi-GB scans keep flat RSS, and
        # backend lookups/writes are batched per assembled/resolved batch
        from trivy_tpu.secret.hitstore import DEFAULT_STORE_MB, HitStore

        store_bytes = hit_cache_bytes or (
            (tuning.dedup_store_mb or DEFAULT_STORE_MB) << 20
        )
        self._hit_store = HitStore(
            self.ruleset_fingerprint,
            backend=hit_cache,
            max_entries=hit_cache_entries,
            max_bytes=store_bytes,
        )
        self._host_fallback = host_fallback
        self._batch_retries = batch_retries
        self.stats = ScanStats()

        from trivy_tpu.parallel.mesh import StagedDispatch, pad_batch

        if dispatch not in ("auto", "single", "round_robin"):
            raise ValueError(
                f"dispatch={dispatch!r}: use 'auto', 'single', or 'round_robin'"
            )
        rr_devices = None
        local = list(devices) if devices is not None else jax.local_devices()
        platform = local[0].platform if local else "cpu"
        if mesh is None and dispatch != "single":
            # 'auto' opts in only on real multi-accelerator hosts; the CPU
            # backend's virtual devices share one memory bus, so multi-stream
            # dispatch there only adds copies (tests opt in explicitly)
            if len(local) > 1 and (
                dispatch == "round_robin" or platform not in ("cpu",)
            ):
                rr_devices = local

        # fused-pass dispatch: ONE placement per batch, every device
        # detector (prefilter, anchored match, license gram gate) runs
        # against the resident rows — the upload is shared, not repeated
        self._staged = StagedDispatch(
            mesh=mesh, devices=rr_devices, rows_multiple=rows_mult
        )
        self._staged.add_stage("match", match_fn, out_axes=2)
        if self._prefilter_fn is not None:
            self._staged.add_stage("prefilter", self._prefilter_fn, out_axes=2)
        self._stage_lock = threading.Lock()
        row_multiple = self._staged.pad_to

        # bench/back-compat surface: the raw jitted match kernel (pure and
        # traceable, pads short batches itself) plus the stream/breaker
        # attributes tests and warm-up loops key off
        match_stage = self._staged.stage_fn("match")
        pad_to = self._staged.pad_to

        def _compat_match(chunks):
            return match_stage(pad_batch(chunks, pad_to))

        if rr_devices is not None:
            _compat_match.n_streams = len(rr_devices)
            _compat_match.breaker = self._staged.breaker
            _compat_match.devices = rr_devices
        self._match = _compat_match

        # transfer-stream sizing: explicit ctor arg > TuningConfig (which
        # folds CLI/env/autotune) > topology default — one worker thread
        # per round-robin device (per-device copies overlap each other),
        # several streams on one accelerator (concurrent device_puts are
        # the only way past a serialized tunnel link), two on the CPU
        # backend (keeps the async machinery exercised in tests without
        # thrashing one memory bus)
        if feed_streams <= 0:
            feed_streams = tuning.feed_streams
        if feed_streams <= 0:
            if rr_devices is not None:
                feed_streams = len(rr_devices)
            elif platform in ("cpu", "METAL"):
                feed_streams = 2
            else:
                feed_streams = SINGLE_DEVICE_STREAMS
        self.feed_streams = max(1, feed_streams)
        if inflight <= 0:
            inflight = tuning.inflight
        self.inflight = max(1, inflight or FEED_INFLIGHT)
        # arena override (0 = the derived queue+windows+margin bound in
        # _ScanRun); clamped there to keep at least a double-buffer cycling
        self.arena_slabs = max(0, arena_slabs or tuning.arena_slabs)
        # dispatch-shape bucket ladder: every shape compiles exactly once
        # (variable trailing-batch shapes would recompile per distinct size).
        # The default ladder stops at B/4 (3 rungs): each extra rung costs
        # a full Mosaic compile of every kernel (~minutes through a
        # remote-compile tunnel), while padding a short trailing batch up
        # to the smallest rung costs microseconds of device time. The
        # depth is a tuning knob (--secret-bucket-rungs) because the
        # tradeoff flips on corpora dominated by tiny trailing batches.
        rungs = max(1, bucket_rungs or tuning.bucket_rungs or 3)
        self.bucket_rungs = rungs
        min_bucket = max(
            8, row_multiple, self.batch_size // (1 << (rungs - 1))
        )
        buckets = [self.batch_size]
        while buckets[-1] // 2 >= min_bucket:
            buckets.append(buckets[-1] // 2)
        self._buckets = sorted(buckets)

        # -- compressed slab wire format (secret/compress.py) ---------------
        # 'auto' opts in only where compression can pay: a real accelerator
        # link (the CPU backend shares one memory bus — compressing for it
        # only burns host cycles) and no mesh (a flat wire buffer has no
        # row axis to shard_map over). Zero-cost-when-off bar: an 'off'
        # scanner builds no codec tables, registers no decompress stage,
        # and allocates no wire-rung state — bench --smoke asserts this.
        from trivy_tpu.parallel.mesh import link_class

        mode = compress or tuning.compress or "auto"
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"compress={mode!r}: use 'auto', 'on', or 'off'"
            )
        comp_on = mode == "on" or (
            mode == "auto" and link_class(platform) != "host"
        )
        if comp_on and mesh is not None:
            if mode == "on":
                logger.warning(
                    "--secret-compress is unsupported under a sharded mesh "
                    "(a flat wire buffer has no row axis to shard); "
                    "shipping raw slabs"
                )
            comp_on = False
        if comp_on and self.chunk_len % 8:
            logger.warning(
                "--secret-compress needs chunk_len %% 8 == 0 (7-bit "
                "packing), got %d; shipping raw slabs", self.chunk_len,
            )
            comp_on = False
        self.compress_min_ratio = float(
            compress_min_ratio or tuning.compress_min_ratio
            or COMPRESS_MIN_RATIO
        )
        if not 0.0 < self.compress_min_ratio <= 1.0:
            raise ValueError(
                f"compress_min_ratio={self.compress_min_ratio} out of (0, 1]"
            )
        self._codec = None
        # per rows-bucket wire-size ladder {top, top/2, top/4, top/8}: the
        # wire buffer buckets to a rung so decompress compiles once per
        # (rows, rung) pair, and a very compressible batch (zero pages,
        # config trees) rides a small rung instead of padding to the top
        self._wire_rungs: dict[int, list[int]] = {}
        if comp_on:
            from trivy_tpu.ops.decompress import build_decompress_fn
            from trivy_tpu.secret.compress import SlabCodec

            self._codec = SlabCodec(self.chunk_len)
            self._staged.add_stage(
                "decompress",
                build_decompress_fn(
                    self.chunk_len, self._codec.tab_bytes,
                    self._codec.tab_len,
                ),
                out_axes=2,
            )
            for b in self._buckets:
                top = -(-int(b * self.chunk_len * self.compress_min_ratio)
                        // 128) * 128
                rungs = [top]
                while len(rungs) < 4 and rungs[-1] // 2 >= 1024:
                    rungs.append(rungs[-1] // 2)
                self._wire_rungs[b] = sorted(rungs)
        self.compress_on = comp_on

    # -- dedup hit cache ----------------------------------------------------
    #
    # Cached value per row digest (the "row verdict"): a 4-tuple
    #   (hit_rules, cand_rules, nfa_ran, lic)
    # - hit_rules: device hit vector (anchored hits + keyword-lane hits)
    # - cand_rules: prefilter candidate rules (== hit_rules' keyword part
    #   plus anchored-lane keyword presences); () when the prefilter is off
    # - nfa_ran: False when the row's batch skipped the anchored kernel —
    #   a conservative, row-pure marker: replaying it marks every guarded
    #   anchored rule unchecked for the row's file, so a candidate there
    #   confirms via full scan (soundness does not depend on which batch
    #   the row originally rode)
    # - lic: fused license-gate verdict: True/False, or None when the
    #   row's batch never ran the gate (consumers must not trust None)
    # The digest is keyed with the ruleset fingerprint (which now folds in
    # the prefilter table) plus a ':lic' namespace when a license gate is
    # active, so entries can never cross modes.

    @property
    def _hit_lru(self):
        # introspection surface for tests/bench (entry count, reuse proofs)
        return self._hit_store._lru

    @property
    def _hit_persist(self):
        return self._hit_store.backend

    def _hit_get(self, key: bytes):
        """Cached row verdict for a row digest from the IN-PROCESS LRU, or
        None. Persistent-store lookups are batched at slab-flush time
        (one pipelined round trip per batch — see ``_ScanRun._feed``)."""
        return self._hit_store.get(key)

    def clear_hit_cache(self) -> None:
        """Drop the in-process hit LRU (persisted entries are untouched) —
        used by bench to measure the cold feed path."""
        self._hit_store.clear_local()

    def _hit_put(self, key: bytes, verdict) -> None:
        self._hit_store.put(key, verdict)

    def seed_hit_entries(self, entries: list) -> int:
        """Pre-warm the dedup store from a peer's export (fleet
        cross-replica warming); returns entries accepted. Entries from a
        different fingerprint namespace are dropped loudly in the store."""
        return self._hit_store.seed(entries)

    def export_warm_hits(self, limit: int = 0) -> list:
        """Warm dedup entries (``[[persist_key, doc], ...]``) for peer
        seeding."""
        from trivy_tpu.secret.hitstore import WARM_EXPORT_LIMIT

        return self._hit_store.export_warm(limit or WARM_EXPORT_LIMIT)

    # -- async feed pipeline ------------------------------------------------

    def warm_buckets(self) -> None:
        """Compile every (bucket shape × stream × stage) combination
        outside any timed region — put + prefilter + match (+ the license
        gram gate, when registered) per rung, so the first real batch
        never pays a compile."""
        stages = ["match"]
        if self._staged.has_stage("prefilter"):
            stages.insert(0, "prefilter")
        if self._staged.has_stage("license"):
            stages.append("license")
        for b in self._buckets:
            for _ in range(max(1, self._staged.n_streams)):
                dev, didx = self._staged.put(
                    np.zeros((b, self.chunk_len), dtype=np.uint8)
                )
                for name in stages:
                    np.asarray(self._staged.run(name, dev, didx))
                # close the warm batch's busy interval: warm-up must not
                # pin the utilization telemetry's in-flight accounting
                self._staged.record_result(didx, True)
        if self._codec is None:
            return
        # compressed path: one decompress compile per (rows, wire-rung)
        # pair; the downstream stages reuse the raw-shape executables
        # compiled above (the decoder's [b, C] output IS the raw shape)
        for b in self._buckets:
            for rung in self._wire_rungs[b]:
                frame = (
                    np.zeros(rung, dtype=np.uint8),
                    np.zeros(b, dtype=np.int32),
                    np.zeros(b, dtype=np.int32),
                    np.zeros(b, dtype=np.uint8),
                )
                for _ in range(max(1, self._staged.n_streams)):
                    dev, didx = self._staged.put_parts(frame)
                    rows = self._staged.run("decompress", dev, didx)
                    for name in stages:
                        np.asarray(self._staged.run(name, rows, didx))
                    self._staged.record_result(didx, True)

    def _ensure_license_stage(self) -> None:
        """Register the license gram-gate kernel as a fused stage (once per
        scanner; the jitted gate itself is process-cached per chunk_len).
        Output is per-BLOCK ([B, chunk_len/block]) so packed-row segments
        resolve to their own verdicts."""
        with self._stage_lock:
            if not self._staged.has_stage("license"):
                from trivy_tpu.licensing.fused import get_gate_fn

                fn = get_gate_fn(self.chunk_len)
                self._lic_block = fn.block
                self._staged.add_stage("license", fn, out_axes=2)

    def scan_files(
        self, files: Iterable[tuple[str, bytes]], license_gate=None
    ) -> Iterator[Secret]:
        """Scan many files; yields per-file results in input order.

        The input iterable is consumed on a dedicated feeder thread, so a
        slow consumer of this generator (or a slow head-of-line
        confirmation) never stalls chunking, hashing, or device transfers
        — backpressure comes only from the bounded arena, dispatch queue,
        and confirm semaphore. See :class:`_ScanRun` for the pipeline.

        ``license_gate`` (a :class:`trivy_tpu.licensing.fused.
        FusedLicenseGate`) opts this scan into the shared-arena fused pass:
        the license gram gate runs over the same resident rows and the
        gate accumulates per-file candidate verdicts for the license
        analyzer — each scanned byte crosses the link once for both
        detectors.
        """
        if license_gate is not None:
            self._ensure_license_stage()
        run = _ScanRun(self, files, obs.current(), license_gate)
        run.start()
        try:
            next_emit = 0
            while True:
                with run.cond:
                    while True:
                        if run.error is not None:
                            raise run.error
                        if next_emit in run.results:
                            r = run.results.pop(next_emit)
                            break
                        if run.total is not None and next_emit >= run.total:
                            return
                        run.cond.wait(0.2)
                yield r.result() if isinstance(r, Future) else r
                next_emit += 1
        finally:
            run.close()

    def scan_bytes(self, path: str, data: bytes) -> Secret:
        """Single-file convenience (still device-prefiltered)."""
        return next(iter(self.scan_files([(path, data)])))

    def tuning_snapshot(self) -> dict:
        """The EFFECTIVE knob set this scanner runs with — post-resolution
        values, per-knob provenance, and (after a scan) the final values
        the online controller left behind. Embedded in BENCH rep details,
        ``--metrics-out``, and heartbeat lines so differently-tuned rounds
        stay comparable and ``--check-regression`` can annotate knob drift
        alongside a throughput change."""
        doc = {
            "feed_streams": self.feed_streams,
            "inflight": self.inflight,
            "arena_slabs": self.arena_slabs,  # 0 = derived per scan
            "bucket_ladder": list(self._buckets),
            "controller": bool(self.tuning.controller),
            "compress": self.compress_on,
            "compress_min_ratio": self.compress_min_ratio,
            "topology": self.tuning.topology,
            "source": dict(self.tuning.source),
        }
        last = getattr(self, "_last_tuning", None)
        if last:
            doc["effective"] = dict(last)
        return doc

    def _note_degraded(self, ctx, err: BaseException) -> None:
        logger.warning(
            "device pipeline failed (%s); completing the scan on the exact "
            "host confirm path — slower, findings identical", err,
        )
        self.stats.add(degraded=1)
        ctx.count("secret.degraded")
        obs.note_scan_degraded()

    # -- host confirmation --------------------------------------------------

    def _confirm(self, st: _FileState, prof=None) -> Secret:
        # span recording happens in scan_files' confirm_task (which holds
        # the scan's trace context); direct callers time themselves
        return self._confirm_inner(st, prof)

    def _confirm_inner(self, st: _FileState, prof=None) -> Secret:
        from trivy_tpu.secret.rules import ascii_lower

        windows_by_id = {
            self.compiled.rule_ids[i]: w for i, w in st.rules.items()
        }
        host_ids = set(self.compiled.host_rule_ids)
        cand_ids: set[str] | None = None
        unchecked_ids: set[str] = set()
        extra_ids: set[str] = set()
        if st.cand is not None:
            rid = self.compiled.rule_ids
            cand_ids = {rid[i] for i in st.cand}
            unchecked_ids = {rid[i] for i in st.unchecked}
            # guarded anchored rules that are file-level candidates but
            # whose kernel was skipped for some chunk may have recorded no
            # window at all — they still need a (full-scan) confirmation
            extra_ids = (unchecked_ids & cand_ids) - set(windows_by_id)
        if not windows_by_id and not host_ids and not extra_ids:
            return Secret(file_path=st.path)
        content = st.data.decode("latin-1")
        lower = ascii_lower(content)
        global_blocks = self.exact.global_block_spans(content)
        hits = []
        for rule in self.exact.rules_for_path(st.path):
            if (
                cand_ids is not None
                and rule.id in self._guarded_ids
                and rule.id not in cand_ids
            ):
                # no keyword of this rule occurs anywhere in the file: the
                # exact engine's match_keywords would reject it, so the
                # confirm (and its wasted_confirm cost) is skipped outright
                # — this is the prefilter's answer to the PR 5 fp_rate rows
                continue
            t0 = time.perf_counter() if prof is not None else 0.0
            if rule.id in windows_by_id:
                if rule.id in unchecked_ids:
                    # some chunk of this file never ran the rule's anchored
                    # kernel (its batch was prefilter-skipped): windows may
                    # be incomplete, so fall back to the full-content scan
                    locs = self.exact.find_rule_locations_fullscan(
                        rule, content, lower, global_blocks
                    )
                elif rule.id in self._windowed_ids:
                    # regex runs only around the device-flagged chunk windows
                    locs = self.exact.find_rule_locations_in_windows(
                        rule, content, lower, windows_by_id[rule.id], global_blocks
                    )
                else:
                    # keyword lane without a start bound: the flagged chunk
                    # locates the keyword, not the match — full-content scan
                    # (detector-accelerated for unbounded-width rules)
                    locs = self.exact.find_rule_locations_fullscan(
                        rule, content, lower, global_blocks
                    )
            elif rule.id in extra_ids:
                locs = self.exact.find_rule_locations_fullscan(
                    rule, content, lower, global_blocks
                )
            elif rule.id in host_ids:
                locs = self.exact.find_rule_locations(
                    rule, content, lower, global_blocks
                )
            else:
                continue
            if prof is not None:
                prof.confirm(rule.id, time.perf_counter() - t0, len(locs))
            hits.extend((rule, loc) for loc in locs)
        return self.exact.build_findings(st.path, content, hits)


# sentinel a worker receives when the pipeline is shutting down or has
# switched to the host fallback (distinct from the end-of-input None)
_ABORT = object()


class _ScanRun:
    """One ``scan_files`` invocation's async pipeline.

    Threads (all daemon, all scoped to this run):

    - **feeder**: consumes the caller's file iterable; chunks, hashes
      (dedup keys), packs small files, and assembles rows into
      :class:`~trivy_tpu.secret.feed.ChunkArena` slabs — large files via
      one vectorized strided gather per slab run, not per-row Python —
      then hands full slabs to the bounded dispatch queue.
    - **transfer streams** (``scanner.feed_streams`` workers): each pulls
      slabs, dispatches through ``scanner._match.dispatch`` (round-robin
      across devices or concurrent streams into one device), keeps a
      bounded in-flight window (double buffering: transfer N+1 overlaps
      kernel N), fetches the oldest result, releases the slab, and
      resolves hits inline. The per-batch retry ladder (re-dispatch,
      OOM halving, circuit-breaker feedback) runs here, per stream.
    - **confirm pool**: exact host confirmation, bounded by a semaphore
      so retained file bytes stay flat on streaming scans.

    The generator side of ``scan_files`` only emits: completed results
    land in ``results`` (the reorder buffer) keyed by input index and are
    yielded in order. Emission never blocks the feeder.

    Failure ladder: a stream that exhausts its retries calls
    :meth:`_degrade` (host fallback: every unresolved and unread file is
    rescanned by the exact host engine — the parity oracle) or, with
    ``host_fallback=False``, :meth:`_fail` so the generator re-raises.
    """

    def __init__(self, sc: TpuSecretScanner, files, ctx, license_gate=None):
        self.sc = sc
        self.files = files
        self.ctx = ctx
        self.enabled = ctx.enabled
        self.prof = ctx.profile() if ctx.enabled else None
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.states: dict[int, _FileState] = {}
        # fused license pass: per-scan candidate gate + fidx -> query path
        # for files the license analyzer will ask about
        self.lic_gate = license_gate
        self.lic_paths: dict[int, str] = {}
        # reorder buffer: input index -> Secret | in-flight Future
        self.results: dict[int, Secret | Future] = {}
        # row digest -> waiting segment lists: identical rows already
        # dispatched but not yet resolved coalesce here instead of being
        # uploaded again (zero pages recur within a single batch)
        self.row_waiters: dict[bytes, list] = {}
        self.total: int | None = None  # set once the input is exhausted
        self.error: BaseException | None = None
        self.degraded = False
        self.stop = threading.Event()
        # wire-accounting baseline: scanner stats are cumulative across
        # scans, so this run's compression ratio needs a delta
        self._stats0 = sc.stats.snapshot()
        self.feed_done = threading.Event()  # input exhausted (or failed)
        streams = sc.feed_streams
        # online tuning (trivy_tpu/tuning.py): the controller adapts the
        # ACTIVE stream count, the per-stream in-flight window, and the
        # arena size mid-scan. Controller-off scans allocate nothing extra
        # — exactly `streams` worker threads, the derived arena bound, no
        # controller thread or decision buffers (the zero-cost-when-off
        # bar bench --smoke enforces)
        self._controller_on = (
            bool(sc.tuning.controller) and sc.tuning.tuning_interval > 0
        )
        self.controller = None
        if self._controller_on:
            from trivy_tpu.tuning import inflight_limit, stream_limit

            n_alloc = stream_limit(streams)
            self._max_inflight = inflight_limit(sc.inflight)
        else:
            n_alloc = streams
            self._max_inflight = sc.inflight
        self.active_streams = streams
        self.inflight = sc.inflight  # run-level window; controller-mutable
        self.in_q: queue.Queue = queue.Queue(maxsize=FEED_QUEUE_DEPTH)
        slabs = sc.arena_slabs or (
            FEED_QUEUE_DEPTH + streams * sc.inflight + ARENA_MARGIN
        )
        # a 1-slab arena cannot double-buffer: the feeder would block on
        # the single slab a worker still holds — keep a cycling pair
        slabs = max(2, slabs)
        self._max_arena_slabs = max(
            slabs, FEED_QUEUE_DEPTH + n_alloc * self._max_inflight
            + ARENA_MARGIN,
        )
        self.arena = ChunkArena(slabs, sc.batch_size, sc.chunk_len)
        # HBM ledger: the arena bound is the worst-case device residency
        # of in-flight batch rows (every slab's rows may be device-side at
        # once across the dispatch windows); released at close()
        flight.note_resident("arena", slabs * sc.batch_size * sc.chunk_len)
        self.pool = ThreadPoolExecutor(max_workers=sc.confirm_workers)
        # backpressure: bounds queued+running confirms so a slow confirm
        # pool cannot accumulate unbounded _FileState.data on a large
        # streaming scan (file bytes are released once its confirm runs)
        self.confirm_slots = threading.Semaphore(sc.confirm_workers * 4)
        # live-telemetry state (obs/timeseries.py): per-stream in-flight
        # window depths and the confirm queue depth, updated per batch /
        # per confirm — cheap enough to keep on untraced scans, read only
        # by an attached sampler's probe
        self._stream_inflight = [0] * n_alloc
        self._confirm_inflight = 0
        self.workers = [
            threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"secret-xfer-{i}",
            )
            for i in range(n_alloc)
        ]
        self.feeder = threading.Thread(
            target=self._feed_guarded, daemon=True, name="secret-feeder"
        )

    def start(self) -> None:
        self.ctx.add_probe(self._telemetry_probe)
        for w in self.workers:
            w.start()
        self.feeder.start()
        if self._controller_on:
            from trivy_tpu.tuning import TuningController

            self.controller = TuningController(
                self, ctx=self.ctx,
                interval=self.sc.tuning.tuning_interval,
            ).start()

    # -- online-tuning adapter (trivy_tpu.tuning.TuningController) ----------

    def knobs(self) -> dict:
        return {
            "feed_streams": self.active_streams,
            "inflight": self.inflight,
            "arena_slabs": self.arena.n_slabs,
        }

    def limits(self) -> dict:
        return {
            "max_streams": len(self.workers),
            "max_inflight": self._max_inflight,
            "max_arena_slabs": self._max_arena_slabs,
        }

    def raw_gauges(self) -> dict:
        s = self.sc.stats.snapshot()
        busy = self.sc._staged.busy.busy_seconds()
        return {
            "queue_depth": float(self.in_q.qsize()),
            "arena_free": float(self.arena.free_slabs),
            "bytes_uploaded_total": float(s["bytes_uploaded"]),
            "batch_splits_total": float(s["batch_splits"]),
            # mean across dispatch targets: the controller reasons about
            # "the device side" as one saturation fraction
            "busy_seconds_total": sum(busy) / max(1, len(busy)),
        }

    def set_streams(self, n: int) -> None:
        # growth wakes parked workers (they poll the active count);
        # shrink parks the highest-numbered streams after they drain
        # their in-flight windows
        self.active_streams = max(1, min(len(self.workers), int(n)))

    def set_inflight(self, n: int) -> None:
        self.inflight = max(1, min(self._max_inflight, int(n)))

    def grow_arena(self, k: int) -> int:
        before = self.arena.n_slabs
        n = self.arena.grow(int(k), self._max_arena_slabs)
        if n > before:
            flight.note_resident(
                "arena", (n - before) * self.arena.rows * self.arena.row_len
            )
        return n

    def _telemetry_probe(self) -> dict[str, float]:
        """In-flight pipeline state for the telemetry sampler: arena
        occupancy, queue depths, per-stream windows, link-byte and
        per-device busy counters. Called only from a sampler thread
        (a few times per second); every read is a lock-or-GIL snapshot."""
        sc = self.sc
        vals = {
            "secret.arena_free_slabs": float(self.arena.free_slabs),
            "secret.arena_slabs": float(self.arena.n_slabs),
            "secret.feed_queue_depth": float(self.in_q.qsize()),
            "secret.files_pending": float(len(self.states)),
            "secret.results_buffered": float(len(self.results)),
            "secret.confirm_inflight": float(self._confirm_inflight),
            "secret.bytes_uploaded_total": float(
                sc.stats.snapshot()["bytes_uploaded"]
            ),
            "secret.active_streams": float(self.active_streams),
            "secret.inflight_window": float(self.inflight),
        }
        for i, n in enumerate(self._stream_inflight):
            vals[f"secret.stream{i}.inflight"] = float(n)
        vals.update(sc._staged.busy.probe())
        return vals

    def close(self) -> None:
        # the controller stops FIRST: it mutates active_streams/inflight/
        # arena, and its final doc() must freeze before the snapshot below
        if self.controller is not None:
            self.controller.stop()
        self.ctx.remove_probe(self._telemetry_probe)
        self.stop.set()
        self.feeder.join(timeout=10.0)
        for w in self.workers:
            w.join(timeout=10.0)
        self.pool.shutdown(wait=False)
        # push the dedup store's write-behind tail (one final round trip)
        # so the NEXT scan — possibly another process — starts warm
        self.sc._hit_store.flush_writes(force=True)
        # slabs still parked in the dispatch queue after an early close
        while True:
            try:
                item = self.in_q.get_nowait()
            except queue.Empty:
                break
            if item is not None and item is not _ABORT:
                self.arena.release(item[0])
        flight.release_resident(
            "arena", self.arena.n_slabs * self.arena.rows * self.arena.row_len
        )
        # feed-path introspection for tests and bench debugging: on a
        # clean scan every slab is back in the arena (no leak into the
        # streaming-RSS budget) and acquires ≫ slabs proves reuse
        self.sc._last_feed_stats = {
            "arena_slabs": self.arena.n_slabs,
            "arena_free": self.arena.free_slabs,
            "arena_acquires": self.arena.acquires,
            "streams": len(self.workers),
        }
        # effective-knob record: what this scan actually ran with at the
        # end (controller-adapted or static) — tuning_snapshot() surfaces
        # it into bench reps, --metrics-out, and heartbeat lines
        ctl_summary = None
        if self.controller is not None:
            d = self.controller.doc()
            # summary only: the full decision log rides the ctx exports
            # (--trace-out instants, --metrics-out tuning block); this
            # snapshot goes into compact bench rep details
            ctl_summary = {
                "ticks": d.get("ticks", 0),
                "decisions": d.get("decisions", 0),
                "initial": d.get("initial"),
                "final": d.get("final"),
            }
        self.sc._last_tuning = {
            "feed_streams": self.active_streams,
            "inflight": self.inflight,
            "arena_slabs": self.arena.n_slabs,
            "controller": ctl_summary,
        }
        # wire-format accounting for THIS run: the `wire` block in
        # --metrics-out, the per-rep wire_compression_ratio in bench, and
        # the process-global gauge on GET /metrics. Compression-off scans
        # publish nothing (no block, no gauge registration) — the
        # zero-cost-when-off bar bench --smoke enforces
        if self.sc._codec is not None:
            from trivy_tpu.obs.metrics import REGISTRY

            d = self.sc.stats.snapshot()
            w = {k: d[k] - self._stats0[k] for k in (
                "bytes_compressed", "bytes_raw_equiv", "bytes_raw_fallback",
                "bytes_gated", "bytes_gated_binary", "chunks_gated_zero",
                "batches_compressed", "batches_raw_fallback",
            )}
            raw_equiv = w["bytes_raw_equiv"] + w["bytes_raw_fallback"]
            shipped = w["bytes_compressed"] + w["bytes_raw_fallback"]
            ratio = shipped / raw_equiv if raw_equiv else 1.0
            wire = {"compress": True, "compression_ratio": ratio, **w}
            self.sc._last_wire = wire
            if self.ctx is not None:
                self.ctx.wire = wire
            REGISTRY.gauge(
                "trivy_tpu_wire_compression_ratio",
                "Link bytes shipped per raw slab byte on the most recent "
                "compressed-feed scan (1.0 = raw)",
            ).set(ratio)

    # -- shared control -----------------------------------------------------

    def _aborted(self) -> bool:
        return (
            self.stop.is_set() or self.error is not None or self.degraded
        )

    def _put_slab(self, item) -> bool:
        while not self._aborted():
            try:
                self.in_q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _put_sentinel(self) -> None:
        while not self._aborted():
            try:
                self.in_q.put(None, timeout=0.2)
                return
            except queue.Full:
                continue

    def _get_work(self):
        while True:
            if self._aborted():
                return _ABORT
            try:
                return self.in_q.get(timeout=0.2)
            except queue.Empty:
                continue

    def _fail(self, err: BaseException) -> None:
        with self.cond:
            if self.error is None:
                self.error = err
            self.cond.notify_all()
        self.stop.set()
        if self.lic_gate is not None:
            self.lic_gate.degrade()

    def _degrade(self, cause: BaseException) -> None:
        """Last rung: move every file with unresolved device work onto the
        exact host confirm path (partial device results are discarded),
        once. The feeder notices ``degraded`` and routes the rest of the
        input stream straight to the host engine."""
        with self.lock:
            if self.degraded or self.error is not None:
                return
            self.degraded = True
            moved = [(i, self.states.pop(i)) for i in sorted(self.states)]
            self.row_waiters.clear()
        if self.lic_gate is not None:
            # device verdicts are incomplete from here on: the license
            # analyzer must classify everything it collected
            self.lic_gate.degrade()
        self.sc._note_degraded(self.ctx, cause)
        for fidx, st in moved:
            self._submit_host(fidx, st.path, st.data)
        with self.cond:
            self.cond.notify_all()

    # -- result plumbing ----------------------------------------------------

    def _acquire_slot(self) -> bool:
        while not (self.stop.is_set() or self.error is not None):
            if self.confirm_slots.acquire(timeout=0.2):
                with self.lock:
                    self._confirm_inflight += 1
                return True
        return False

    def _release_slot(self) -> None:
        with self.lock:
            self._confirm_inflight -= 1
        self.confirm_slots.release()

    def _set_result(self, fidx: int, value) -> None:
        with self.cond:
            self.results[fidx] = value
            self.cond.notify_all()

    def _confirm_task(self, st: _FileState) -> Secret:
        try:
            with obs.activate(self.ctx), self.ctx.span("secret.confirm"):
                return self.sc._confirm(st, self.prof)
        finally:
            self._release_slot()

    def _host_task(self, path: str, data: bytes) -> Secret:
        # degraded-mode rung: the exact host engine IS the parity oracle,
        # so fallback findings are byte-identical by definition
        try:
            with obs.activate(self.ctx), self.ctx.span("secret.host_fallback"):
                return self.sc.exact.scan_bytes(path, data)
        finally:
            self._release_slot()

    def _submit_confirm(self, fidx: int, st: _FileState) -> None:
        if not self._acquire_slot():
            return  # shutting down; nobody will wait on this result
        self._set_result(fidx, self.pool.submit(self._confirm_task, st))

    def _submit_host(self, fidx: int, path: str, data: bytes) -> None:
        if not self._acquire_slot():
            return
        self._set_result(fidx, self.pool.submit(self._host_task, path, data))

    def _apply_lic(self, segs, lic) -> None:
        """Fold one row's fused license verdict into the gate. ``lic`` is
        a tuple of hit BLOCK indices from the gram gate (usually empty),
        or None when the row's batch never ran it — a wanted file
        replaying an ungated cached row falls back to exact classification
        (gate.skip), never to a silent miss.

        Segment row-offsets are reconstructed from the packing layout
        (cumulative ``len + gap``, exactly how emit_pack laid them out) so
        a hit block flags only the file(s) it overlaps."""
        gate = self.lic_gate
        if gate is None:
            return
        if lic is None:
            for fidx, _, _ in segs:
                path = self.lic_paths.get(fidx)
                if path is not None:
                    gate.skip(path)
            return
        if not lic:
            return
        blk = self.sc._lic_block
        gap = self.sc.overlap
        chunk_len = self.sc.chunk_len
        off = 0
        for i, (fidx, ws, we) in enumerate(segs):
            seg_len = we - ws
            if len(segs) == 1:
                lo, hi = 0, chunk_len  # whole-row segment (big-file chunk)
            else:
                lo, hi = off, off + seg_len
                off += seg_len + gap
            path = self.lic_paths.get(fidx)
            if path is None:
                continue
            # a hit block overlapping [lo, hi) flags this segment's file;
            # boundary-straddling blocks flag both neighbors (FP-only)
            if any(b * blk < hi and (b + 1) * blk > lo for b in lic):
                gate.flag(path)

    def _apply_hits(self, batch: list) -> None:
        """Credit resolved rows to their file segments; ``batch`` is
        ``[(segs, hit_rules, cand_rules, nfa_ran, lic)]`` (the row-verdict
        schema of the dedup cache). Every row hit applies to every segment
        — cross-segment false candidates are discarded by the exact
        confirm. Files whose last pending row resolved here go to the
        confirm pool (the semaphore is taken OUTSIDE the pipeline lock so
        a full confirm queue stalls only the calling thread, not
        resolution bookkeeping on other streams)."""
        sc = self.sc
        prof = self.prof
        if prof is not None:
            rule_ids = sc.compiled.rule_ids
            for _, hit_rules, cand_rules, _, _ in batch:
                # one logical device hit per (row, rule) — dedup-cache and
                # coalesced rows count too: they cost a confirm all the same
                for r in hit_rules:
                    prof.gate_hit(rule_ids[r])
                for r in cand_rules:
                    prof.prefilter_hit(rule_ids[r])
        guarded_anchored = sc._guarded_anchored
        ready: list[tuple[int, _FileState]] = []
        with self.lock:
            for segs, hit_rules, cand_rules, nfa_ran, _ in batch:
                for fidx, ws, we in segs:
                    st = self.states.get(fidx)
                    if st is None:
                        continue  # already moved to the host path
                    for r in hit_rules:
                        st.rules.setdefault(r, []).append((ws, we))
                    if st.cand is not None:
                        st.cand.update(cand_rules)
                        if not nfa_ran:
                            st.unchecked.update(guarded_anchored)
                for fidx, _, _ in segs:
                    st = self.states.get(fidx)
                    if st is None:
                        continue
                    st.pending -= 1
                    if st.pending == 0:
                        del self.states[fidx]
                        ready.append((fidx, st))
        for segs, _, _, _, lic in batch:
            self._apply_lic(segs, lic)
        for fidx, st in ready:
            self._submit_confirm(fidx, st)

    def _resolve(
        self,
        batch_hits: np.ndarray | None,
        batch_meta: list,
        pre: np.ndarray | None = None,
        lic_arr: np.ndarray | None = None,
        nfa_ran: bool = True,
        lic_ran: bool = False,
    ) -> None:
        """Fold one fetched batch into file state. ``batch_hits`` is the
        anchored/full matcher output (None when the batch skipped it),
        ``pre`` the prefilter candidate mask, ``lic_arr`` the license gate
        flags; all sliced to the live rows here (rows past
        ``len(batch_meta)`` are bucket padding)."""
        sc = self.sc
        n = len(batch_meta)
        by_row: dict[int, list[int]] = {}
        cand_by_row: dict[int, list[int]] = {}
        if pre is not None:
            pre = np.asarray(pre[:n], dtype=bool)
            rows, ridx = np.nonzero(pre)
            for row, r in zip(rows.tolist(), ridx.tolist()):
                cand_by_row.setdefault(row, []).append(r)
            # keyword-lane hits come straight from the prefilter mask (the
            # matcher no longer carries that lane); anchored hits from the
            # matcher when it ran
            kw_cols = sc._kw_lane_cols
            hits = (
                np.array(batch_hits[:n], dtype=bool, copy=True)
                if batch_hits is not None
                else np.zeros((n, sc.compiled.num_rules), dtype=bool)
            )
            if len(kw_cols):
                hits[:, kw_cols] |= pre[:, kw_cols]
            hit_rows = int(pre.any(axis=1).sum())
            sc.stats.add(rows_prefiltered=n, rows_prefilter_hit=hit_rows)
            if self.prof is not None:
                self.prof.prefilter_rows(n, 0 if nfa_ran else n, hit_rows)
        else:
            hits = np.asarray(batch_hits[:n])
        rows, ridx = np.nonzero(hits)
        for row, r in zip(rows.tolist(), ridx.tolist()):
            by_row.setdefault(row, []).append(r)
        lic_by_row: dict[int, tuple[int, ...]] = {}
        if lic_ran and lic_arr is not None:
            lic_arr = np.asarray(lic_arr[:n], dtype=bool)
            rows, blks = np.nonzero(lic_arr)
            for row, b in zip(rows.tolist(), blks.tolist()):
                lic_by_row.setdefault(row, ())
                lic_by_row[row] = lic_by_row[row] + (b,)
            sc.stats.add(
                license_rows_gated=n,
                license_rows_flagged=int(lic_arr.any(axis=1).sum()),
            )
        apply: list = []
        for row, (key, segs, _) in enumerate(batch_meta):
            hit_rules = tuple(by_row.get(row, ()))
            cand_rules = tuple(cand_by_row.get(row, ()))
            lic = lic_by_row.get(row, ()) if lic_ran else None
            verdict = (hit_rules, cand_rules, nfa_ran, lic)
            apply.append((segs,) + verdict)
            if key is not None:
                self.sc._hit_put(key, verdict)
                with self.lock:
                    waiting = self.row_waiters.pop(key, ())
                for w in waiting:
                    apply.append((w,) + verdict)
        # write-behind flush: one pipelined backend round trip per batch
        # (no-op without a persistent backend / below the batch threshold)
        self.sc._hit_store.flush_writes()
        self._apply_hits(apply)

    # -- transfer-stream workers --------------------------------------------

    def _worker(self, wid: int) -> None:
        """One transfer stream: place slabs once, run the fused device
        stages against the resident rows, keep a bounded in-flight window
        (double buffering), fetch the oldest, resolve inline.

        Per-batch staging: the PREFILTER (and, when fused, the license
        gram gate) dispatches immediately with the upload; its fetch is
        the batch's first sync point and decides whether the anchored
        matcher runs at all — a batch with no candidate for any anchored
        rule (and no unguarded anchored rules in the ruleset) resolves
        from the prefilter mask alone, skipping the expensive kernel AND
        its host confirms. The slab releases only after the LAST stage
        reading the resident input has fetched (device_put may alias host
        memory on the CPU backend — an earlier release would let the
        feeder refill bytes a later-stage kernel still reads).

        Per-batch failure ladder as in README "Robustness": re-dispatch up
        to ``batch_retries`` times (under round-robin the retry lands on
        the next healthy device and the breaker hears about it),
        OOM-shaped errors split the batch in half, and only an exhausted
        ladder (or every device circuit-broken) escalates to the
        scan-level host fallback.

        Stall instrumentation (all on the spawning scan's context):
        ``secret.feed_wait`` is time blocked on the host feed
        (feed-starved), ``secret.dispatch`` the enqueue/transfer handoff
        (upload-bound), ``secret.prefilter`` the prefilter fetch,
        ``secret.device_wait`` the blocking matcher fetch (device-bound)."""
        from trivy_tpu.parallel.mesh import DevicesUnavailable

        sc = self.sc
        ctx = self.ctx
        staged = sc._staged
        use_pf = staged.has_stage("prefilter")
        lic_gate = self.lic_gate
        prof = self.prof
        stats = sc.stats
        chunk_len = sc.chunk_len
        # (dev_input, meta, batch, slab_id, device_idx, retries, handles);
        # slab_id is None for retry copies, which own their arrays outright
        pending: deque = deque()

        def rebatch(batch: np.ndarray, meta: list) -> np.ndarray:
            """Fresh bucket-padded copy of a failed batch's live rows —
            the source slab is released right after, so retries never
            alias arena memory the feeder may refill."""
            n = next(b for b in sc._buckets if b >= len(meta))
            out = np.zeros((n, chunk_len), dtype=np.uint8)
            out[: len(meta)] = batch[: len(meta)]
            return out

        def recover(batch, meta, slab_id, retries, err) -> list:
            """Ladder decision for one failed batch: work items to
            re-dispatch, or raise when the ladder is exhausted. Always
            ends the source slab's ownership.

            A compressed batch degrades to raw rows FIRST (the host
            reference decoder, byte-identical to the device kernel by the
            codec fuzz contract): every rung of the ladder — whole-batch
            retry, OOM halves, host fallback — then runs on plain rows,
            so a decoder-side failure can never loop through the codec."""
            if isinstance(batch, CompressedSlab):
                try:
                    batch = sc._codec.decode_slab(batch)
                except Exception as dec_err:
                    # an undecodable frame is an encoder bug, not a device
                    # fault: no retry can fix it — escalate to the exact
                    # host path, which rereads original file bytes
                    logger.warning(
                        "compressed batch unrecoverable after device error "
                        "(%s); decode failed: %s", err, dec_err,
                    )
                    if slab_id is not None:
                        self.arena.release(slab_id)
                    raise _DeviceFailed(err)
            if isinstance(err, DevicesUnavailable):
                if slab_id is not None:
                    self.arena.release(slab_id)
                raise _DeviceFailed(err)  # no device left to retry on
            if _is_oom(err) and len(meta) > 1:
                stats.add(batch_splits=1)
                if self.enabled:
                    ctx.count("secret.batch_splits")
                flight.record(
                    "oom", "secret.batch_split",
                    {"rows": len(meta), "error": str(err)},
                )
                logger.warning(
                    "device OOM on a %d-row batch (%s); splitting and "
                    "re-dispatching the halves", len(meta), err,
                )
                mid = (len(meta) + 1) // 2
                halves = [
                    (rebatch(batch[:mid], meta[:mid]), meta[:mid], None, retries),
                    (rebatch(batch[mid:], meta[mid:]), meta[mid:], None, retries),
                ]
                if slab_id is not None:
                    self.arena.release(slab_id)
                return halves
            if retries < sc._batch_retries:
                stats.add(batch_retries=1)
                if self.enabled:
                    ctx.count("secret.batch_retries")
                flight.record(
                    "retry", "secret.batch_retry",
                    {"rows": len(meta), "attempt": retries + 1,
                     "error": str(err)},
                )
                logger.warning(
                    "device error on a %d-row batch (retry %d/%d): %s",
                    len(meta), retries + 1, sc._batch_retries, err,
                )
                fresh = rebatch(batch, meta)
                if slab_id is not None:
                    self.arena.release(slab_id)
                return [(fresh, meta, None, retries + 1)]
            if slab_id is not None:
                self.arena.release(slab_id)
            raise _DeviceFailed(err)

        def want_lic(meta) -> bool:
            if lic_gate is None:
                return False
            lp = self.lic_paths
            return any(
                fidx in lp for _, segs, _ in meta for fidx, _, _ in segs
            )

        def dispatch_batch(batch, meta, slab_id, retries) -> None:
            work = [(batch, meta, slab_id, retries)]
            while work:
                b, m, sid, r = work.pop()
                placed = False
                didx = None
                try:
                    if isinstance(b, CompressedSlab):
                        # ship the wire frame, expand on device ahead of
                        # every other stage; the decompressed rows are
                        # the resident array the stages read, so
                        # downstream dispatch is shape-identical to the
                        # raw path. The frame placement stays in the
                        # upload bucket (it IS the link transfer); only
                        # the decode launch is codec time
                        with ctx.span("secret.dispatch"):
                            parts, didx = staged.put_parts(b.arrays())
                            placed = True
                        with ctx.span("secret.decompress"):
                            dev = staged.run("decompress", parts, didx)
                        h: dict = {}
                    else:
                        with ctx.span("secret.dispatch"):
                            dev, didx = staged.put(b)
                            placed = True
                        h = {}
                    with ctx.span("secret.dispatch"):
                        if use_pf:
                            h["pre"] = staged.run("prefilter", dev, didx)
                        else:
                            h["match"] = staged.run("match", dev, didx)
                        if want_lic(m):
                            h["lic"] = staged.run("license", dev, didx)
                except Exception as e:
                    # dispatch-time failure (breaker already notified by
                    # the placement layer); walk the ladder. A batch that
                    # placed but failed at stage launch closes its busy
                    # interval here (no fetch will ever report it)
                    if placed:
                        staged.busy.end(didx)
                    work.extend(recover(b, m, sid, r, e))
                    continue
                pending.append((dev, m, b, sid, didx, r, h))

        def fetch_oldest() -> None:
            dev, meta, batch, sid, didx, retries, h = pending.popleft()
            try:
                faults.check(
                    "device.fetch", key=f"d{didx if didx is not None else 0}"
                )
                t0 = time.perf_counter() if prof is not None else 0.0
                pre = None
                arr = None
                nfa_ran = True
                if use_pf:
                    with ctx.span("secret.prefilter"):
                        pre = np.asarray(h["pre"])
                    live = pre[: len(meta)]
                    need_nfa = sc._has_unguarded_anchored or bool(
                        live[:, sc._guarded_anchored_cols].any()
                        if len(sc._guarded_anchored_cols)
                        else False
                    )
                    if need_nfa:
                        with ctx.span("secret.dispatch"):
                            mh = staged.run("match", dev, didx)
                        with ctx.span("secret.device_wait"):
                            arr = np.asarray(mh)
                    else:
                        nfa_ran = False
                        stats.add(
                            rows_nfa_skipped=len(meta),
                            batches_nfa_skipped=1,
                        )
                        if self.enabled:
                            ctx.count("secret.rows_nfa_skipped", len(meta))
                else:
                    with ctx.span("secret.device_wait"):
                        arr = np.asarray(h["match"])
                lic_ran = "lic" in h
                lic_arr = np.asarray(h["lic"]) if lic_ran else None
                # every stage that reads the resident input has now fetched
                # — only here is the slab provably free of zero-copy device
                # views (jax.device_put may ALIAS host memory on the CPU
                # backend, so "the transfer finished" is not enough while a
                # later-stage kernel could still read the input)
                if sid is not None:
                    self.arena.release(sid)
                    sid = None
                if prof is not None:
                    # per-bucket dispatch cost: the bucket is the padded
                    # batch shape (the compile-once ladder rung), rows are
                    # the live rows it carried
                    prof.bucket_dispatch(
                        batch.shape[0], len(meta), time.perf_counter() - t0
                    )
            except Exception as e:
                staged.record_result(didx, False)
                for item in recover(batch, meta, sid, retries, e):
                    dispatch_batch(*item)
                return
            staged.record_result(didx, True)
            if sid is not None:
                self.arena.release(sid)
            if not self.degraded:
                self._resolve(
                    arr, meta, pre=pre, lic_arr=lic_arr,
                    nfa_ran=nfa_ran, lic_ran=lic_ran,
                )

        def release_pending() -> None:
            while pending:
                _, _, _, sid, didx, _, _ = pending.popleft()
                if sid is not None:
                    self.arena.release(sid)
                # close the dropped batch's busy interval: a degraded scan
                # runs on for minutes, and an unclosed interval would pin
                # the dead device's busy_ratio gauge at 1.0 the whole time
                staged.busy.end(didx)

        with obs.activate(ctx):
            try:
                while True:
                    if wid >= self.active_streams:
                        # parked by the online controller: drain this
                        # stream's in-flight window, then idle until
                        # unparked, shutdown, or end of input — a parked
                        # stream takes no new work, which is exactly how
                        # "shrink streams" reduces link concurrency
                        while pending and not self._aborted():
                            fetch_oldest()
                            self._stream_inflight[wid] = len(pending)
                        if self._aborted() or self.feed_done.is_set():
                            break
                        self.stop.wait(0.1)
                        continue
                    with ctx.span("secret.feed_wait"):
                        item = self._get_work()
                    if item is _ABORT:
                        break
                    if item is None:
                        # end-of-input sentinel: re-post it so the next
                        # active worker sees it too (one sentinel cascades
                        # through however many streams are active; parked
                        # workers exit on feed_done instead)
                        self._put_sentinel()
                        break
                    slab_id, batch, meta = item
                    dispatch_batch(batch, meta, slab_id, 0)
                    self._stream_inflight[wid] = len(pending)
                    while len(pending) >= self.inflight:
                        fetch_oldest()
                        self._stream_inflight[wid] = len(pending)
                while pending and not self._aborted():
                    fetch_oldest()
                    self._stream_inflight[wid] = len(pending)
            except _DeviceFailed as e:
                release_pending()
                if sc._host_fallback:
                    self._degrade(e.cause)
                else:
                    self._fail(e.cause)
            except BaseException as e:  # unexpected: surface it loudly
                release_pending()
                self._fail(e)
            finally:
                release_pending()
                self._stream_inflight[wid] = 0
                if self.degraded:
                    # return whatever the feeder parked before it noticed
                    while True:
                        try:
                            item = self.in_q.get_nowait()
                        except queue.Empty:
                            break
                        if item is not None and item is not _ABORT:
                            self.arena.release(item[0])

    # -- feeder -------------------------------------------------------------

    def _feed_guarded(self) -> None:
        with obs.activate(self.ctx):
            try:
                self._feed()
            except BaseException as e:
                self._fail(e)

    def _feed(self) -> None:
        sc = self.sc
        ctx = self.ctx
        enabled = self.enabled
        stats = sc.stats
        chunk_len = sc.chunk_len
        B = sc.batch_size
        dedup = sc._dedup
        # fused-license scans use a disjoint digest namespace: their cached
        # row verdicts carry a license-gate bit that plain scans never set
        fp_key = (
            sc.ruleset_fingerprint
            if self.lic_gate is None
            else sc.ruleset_fingerprint + b":lic"
        )
        use_pf = sc.prefilter_on
        lic_gate = self.lic_gate
        # widest gram/anchor byte window the device gate provably sees
        # interior to some chunk (licensing/fused.py host patch covers the
        # rest)
        lic_span_bound = sc.overlap - 2
        gap = sc.overlap
        pack_max = chunk_len - gap
        blake2b = hashlib.blake2b

        def lic_register(fidx: int, path: str, data: bytes) -> None:
            """Fused pass bookkeeping for one file entering the device
            feed: coverage + the host wide-window patch, and the fidx ->
            path mapping row resolution flags against."""
            if lic_gate is not None and lic_gate.wants(path):
                self.lic_paths[fidx] = path
                lic_gate.feed_file(path, data, lic_span_bound)

        def lic_skip(path: str) -> None:
            """This path's bytes will not (all) ride the device pass —
            the license analyzer must classify it itself."""
            if lic_gate is not None and lic_gate.wants(path):
                lic_gate.skip(path)

        persist_on = dedup and sc._hit_store.backend is not None
        # compressed feed on -> the zero gate is on (all-zero rows resolve
        # through a forced dedup key instead of crossing the link again)
        zero_gate = sc._codec is not None
        slab_id: int | None = None
        slab: np.ndarray | None = None
        used = 0
        # per-row feed metadata:
        # (digest | None, [(fidx, win_start, win_end)], corpus_bytes)
        meta: list[tuple[bytes | None, list[tuple[int, int, int]], int]] = []
        # slab rows awaiting the bulk strided gather from the current file
        copy_rows: list[int] = []
        copy_starts: list[int] = []
        copy_win = None  # row_windows view over the current file's bytes
        pack_pending: list[tuple[int, bytes]] = []
        pack_len = 0
        total = 0

        class _FeedAbort(Exception):
            pass

        def flush_copies() -> None:
            nonlocal copy_rows, copy_starts
            if copy_rows:
                # ONE vectorized gather for every full row the current
                # file placed in this slab
                slab[np.asarray(copy_rows)] = copy_win[np.asarray(copy_starts)]
                copy_rows = []
                copy_starts = []

        def ensure_slab() -> None:
            nonlocal slab_id, slab, used
            if slab is None:
                with ctx.span("secret.arena_wait"):
                    got = self.arena.acquire(self._aborted)
                if got is None:
                    raise _FeedAbort
                slab_id, slab = got
                used = 0

        def register_state(fidx: int, st: _FileState) -> bool:
            """False when the scan degraded concurrently — the caller
            must route the file to the host path instead (a state added
            after :meth:`_degrade` swept the table would never resolve)."""
            with self.lock:
                if self.degraded:
                    return False
                self.states[fidx] = st
                return True

        def route_row(key, segs, nbytes) -> bool:
            """True when the row resolved without an upload: served from
            the hit cache or coalesced onto an identical in-flight row."""
            if key is None:
                return False
            cached = sc._hit_get(key)
            if cached is not None:
                stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                if enabled:
                    ctx.count("secret.bytes_dedup_hit", nbytes)
                self._apply_hits([(segs,) + cached])
                return True
            with self.lock:
                waiting = self.row_waiters.get(key)
                if waiting is not None:
                    waiting.append(segs)
                    coalesced = True
                else:
                    self.row_waiters[key] = []
                    coalesced = False
            if coalesced:
                stats.add(chunks_dedup_hit=1, bytes_dedup_hit=nbytes)
                if enabled:
                    ctx.count("secret.bytes_dedup_hit", nbytes)
            return coalesced

        def warm_filter() -> None:
            """Persistent-store lookup for the assembled slab's rows: ONE
            pipelined backend round trip per batch (never per row). Rows
            whose verdict is already persisted resolve right here — no
            upload, no kernel — and the slab compacts over the survivors
            with one vectorized gather."""
            nonlocal meta
            keys = [k for k, _, _ in meta if k is not None]
            if not keys:
                return
            with ctx.span("secret.warm_hit"):
                found = sc._hit_store.lookup_batch(keys)
            if not found:
                return
            live: list[int] = []
            warm_apply: list = []
            warm_rows = 0
            warm_bytes = 0
            for i, (k, segs, nbytes) in enumerate(meta):
                v = found.get(k) if k is not None else None
                if v is None:
                    live.append(i)
                    continue
                warm_rows += 1
                warm_bytes += nbytes
                warm_apply.append((segs,) + v)
                with self.lock:
                    waiting = self.row_waiters.pop(k, ())
                for w in waiting:
                    warm_apply.append((w,) + v)
            if not warm_apply:
                return
            # chunks_uploaded was counted at assembly; correct it so the
            # dedup-hit-rate denominators stay exact
            stats.add(
                chunks_dedup_hit=warm_rows, bytes_dedup_hit=warm_bytes,
                chunks_warm_hit=warm_rows, bytes_warm_hit=warm_bytes,
                chunks_uploaded=-warm_rows,
            )
            if enabled:
                ctx.count("secret.bytes_dedup_hit", warm_bytes)
                ctx.count("secret.bytes_warm_hit", warm_bytes)
            self._apply_hits(warm_apply)
            if live:
                slab[: len(live)] = slab[np.asarray(live)]
            meta = [meta[i] for i in live]

        def compress_slab(n: int):
            """Try to compress the assembled slab's live rows into a wire
            frame riding a SECOND arena slab (the wire stays in pinned,
            reused memory and inherits arena backpressure). Returns the
            dispatch-queue item ``(dst_slab_id, CompressedSlab, meta)``,
            or None for the raw fallback: the batch can't beat the
            min-ratio wire budget, or the encoder errored (degrade to raw
            is the codec's failure contract, never a failed scan)."""
            dst_id = None
            try:
                with ctx.span("secret.compress"):
                    plan = sc._codec.plan(slab[: len(meta)])
                    total = plan.total()
                    rung = next(
                        (r for r in sc._wire_rungs[n] if r >= total), None
                    )
                    if rung is None:
                        return None  # doesn't pay — ship the raw slab
                    got = self.arena.acquire(self._aborted)
                    if got is None:
                        raise _FeedAbort
                    dst_id, dst = got
                    cs = sc._codec.emit(plan, n, rung, dst.reshape(-1))
            except _FeedAbort:
                raise
            except Exception as e:
                logger.warning(
                    "slab compression failed (%s: %s); shipping raw",
                    type(e).__name__, e,
                )
                if dst_id is not None:
                    self.arena.release(dst_id)
                return None
            wire = rung + cs.frame_bytes()
            bin_rows = int(plan.binary.sum())
            stats.add(
                bytes_uploaded=wire,
                bytes_compressed=wire,
                bytes_raw_equiv=n * chunk_len,
                bytes_gated_binary=bin_rows * chunk_len,
                batches_compressed=1,
            )
            if enabled:
                ctx.count("secret.bytes_uploaded", wire)
                ctx.count("secret.bytes_compressed", wire)
                if bin_rows:
                    ctx.count(
                        "secret.bytes_gated_binary", bin_rows * chunk_len
                    )
            return (dst_id, cs, meta)

        def flush() -> None:
            nonlocal slab_id, slab, used, meta
            flush_copies()
            if persist_on and meta:
                warm_filter()
            if not meta:
                # empty slab: padding-only batches are never sent (and a
                # fully-warm slab resolved above with no upload at all)
                if slab is not None:
                    self.arena.release(slab_id)
                    slab_id = None
                    slab = None
                    used = 0
                # a fully-warm flush is still a batch boundary: the pack
                # staleness bound below must hold on warm streaming scans
                # too, or a lone small file would stall in-order emission
                # until end-of-input
                if pack_pending:
                    emit_pack()
                return
            n = next(b for b in sc._buckets if b >= len(meta))
            item = None
            if sc._codec is not None:
                item = compress_slab(n)
            if item is None:
                # raw slab (codec off, fallback, or incompressible batch)
                stats.add(bytes_uploaded=n * chunk_len)
                if sc._codec is not None:
                    stats.add(
                        bytes_raw_fallback=n * chunk_len,
                        batches_raw_fallback=1,
                    )
                    if enabled:
                        ctx.count(
                            "secret.bytes_raw_fallback", n * chunk_len
                        )
                if enabled:
                    ctx.count("secret.bytes_uploaded", n * chunk_len)
                item = (slab_id, slab[:n], meta)
            else:
                # the wire frame rides its own slab; the source slab is
                # done the moment the encoder copied out of it
                self.arena.release(slab_id)
            if enabled:
                ctx.sample("secret.queue_depth", self.in_q.qsize())
            ok = self._put_slab(item)
            if not ok:
                self.arena.release(item[0])
            slab_id = None
            slab = None
            used = 0
            meta = []
            if not ok:
                raise _FeedAbort
            # bound pack-row staleness to one batch: a lone small file must
            # not sit in pack_pending while big files stream past it — its
            # unresolved state would stall in-order emission and let results
            # accumulate unbounded on a streaming scan. The partial pack row
            # rides the next batch instead (re-entry is shallow: the fresh
            # meta holds one row, far below batch_size, so no second flush)
            if pack_pending:
                emit_pack()

        def emit_pack() -> None:
            nonlocal pack_len, used
            if not pack_pending:
                return
            items = list(pack_pending)
            pack_pending.clear()
            pack_len = 0
            key = None
            # the zero gate extends to single-file pack rows (a tree of
            # zero-filled placeholder files): same forced-key trick as
            # feed_big's chunk rows, same digest domain
            single_zero = (
                zero_gate
                and len(items) == 1
                and not any(items[0][1])
            )
            if dedup or single_zero:
                if len(items) == 1:
                    # single-segment row == plain chunk-row layout: share the
                    # plain digest domain so it dedups across both paths
                    key = blake2b(
                        items[0][1], digest_size=16, key=fp_key
                    ).digest()
                else:
                    h = blake2b(
                        digest_size=16, key=fp_key, person=b"packed-row"
                    )
                    for _, d in items:
                        h.update(len(d).to_bytes(4, "little"))
                        h.update(d)
                    key = h.digest()
            segs = [(fidx, 0, len(d)) for fidx, d in items]
            nbytes = sum(len(d) for _, d in items)
            stats.add(chunks=1)
            if route_row(key, segs, nbytes):
                if single_zero:
                    stats.add(bytes_gated=nbytes, chunks_gated_zero=1)
                    if enabled:
                        ctx.count("secret.bytes_gated", nbytes)
                return
            ensure_slab()
            row = slab[used]
            row[:] = 0  # zero guard gaps + stale tail (slabs are reused)
            off = 0
            for _, d in items:
                row[off : off + len(d)] = np.frombuffer(d, dtype=np.uint8)
                off += len(d) + gap
            meta.append((key, segs, nbytes))
            used += 1
            stats.add(chunks_uploaded=1)
            if len(segs) > 1:
                stats.add(
                    rows_packed=1, files_packed=len(segs), bytes_packed=nbytes
                )
                if enabled:
                    ctx.count("secret.bytes_packed", nbytes)
            if used == B:
                flush()

        def add_small(fidx: int, data: bytes) -> None:
            # small-file packing: files below a row's size accumulate and
            # share one row, separated by >=span zero gaps (see module
            # docstring for why packing cannot suppress a real match)
            nonlocal pack_len
            if pack_len and pack_len + gap + len(data) > chunk_len:
                emit_pack()
            pack_pending.append((fidx, data))
            pack_len += (gap if pack_len else 0) + len(data)

        def feed_big(fidx: int, path: str, data: bytes) -> None:
            nonlocal used, copy_win
            starts = chunk_spans(len(data), chunk_len, sc.overlap)
            if not register_state(
                fidx,
                _FileState(
                    path=path, data=data, pending=len(starts),
                    cand=set() if use_pf else None,
                ),
            ):
                lic_skip(path)
                self._submit_host(fidx, path, data)
                return
            lic_register(fidx, path, data)
            arr = np.frombuffer(data, dtype=np.uint8)
            n = arr.size
            stats.add(bytes_in=len(data), chunks=len(starts))
            copy_win = row_windows(arr, chunk_len)
            uploaded = 0
            for s in starts:
                end = min(s + chunk_len, n)
                # zero gate (compressed feed's "never ship unscannable
                # bytes"): all-zero rows — sparse images, zero pages —
                # get a forced dedup key even with dedup off, so the
                # first one ships (possibly compressed 8x) and every
                # other resolves through the ordinary dedup/coalesce
                # machinery. Soundness-free by construction: the row
                # still rides the real verdict path once, so a ruleset
                # that somehow matches NUL runs keeps its findings
                is_zero = zero_gate and not arr[s:end].any()
                key = (
                    blake2b(arr[s:end], digest_size=16, key=fp_key).digest()
                    if dedup or is_zero
                    else None
                )
                segs = [(fidx, s, s + chunk_len)]
                if route_row(key, segs, end - s):
                    if is_zero:
                        stats.add(
                            bytes_gated=end - s, chunks_gated_zero=1
                        )
                        if enabled:
                            ctx.count("secret.bytes_gated", end - s)
                    continue
                ensure_slab()
                if end - s == chunk_len:
                    copy_rows.append(used)
                    copy_starts.append(s)
                else:
                    # short tail row: copy, then zero the stale remainder
                    slab[used, : end - s] = arr[s:end]
                    slab[used, end - s :] = 0
                meta.append((key, segs, end - s))
                used += 1
                uploaded += 1
                if used == B:
                    flush()
            flush_copies()  # the view dies with this file's scope
            copy_win = None
            if uploaded:
                stats.add(chunks_uploaded=uploaded)

        feed_ok = True
        try:
            for fidx, (path, data) in enumerate(self.files):
                total = fidx + 1
                if self.stop.is_set() or self.error is not None:
                    total -= 1  # not processed; the generator is closing
                    break
                if self.degraded:
                    # device path is gone: route straight to the exact host
                    # engine under the same confirm backpressure (files
                    # already swept by _degrade keep their host results)
                    pack_pending.clear()
                    lic_skip(path)
                    self._submit_host(fidx, path, data)
                    continue
                try:
                    with ctx.span("secret.assemble"):
                        if sc.exact.allow_path(path):
                            # path-level global allowlist: skip the whole
                            # file (ref: scanner.go:388-392) — no device work
                            lic_skip(path)
                            self._set_result(fidx, Secret(file_path=path))
                        elif not data:
                            # empty file: nothing for the device to match —
                            # resolve host-side immediately (host-lane rules
                            # still run there); zero bytes means the fused
                            # license gate misses nothing either
                            if lic_gate is not None and lic_gate.wants(path):
                                lic_gate.cover(path)
                            self._submit_confirm(
                                fidx,
                                _FileState(
                                    path=path, data=data, pending=0,
                                    cand=set() if use_pf else None,
                                ),
                            )
                        elif sc._pack_small and len(data) <= pack_max:
                            stats.add(bytes_in=len(data))
                            if register_state(
                                fidx,
                                _FileState(
                                    path=path, data=data, pending=1,
                                    cand=set() if use_pf else None,
                                ),
                            ):
                                lic_register(fidx, path, data)
                                add_small(fidx, data)
                            else:
                                lic_skip(path)
                                self._submit_host(fidx, path, data)
                        else:
                            feed_big(fidx, path, data)
                except _FeedAbort:
                    # mid-file abort: a registered state was already swept
                    # onto the host path by _degrade; on plain shutdown the
                    # generator is closing and nobody waits on this file
                    if not self.degraded:
                        break
            if not self._aborted():
                try:
                    emit_pack()  # flush the partial pack row
                    flush()  # dispatch the final partial slab
                except _FeedAbort:
                    pass
        except BaseException:
            # do NOT publish `total` on a failed feed: emission must see
            # the error (set by _feed_guarded), not a truncated-but-
            # "complete" input count that would silently swallow it
            feed_ok = False
            raise
        finally:
            if slab is not None:
                # an unflushed (empty or aborted) slab goes straight back:
                # padding rows never reach the dispatch queue or dedup keys
                self.arena.release(slab_id)
            with self.cond:
                if feed_ok:
                    self.total = total
                self.cond.notify_all()
            # end-of-input: parked workers exit on feed_done; ONE sentinel
            # cascades through the active workers (each re-posts it before
            # exiting), so the count stays right however many streams the
            # online controller parked or woke mid-scan
            self.feed_done.set()
            self._put_sentinel()
