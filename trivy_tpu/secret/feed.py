"""Async feed-path primitives for the TPU secret scanner.

The e2e ceiling of the secret pipeline is the host→device link, not the
kernel (BENCH_r05: kernel ~900 MB/s, link ~10 MB/s serialized). Raising it
needs three host-side properties, and this module supplies the two
data-structure halves (the thread topology lives in
:mod:`trivy_tpu.secret.tpu_scanner`):

- **ChunkArena** — a fixed pool of preallocated, reusable row slabs
  (``[batch, chunk_len]`` uint8). Slabs are acquired by the batch
  assembler, handed through the dispatch queue to a transfer stream, and
  released only after the device fetch completes, so a slab can never be
  refilled while a transfer may still be reading it (the CPU backend's
  zero-copy aliasing and the axon tunnel's transfer journal both care).
  The pool bound doubles as feed backpressure: when every slab is in
  flight the assembler blocks instead of growing RSS — the equivalent of
  the reference's bounded channel between walker goroutines and workers.
  Addresses are stable for the life of a scan ("pinned" in the transfer
  sense: the tunnel/PJRT layer sees the same host buffers batch after
  batch).

- **FileStream** — a byte-bounded handoff queue that turns a push-style
  producer (the secret analyzer's ``collect()`` during the artifact walk)
  into the pull-style iterable ``scan_files`` consumes, so file reads and
  device scanning overlap instead of alternating in 64 MB bursts. The
  byte bound is the walk-side backpressure: a stalled device pipeline
  blocks the walk at a fixed buffered-bytes budget instead of buffering
  the tree.

Batch assembly itself is vectorized in the scanner: a large file's full
rows are gathered into a slab with ONE strided-fancy-index copy
(``sliding_window_view(data)[starts]``) instead of a Python loop of
per-row slice copies, and counters accumulate per file, not per row.
"""

from __future__ import annotations

import threading
from collections import deque
from collections.abc import Callable, Iterator

import numpy as np

__all__ = ["ChunkArena", "FileStream", "row_windows"]


def row_windows(arr: np.ndarray, row_len: int):
    """All ``row_len`` windows of a 1-D uint8 array as a zero-copy view,
    or None when the array is shorter than one row. Fancy-indexing the
    view with a list of chunk starts gathers every full row of a file in
    a single C-level copy."""
    if arr.size < row_len:
        return None
    from numpy.lib.stride_tricks import sliding_window_view

    return sliding_window_view(arr, row_len)


class ChunkArena:
    """Fixed pool of reusable ``[rows, row_len]`` uint8 slabs.

    ``acquire`` blocks while every slab is in flight (bounded feed);
    ``release`` returns a slab after its transfer is provably finished.
    ``acquire`` takes an ``abort`` predicate so a shutting-down or
    degrading pipeline can stop waiting instead of deadlocking on slabs
    that will never come back.
    """

    def __init__(self, n_slabs: int, rows: int, row_len: int):
        if n_slabs < 1:
            raise ValueError("ChunkArena needs at least one slab")
        self._bufs = [
            np.zeros((rows, row_len), dtype=np.uint8) for _ in range(n_slabs)
        ]
        self._free: deque[int] = deque(range(n_slabs))
        self._cond = threading.Condition()
        self.n_slabs = n_slabs
        self.rows = rows
        self.row_len = row_len
        self.acquires = 0  # lifetime acquisitions: reuse proof for tests

    def acquire(
        self, abort: Callable[[], bool] | None = None, poll: float = 0.2
    ) -> tuple[int, np.ndarray] | None:
        """``(slab_id, slab)`` of a free slab, or None once ``abort()``
        turns true while waiting."""
        with self._cond:
            while not self._free:
                if abort is not None and abort():
                    return None
                self._cond.wait(poll)
            i = self._free.popleft()
            self.acquires += 1
            return i, self._bufs[i]

    def release(self, slab_id: int) -> None:
        with self._cond:
            if slab_id in self._free:
                raise ValueError(f"slab {slab_id} released twice")
            self._free.append(slab_id)
            self._cond.notify()

    def grow(self, n: int, max_slabs: int | None = None) -> int:
        """Add up to ``n`` fresh slabs (online tuning: a controller that
        raises the stream count or window depth grows the pool so slab
        backpressure doesn't starve the new capacity), bounded by
        ``max_slabs``. Returns the new slab count. Growth only — slabs may
        be in flight at any moment, so shrinking would mean tracking
        retirement; the bound comes from the controller's limits."""
        with self._cond:
            if max_slabs is not None:
                n = min(n, max_slabs - self.n_slabs)
            for _ in range(max(0, n)):
                self._bufs.append(
                    np.zeros((self.rows, self.row_len), dtype=np.uint8)
                )
                self._free.append(len(self._bufs) - 1)
                self.n_slabs += 1
            self._cond.notify_all()
            return self.n_slabs

    @property
    def free_slabs(self) -> int:
        with self._cond:
            return len(self._free)

    def nbytes(self) -> int:
        return self.n_slabs * self.rows * self.row_len


class _Closed:
    pass


_CLOSED = _Closed()


class FileStream:
    """Byte-bounded (path, bytes) handoff queue, iterable exactly once.

    Producer side: :meth:`put` blocks while ``max_bytes`` of content is
    already buffered (walk-side backpressure); :meth:`close` ends the
    stream; :meth:`fail` poisons it so a blocked/future producer raises
    the consumer's error instead of hanging on a dead pipeline.
    Consumer side: iterate — each item is popped as soon as the scanner
    takes it, releasing its bytes from the budget.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max(1, max_bytes)
        self._q: deque = deque()
        self._buffered = 0
        self._closed = False
        self._error: BaseException | None = None
        self._cond = threading.Condition()

    def put(self, path: str, data: bytes) -> None:
        with self._cond:
            while (
                self._buffered >= self.max_bytes
                and self._error is None
                and not self._closed
            ):
                self._cond.wait(0.2)
            if self._error is not None:
                raise self._error
            if self._closed:
                raise RuntimeError("FileStream is closed")
            self._q.append((path, data))
            self._buffered += len(data)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def fail(self, err: BaseException) -> None:
        """Poison the stream: drop buffered items (the consumer is gone)
        and make every producer call raise ``err``."""
        with self._cond:
            self._error = err
            self._q.clear()
            self._buffered = 0
            self._cond.notify_all()

    def __iter__(self) -> Iterator[tuple[str, bytes]]:
        while True:
            with self._cond:
                while not self._q and not self._closed and self._error is None:
                    self._cond.wait(0.2)
                if self._error is not None:
                    return
                if not self._q:
                    return  # closed and drained
                path, data = self._q.popleft()
                self._buffered -= len(data)
                self._cond.notify_all()
            yield path, data
