"""Persistent cross-scan chunk-dedup store (ROADMAP item 2).

The PR 2 hit cache held row verdicts in a per-process, entry-bounded LRU
with optional per-row persistence. At fleet scale the same base images and
vendored trees are re-scanned constantly, so this promotes it to a
first-class shared store:

- **byte-bounded** in-process LRU (``--secret-dedup-mb``): the bound is an
  RSS budget, not an entry count — a streaming multi-GB scan's dedup state
  stays flat no matter how many distinct rows it sees;
- **fingerprint-versioned namespace**: every persisted key lives under
  ``secret-hitv<V>:<fingerprint>:`` where the fingerprint folds the full
  effective config — compiled ruleset (ids/regexes/keywords/paths), the
  prefilter table, chunk length, AND the ``--secret-config`` file content
  — so a changed rule file can never serve stale verdicts cross-process.
  A namespace marker records the last fingerprint seen; a mismatch logs a
  LOUD cold-start line instead of silently missing forever;
- **batched backend IO**: lookups happen per assembled batch
  (:meth:`lookup_batch` — one pipelined round trip per batch on redis,
  see ``cache/redis.py``), writes are write-behind buffered and flushed
  per resolved batch (:meth:`flush_writes`);
- **warm export / seed**: a coordinator exports its hottest entries
  (:meth:`export_warm`) and pre-seeds replicas' stores over the fleet
  shard wire (:meth:`seed`), so a fresh replica joins a re-scan warm.

Verdict wire/persist schema (the PR 7 "row verdict"):
``{"r": hit_rules, "c": cand_rules, "n": nfa_ran, "l": lic|None}``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from trivy_tpu import log
from trivy_tpu.obs import metrics as obs_metrics

logger = log.logger("secret:hitstore")

# persisted-namespace version: bump when the verdict schema or the
# fingerprint recipe changes (v3: fingerprint folds --secret-config file
# content; lookups/writes are batched)
STORE_VERSION = 3

# default in-process LRU byte budget; entries are tiny (most verdicts are
# empty tuples), so 32 MB holds ~10^5-10^6 rows
DEFAULT_STORE_MB = 32

# write-behind buffer flushed in one pipelined round trip once this many
# verdicts are pending (or at scan end, force=True)
WRITE_BATCH = 256

# cross-replica warming export bound: enough to cover a large shared base
# tree without bloating a shard RPC body
WARM_EXPORT_LIMIT = 4096

_gauge_lock = threading.Lock()
_gauges: dict | None = None


def _store_gauges() -> dict:
    """Lazily registered so scans without a persistent store render no
    dedup-store metric rows at all (the zero-cost-when-off bar)."""
    global _gauges
    with _gauge_lock:
        if _gauges is None:
            _gauges = {
                "entries": obs_metrics.REGISTRY.gauge(
                    "trivy_tpu_dedup_store_entries",
                    "row verdicts held in the in-process dedup LRU",
                ),
                "bytes": obs_metrics.REGISTRY.gauge(
                    "trivy_tpu_dedup_store_bytes",
                    "estimated bytes held by the in-process dedup LRU",
                ),
                "warm_hits": obs_metrics.REGISTRY.gauge(
                    "trivy_tpu_dedup_warm_hits_total",
                    "rows served from the persistent cross-scan store",
                ),
            }
        return _gauges


def verdict_to_doc(verdict: tuple) -> dict:
    hit_rules, cand_rules, nfa_ran, lic = verdict
    return {
        "r": list(hit_rules),
        "c": list(cand_rules),
        "n": int(nfa_ran),
        "l": lic if lic is None else list(lic),
    }


def doc_to_verdict(doc: dict) -> tuple | None:
    try:
        lic = doc.get("l")
        return (
            tuple(doc["r"]),
            tuple(doc.get("c", ())),
            bool(doc.get("n", 1)),
            None if lic is None else tuple(lic),
        )
    except (KeyError, TypeError):
        return None


def _entry_bytes(key: bytes, verdict: tuple) -> int:
    hit_rules, cand_rules, _, lic = verdict
    return 64 + len(key) + 8 * (
        len(hit_rules) + len(cand_rules) + (len(lic) if lic else 0)
    )


class HitStore:
    """Row-verdict store: byte-bounded LRU in front of an optional
    persistent ``trivy_tpu.cache`` backend. Thread-safe; all backend IO
    is serialized under one lock (the RESP socket is not reentrant)."""

    def __init__(
        self,
        fingerprint: bytes,
        backend=None,
        max_entries: int = 0,
        max_bytes: int = 0,
        write_batch: int = WRITE_BATCH,
    ):
        self.fingerprint = fingerprint
        self.backend = backend
        self.max_entries = int(max_entries) or (1 << 16)
        self.max_bytes = int(max_bytes) or DEFAULT_STORE_MB * (1 << 20)
        self.write_batch = max(1, int(write_batch))
        self._lru: OrderedDict[bytes, tuple] = OrderedDict()
        self._lru_bytes = 0
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._pending: dict[str, dict] = {}  # write-behind buffer
        self.stats = {
            "lru_hits": 0,
            "warm_hits": 0,        # rows served from the backend
            "backend_lookups": 0,  # batched round trips issued
            "backend_writes": 0,   # batched write round trips issued
            "seeded": 0,           # entries pre-inserted by a warm peer
            "evictions": 0,
        }
        if backend is not None:
            self._check_namespace()

    # -- namespace ----------------------------------------------------------

    @property
    def prefix(self) -> str:
        return f"secret-hitv{STORE_VERSION}:{self.fingerprint.hex()}:"

    def _persist_key(self, key: bytes) -> str:
        return self.prefix + key.hex()

    # namespaces remembered by the marker (coexisting configs against one
    # shared backend are legitimate — each warns once ever, not per scan)
    MARKER_FPS = 16

    def _check_namespace(self) -> None:
        """Loud-miss guard: the marker records the fingerprints this
        backend has served. A fingerprint the marker has never seen —
        while others exist — means the effective config changed (rule
        file edit, prefilter-table change, chunk-len retune) or this is a
        new config's first scan; either way prior entries are invisible
        by design, so say so ONCE (the fp then joins the marker set —
        legitimately coexisting configs must not flap a warning on every
        scan)."""
        marker_key = f"secret-hit-ns:v{STORE_VERSION}"
        try:
            with self._io_lock:
                marker = self.backend.get_blob(marker_key) or {}
                fps = list(marker.get("fps") or [])
                # legacy single-fp marker shape
                if not fps and marker.get("fp"):
                    fps = [marker["fp"]]
                fp = self.fingerprint.hex()
                if fp in fps:
                    return
                if fps:
                    logger.warning(
                        "persistent dedup store: fingerprint %s not seen "
                        "before on this backend (last writers: %s) — the "
                        "effective secret config (rules, prefilter table, "
                        "--secret-config content, or chunk length) differs, "
                        "so this namespace starts COLD; prior entries stay "
                        "invisible by design",
                        fp[:16], ", ".join(f[:16] for f in fps[-3:]),
                    )
                    from trivy_tpu.obs import recorder as flight

                    flight.record(
                        "cold", "warm-store cold start",
                        {"fingerprint": fp[:16]},
                    )
                fps = (fps + [fp])[-self.MARKER_FPS:]
                self.backend.put_blob(marker_key, {"fps": fps})
        except Exception as e:  # the store is an accelerator, never a dep
            logger.warning("dedup store namespace check failed: %s", e)

    # -- LRU ----------------------------------------------------------------

    def _insert_locked(self, key: bytes, verdict: tuple) -> None:
        old = self._lru.pop(key, None)
        if old is not None:
            self._lru_bytes -= _entry_bytes(key, old)
        self._lru[key] = verdict
        self._lru_bytes += _entry_bytes(key, verdict)
        # byte bound first (the RSS budget), entry bound as a backstop
        while self._lru and (
            self._lru_bytes > self.max_bytes
            or len(self._lru) > self.max_entries
        ):
            k, v = self._lru.popitem(last=False)
            self._lru_bytes -= _entry_bytes(k, v)
            self.stats["evictions"] += 1

    def get(self, key: bytes) -> tuple | None:
        """In-process LRU lookup only — the synchronous per-row path.
        Persistent lookups are batched (:meth:`lookup_batch`)."""
        with self._lock:
            v = self._lru.get(key)
            if v is not None:
                self._lru.move_to_end(key)
                self.stats["lru_hits"] += 1
            return v

    def put(self, key: bytes, verdict: tuple) -> None:
        """Insert locally and buffer the persistent write (write-behind;
        call :meth:`flush_writes` per resolved batch)."""
        with self._lock:
            self._insert_locked(key, verdict)
            if self.backend is not None:
                self._pending[self._persist_key(key)] = verdict_to_doc(verdict)

    def clear_local(self) -> None:
        """Drop the in-process LRU (persisted entries untouched) — bench
        uses this to measure cold vs warm feed paths."""
        with self._lock:
            self._lru.clear()
            self._lru_bytes = 0

    @property
    def entries(self) -> int:
        return len(self._lru)

    @property
    def bytes(self) -> int:
        return self._lru_bytes

    # -- batched backend IO --------------------------------------------------

    def lookup_batch(self, keys: list[bytes]) -> dict[bytes, tuple]:
        """Resolve row digests against the persistent backend in ONE
        pipelined round trip; found verdicts enter the LRU. Keys already
        resolved locally are answered from the LRU without IO."""
        out: dict[bytes, tuple] = {}
        if not keys:
            return out
        misses: list[bytes] = []
        with self._lock:
            for k in keys:
                v = self._lru.get(k)
                if v is not None:
                    self._lru.move_to_end(k)
                    out[k] = v
                else:
                    misses.append(k)
        if self.backend is None or not misses:
            return out
        from trivy_tpu import cache as cache_mod

        ids = {self._persist_key(k): k for k in misses}
        try:
            with self._io_lock:
                found = cache_mod.get_blobs(self.backend, list(ids))
            self.stats["backend_lookups"] += 1
        except Exception as e:
            logger.warning("dedup store batch lookup failed: %s", e)
            return out
        warm = 0
        with self._lock:
            for pid, doc in found.items():
                v = doc_to_verdict(doc)
                if v is None:
                    continue
                k = ids[pid]
                self._insert_locked(k, v)
                out[k] = v
                warm += 1
            self.stats["warm_hits"] += warm
        if warm and self.backend is not None:
            _store_gauges()["warm_hits"].set(self.stats["warm_hits"])
        return out

    def flush_writes(self, force: bool = False) -> None:
        """Push the write-behind buffer in one pipelined round trip once it
        reaches the batch size (or unconditionally with ``force``)."""
        if self.backend is None:
            return
        with self._lock:
            if not self._pending or (
                not force and len(self._pending) < self.write_batch
            ):
                return
            pending, self._pending = self._pending, {}
        from trivy_tpu import cache as cache_mod

        try:
            with self._io_lock:
                cache_mod.set_blobs(self.backend, pending)
            self.stats["backend_writes"] += 1
        except Exception as e:
            logger.warning("dedup store batch write failed: %s", e)
        g = _store_gauges()
        g["entries"].set(self.entries)
        g["bytes"].set(self.bytes)

    # -- cross-replica warming ----------------------------------------------

    def export_warm(self, limit: int = WARM_EXPORT_LIMIT) -> list[list]:
        """Warm entries as ``[[persist_key, doc], ...]`` — the hottest
        local entries (most recently used first), or, when the local LRU
        is cold but a persistent backend is warm, a bounded enumeration
        of this store's namespace. Entries carry their FULL namespace key
        (version + fingerprint), so a receiver can verify soundness
        without any side-channel fingerprint exchange."""
        entries: list[list] = []
        with self._lock:
            for k in reversed(self._lru):  # most recently used first
                entries.append(
                    [self._persist_key(k), verdict_to_doc(self._lru[k])]
                )
                if len(entries) >= limit:
                    break
        if not entries and self.backend is not None:
            from trivy_tpu import cache as cache_mod

            try:
                with self._io_lock:
                    found = cache_mod.warm_blobs(
                        self.backend, self.prefix, limit
                    )
                entries = [[k, v] for k, v in sorted(found.items())]
            except Exception as e:
                logger.warning("dedup store warm export failed: %s", e)
        return entries

    def seed(self, entries: list) -> int:
        """Pre-insert a peer's warm entries. Only keys under THIS store's
        namespace (same version + fingerprint, i.e. provably the same
        effective config) are accepted — anything else is dropped, with
        one loud line naming the count (applying verdicts computed under
        different rules would be unsound)."""
        n = dropped = 0
        prefix = self.prefix
        with self._lock:
            for item in entries or []:
                try:
                    pid, doc = item[0], item[1]
                    if not str(pid).startswith(prefix):
                        dropped += 1
                        continue
                    key = bytes.fromhex(pid[len(prefix):])
                    v = doc_to_verdict(doc)
                except (ValueError, TypeError, IndexError):
                    dropped += 1
                    continue
                if v is None:
                    dropped += 1
                    continue
                self._insert_locked(key, v)
                n += 1
            self.stats["seeded"] += n
        if dropped:
            logger.warning(
                "dedup warm seed: %d entr%s dropped (different fingerprint "
                "namespace — the peer runs different rules/config/chunking)",
                dropped, "y" if dropped == 1 else "ies",
            )
        return n


def export_backend_warm(cache, limit: int = WARM_EXPORT_LIMIT) -> list[list]:
    """Warm entries straight off a cache backend, across every dedup
    namespace version-``STORE_VERSION`` holds — the fleet coordinator uses
    this to pre-seed replicas without building a scanner (no jax, no
    kernel compiles); each replica's store accepts only its own
    namespace's entries."""
    from trivy_tpu import cache as cache_mod

    found = cache_mod.warm_blobs(cache, f"secret-hitv{STORE_VERSION}:", limit)
    return [[k, v] for k, v in sorted(found.items())]
