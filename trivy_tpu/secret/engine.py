"""Secret scan engine: rule evaluation, censoring, finding construction.

Behavioral contract modeled on the reference scan loop (ref:
pkg/fanal/secret/scanner.go:377-463): per file — global path allowlist, then
for each rule: path match, per-rule path allowlist, keyword prefilter, regex
location finding, exclude-block suppression, per-rule allow-regex
suppression; matched bytes are censored (ref: scanner.go:465-473) and each
location becomes a finding with 1-based line numbers, a censored match line
truncated to a display budget, and ±2 lines of code context (ref:
scanner.go:495-558). Findings are sorted deterministically so output is
stable under any execution order — the property that lets the TPU batch path
produce byte-identical results.

Content is handled as latin-1 text: a 1:1 byte<->char mapping, so regex spans
ARE byte offsets and censoring is byte-exact regardless of encoding.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

from trivy_tpu import log, obs
from trivy_tpu.secret.rules import (
    SECRET_GROUP,
    AllowRule,
    Rule,
    ascii_lower,
    builtin_allow_rules,
    builtin_rules,
)
from trivy_tpu.types import Code, Line, Secret, SecretFinding, Severity

logger = log.logger("secret")

# Display budget for a rendered line (ref: scanner.go findLocation 100-char cap).
MAX_LINE_LENGTH = 100
# Context lines around the cause block (ref: scanner.go:495-558 ±2 lines).
CONTEXT_LINES = 2


@dataclass(frozen=True)
class Location:
    start: int
    end: int


@dataclass
class ScannerConfig:
    """User configuration (ref: pkg/fanal/secret/scanner.go:277-307).

    Loaded from a ``trivy-secret.yaml``-shaped mapping: custom rules, custom
    allow rules, rule disabling, builtin-rule restriction, global exclude
    blocks.
    """

    custom_rules: list[Rule] = field(default_factory=list)
    custom_allow_rules: list[AllowRule] = field(default_factory=list)
    enable_builtin_rule_ids: list[str] | None = None
    disable_rule_ids: list[str] = field(default_factory=list)
    disable_allow_rule_ids: list[str] = field(default_factory=list)
    exclude_block_regexes: list[str] = field(default_factory=list)
    # sha256 of the source --secret-config file bytes ("" when built
    # programmatically): folded into persistent dedup/manifest namespaces
    source_digest: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "ScannerConfig":
        def to_rule(rd: dict) -> Rule:
            eb = rd.get("exclude-block")
            if isinstance(eb, dict):
                exclude_blocks = list(eb.get("regexes", []) or [])
            elif isinstance(eb, str):
                exclude_blocks = [eb]
            else:
                exclude_blocks = []
            return Rule(
                id=rd["id"],
                category=rd.get("category", "Custom"),
                title=rd.get("title", rd["id"]),
                severity=Severity.parse(rd.get("severity", "UNKNOWN")),
                regex=rd["regex"],
                keywords=list(rd.get("keywords", []) or []),
                path=rd.get("path"),
                secret_group_name=rd.get("secret-group-name") or rd.get("secret_group_name"),
                allow_rules=[to_allow(a) for a in rd.get("allow-rules", []) or []],
                exclude_blocks=exclude_blocks,
            )

        def to_allow(ad: dict) -> AllowRule:
            return AllowRule(
                id=ad["id"],
                description=ad.get("description", ""),
                path=ad.get("path"),
                regex=ad.get("regex"),
            )

        return cls(
            custom_rules=[to_rule(r) for r in d.get("rules", []) or []],
            custom_allow_rules=[to_allow(a) for a in d.get("allow-rules", []) or []],
            enable_builtin_rule_ids=d.get("enable-builtin-rules"),
            disable_rule_ids=list(d.get("disable-rules", []) or []),
            disable_allow_rule_ids=list(d.get("disable-allow-rules", []) or []),
            exclude_block_regexes=list(
                (d.get("exclude-block", {}) or {}).get("regexes", []) or []
            ),
        )

    @classmethod
    def from_yaml_file(cls, path: str) -> "ScannerConfig":
        import hashlib

        import yaml  # baked in via transformers' dependency set

        with open(path, "rb") as f:
            raw = f.read()
        cfg = cls.from_dict(yaml.safe_load(raw.decode("utf-8")) or {})
        # content digest of the config FILE: the persistent dedup store and
        # the incremental-scan manifest fold this into their namespace keys,
        # so editing the rule file invalidates every persisted entry even
        # when the parsed rule set happens to hash the same way (allow
        # rules, exclude blocks, and disables don't change hit vectors but
        # DO change findings)
        cfg.source_digest = hashlib.sha256(raw).hexdigest()
        return cfg


class SecretScanner:
    """Evaluates the effective ruleset over file contents.

    This is the exact-semantics engine. It is used directly as the CPU
    backend, and as the confirmation stage of the TPU backend (which uses the
    device prefilter to decide *which* (file, rule) pairs ever reach it).
    """

    def __init__(self, config: ScannerConfig | None = None):
        cfg = config or ScannerConfig()
        rules = builtin_rules()
        if cfg.enable_builtin_rule_ids is not None:
            enabled = set(cfg.enable_builtin_rule_ids)
            unknown = enabled - {r.id for r in rules}
            if unknown:
                raise ValueError(f"unknown builtin rule ids: {sorted(unknown)}")
            rules = [r for r in rules if r.id in enabled]
        disabled = set(cfg.disable_rule_ids)
        rules = [r for r in rules if r.id not in disabled]
        for r in cfg.custom_rules:
            if r.id in disabled:
                continue
            rules.append(r)
        self.rules: list[Rule] = rules

        allow = builtin_allow_rules() + list(cfg.custom_allow_rules)
        disabled_allow = set(cfg.disable_allow_rule_ids)
        self.allow_rules: list[AllowRule] = [a for a in allow if a.id not in disabled_allow]

        self.global_exclude_blocks: list[re.Pattern] = [
            re.compile(p) for p in cfg.exclude_block_regexes
        ]

    # -- path-level filters -------------------------------------------------

    def allow_path(self, path: str) -> bool:
        """Global path allowlist: file skipped entirely (ref: scanner.go:388-392)."""
        return any(a.path_re and a.path_re.search(path) for a in self.allow_rules)

    def rules_for_path(self, path: str) -> list[Rule]:
        """Rules applicable to this path after path match + per-rule path allow."""
        return [
            r for r in self.rules if r.match_path(path) and not r.allow_path(path)
        ]

    # -- location finding (shared by CPU and TPU-confirm paths) -------------

    def global_block_spans(self, content: str) -> list[tuple[int, int]]:
        """Spans of user-configured global exclude blocks, computed once per file
        (the reference builds its block index lazily per content,
        ref: scanner.go:237-275)."""
        spans: list[tuple[int, int]] = []
        for pat in self.global_exclude_blocks:
            spans.extend(m.span() for m in pat.finditer(content))
        return spans

    def find_rule_locations(
        self,
        rule: Rule,
        content: str,
        lower: str,
        global_blocks: list[tuple[int, int]] | None = None,
    ) -> list[Location]:
        """All surviving match locations of one rule in ``content``.

        ``content`` must be latin-1-decoded bytes so spans are byte offsets.
        """
        if not rule.match_keywords(lower):
            return []
        locs: list[Location] = []
        for m in rule.regex_re.finditer(content):
            if rule.secret_group_name and rule.secret_group_name in rule.regex_re.groupindex:
                start, end = m.span(rule.secret_group_name)
            else:
                start, end = m.span()
            if start == end or start < 0:
                continue
            locs.append(Location(start, end))
        return self._filter_locations(rule, content, locs, global_blocks)

    def _filter_locations(
        self,
        rule: Rule,
        content: str,
        locs: list[Location],
        global_blocks: list[tuple[int, int]] | None,
    ) -> list[Location]:
        """Exclude-block and allow-regex suppression shared by every
        location-finding strategy."""
        if not locs:
            return []
        # exclude-block suppression: a location is dropped only when a block
        # fully contains it (ref: scanner.go Location.Match containment).
        blocks: list[tuple[int, int]] = list(
            global_blocks if global_blocks is not None else self.global_block_spans(content)
        )
        for pat in rule.exclude_block_res:
            blocks.extend(m.span() for m in pat.finditer(content))
        if blocks:
            locs = [
                l
                for l in locs
                if not any(bs <= l.start and l.end <= be for bs, be in blocks)
            ]
        # allow regexes (per-rule + global) are tested against the extracted
        # secret text itself (ref: scanner.go AllowLocation).
        allow_res = [a.regex_re for a in rule.allow_rules if a.regex_re is not None]
        allow_res += [a.regex_re for a in self.allow_rules if a.regex_re is not None]
        if allow_res:
            locs = [
                l
                for l in locs
                if not any(p.search(content[l.start : l.end]) for p in allow_res)
            ]
        return locs

    def find_rule_locations_fullscan(
        self,
        rule: Rule,
        content: str,
        lower: str,
        global_blocks: list[tuple[int, int]] | None = None,
    ) -> list[Location]:
        """:meth:`find_rule_locations` semantics, but unbounded-width rules
        locate candidate match starts with the bounded start-detector and
        take the true extent via ``match()`` — avoiding the regex engine's
        whole-content rescan. Used by the TPU confirm path for device
        keyword-lane rules, where flagged chunks bound the *keyword*
        position, not the match start, so no window restriction is sound.
        """
        det = None
        if not rule.has_lookaround:
            wmax = rule.max_match_width
            if wmax is None or wmax > 8192:
                det = rule.start_detector
        if det is None:
            return self.find_rule_locations(rule, content, lower, global_blocks)
        if not rule.match_keywords(lower):
            return []
        locs: list[Location] = []
        n = len(content)
        pos = 0
        while pos < n:
            dm = det[0].search(content, pos)
            if dm is None:
                break
            m = rule.regex_re.match(content, dm.start())
            if m is None:
                pos = dm.start() + 1
                continue
            if rule.secret_group_name and rule.secret_group_name in rule.regex_re.groupindex:
                start, end = m.span(rule.secret_group_name)
            else:
                start, end = m.span()
            pos = m.end() if m.end() > dm.start() else dm.start() + 1
            if start == end or start < 0:
                continue
            locs.append(Location(start, end))
        return self._filter_locations(rule, content, locs, global_blocks)

    def find_rule_locations_in_windows(
        self,
        rule: Rule,
        content: str,
        lower: str,
        windows: list[tuple[int, int]],
        global_blocks: list[tuple[int, int]] | None = None,
    ) -> list[Location]:
        """Same results as :meth:`find_rule_locations` restricted to matches
        whose *start* lies inside the given windows.

        SOUND ONLY when the device guarantees flagged chunks contain the
        match start: the anchored device lane (anchor literal at fixed
        offset from the match start), or the keyword lane for bounded-width
        rules whose keyword provably sits inside every match
        (``Rule.keyword_in_match`` — the keyword occurrence then bounds the
        start within ``max_match_width``). Keyword-lane rules without that
        proof must use :meth:`find_rule_locations_fullscan` instead — the
        caller (TpuSecretScanner._confirm_inner) enforces this split.

        Bounded-width rules use ``search(pos, endpos)`` over windows padded
        by the match width so ``^``/lookbehind/word-prefix see real context;
        unbounded-width rules locate candidate starts with the bounded
        start-detector prefix and take the true extent via ``match()``.
        Lookaround rules fall back to a full scan (their context is
        unbounded by getwidth()).
        """
        if not rule.match_keywords(lower):  # keywords are a whole-file test
            return []
        wmax = rule.max_match_width
        if rule.has_lookaround:
            # lookarounds examine context beyond getwidth()'s bound, so the
            # fixed padding below cannot guarantee parity — full scan instead
            return self.find_rule_locations(rule, content, lower, global_blocks)
        detector = None
        if wmax is None or wmax > 8192:
            # unbounded match width: locate candidate starts with the bounded
            # start-detector prefix, then take the true (unbounded) extent
            # via match() at each candidate — no full-file rescans
            detector = rule.start_detector
            if detector is None:
                return self.find_rule_locations(rule, content, lower, global_blocks)
        n = len(content)
        # slack beyond the match width for anchor/word-prefix context
        pad = (detector[1] if detector else wmax) + 256
        ivs = sorted((max(0, s - pad), min(n, e + pad)) for s, e in windows)
        merged: list[list[int]] = []
        for s, e in ivs:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        verify_edges = rule.has_end_anchor
        locs: list[Location] = []
        pos = 0  # carried across windows: preserves finditer's global
        # non-overlapping consumption order when a match spans a gap
        for s, e in merged:
            pos = max(pos, s)
            while pos <= e:
                if detector is not None:
                    dm = detector[0].search(content, pos, min(n, e + detector[1]))
                    if dm is None or dm.start() > e:
                        break
                    m = rule.regex_re.match(content, dm.start())
                    if m is None:
                        pos = dm.start() + 1
                        continue
                else:
                    m = rule.regex_re.search(content, pos, e)
                    if m is None:
                        break
                    if verify_edges and e < n and m.end() >= e - 1:
                        # finditer's endpos acts as end-of-string, so a
                        # terminal $/\Z (incl. the before-trailing-\n form)
                        # may have fired mid-content; re-match at the same
                        # start against the real string end — the
                        # authoritative span the full scan sees
                        m2 = rule.regex_re.match(content, m.start())
                        if m2 is None:
                            pos = m.start() + 1
                            continue
                        m = m2
                if (
                    rule.secret_group_name
                    and rule.secret_group_name in rule.regex_re.groupindex
                ):
                    start, end = m.span(rule.secret_group_name)
                else:
                    start, end = m.span()
                # non-overlapping consumption order, as finditer would do
                pos = m.end() if m.end() > pos else pos + 1
                if start == end or start < 0:
                    continue
                locs.append(Location(start, end))
        # exclude blocks and allow regexes replicate find_rule_locations over
        # the full content (a block straddling a window must still suppress)
        return self._filter_locations(rule, content, locs, global_blocks)

    # -- full scan ----------------------------------------------------------

    def scan_bytes(self, file_path: str, data: bytes) -> Secret:
        """Scan one file's bytes; returns a :class:`Secret` (possibly empty)."""
        if self.allow_path(file_path):
            return Secret(file_path=file_path)
        content = data.decode("latin-1")
        return self.scan_content(file_path, content)

    def scan_content(self, file_path: str, content: str) -> Secret:
        # per-rule cost profile on the active trace context (the CPU
        # backend and the TPU path's degraded host fallback both come
        # through here, so a degraded scan still profiles per rule); one
        # enabled check per file when tracing is off
        ctx = obs.current()
        prof = ctx.profile() if ctx.enabled else None
        # ASCII-only fold, matching Rule.lower_keywords and the device
        # prefilter (bytes A-Z, no locale) — see rules.ascii_lower
        lower = ascii_lower(content)
        global_blocks = self.global_block_spans(content)
        hits: list[tuple[Rule, Location]] = []
        for rule in self.rules_for_path(file_path):
            t0 = time.perf_counter() if prof is not None else 0.0
            locs = self.find_rule_locations(rule, content, lower, global_blocks)
            if prof is not None:
                prof.confirm(rule.id, time.perf_counter() - t0, len(locs))
            for loc in locs:
                hits.append((rule, loc))
        return self.build_findings(file_path, content, hits)

    def build_findings(
        self, file_path: str, content: str, hits: list[tuple[Rule, Location]]
    ) -> Secret:
        """Censor all hit spans jointly, then render findings deterministically."""
        if not hits:
            return Secret(file_path=file_path)
        # de-duplicate identical (rule, span) pairs — the TPU path may confirm
        # the same location from two overlapping chunks.
        seen: set[tuple[str, int, int]] = set()
        uniq: list[tuple[Rule, Location]] = []
        for rule, loc in hits:
            key = (rule.id, loc.start, loc.end)
            if key not in seen:
                seen.add(key)
                uniq.append((rule, loc))
        censored = _censor(content, [l for _, l in uniq])
        lines = _LineIndex(content, censored)
        findings = [
            _render_finding(rule, loc, lines) for rule, loc in uniq
        ]
        findings.sort(key=lambda f: (f.start_line, f.rule_id, f.offset, f.end_line))
        return Secret(file_path=file_path, findings=findings)


def _censor(content: str, locations: list[Location]) -> str:
    """Replace every secret span with '*' bytes (ref: scanner.go:465-473)."""
    buf = list(content)
    for loc in locations:
        for i in range(loc.start, min(loc.end, len(buf))):
            if buf[i] != "\n":
                buf[i] = "*"
    return "".join(buf)


class _LineIndex:
    """Byte-offset -> line mapping over raw and censored content."""

    def __init__(self, content: str, censored: str):
        self.raw_lines = content.split("\n")
        self.censored_lines = censored.split("\n")
        # starts[i] = offset of first char of line i (0-based line index)
        self.starts: list[int] = [0]
        pos = 0
        for ln in self.raw_lines[:-1]:
            pos += len(ln) + 1
            self.starts.append(pos)

    def line_of(self, offset: int) -> int:
        """0-based line index containing byte ``offset`` (bisect on starts)."""
        import bisect

        return bisect.bisect_right(self.starts, offset) - 1


def _render_finding(rule: Rule, loc: Location, lines: _LineIndex) -> SecretFinding:
    start_li = lines.line_of(loc.start)
    end_li = lines.line_of(max(loc.start, loc.end - 1))
    start_line = start_li + 1
    end_line = end_li + 1

    def render_line(li: int) -> tuple[str, bool]:
        raw = lines.censored_lines[li]
        if len(raw) <= MAX_LINE_LENGTH:
            return raw, False
        # Long line: show a fixed window anchored just before the secret so
        # the cause stays visible (display-budget semantics, ref:
        # scanner.go:495-558).
        local = max(0, loc.start - lines.starts[li]) if li == start_li else 0
        begin = max(0, min(local - 20, len(raw) - MAX_LINE_LENGTH))
        return raw[begin : begin + MAX_LINE_LENGTH], True

    match_text, _ = render_line(start_li)

    code_lines: list[Line] = []
    first = max(0, start_li - CONTEXT_LINES)
    last = min(len(lines.censored_lines) - 1, end_li + CONTEXT_LINES)
    for li in range(first, last + 1):
        content_text, truncated = render_line(li)
        is_cause = start_li <= li <= end_li
        code_lines.append(
            Line(
                number=li + 1,
                content=content_text,
                is_cause=is_cause,
                truncated=truncated,
                highlighted=content_text,
                first_cause=is_cause and li == start_li,
                last_cause=is_cause and li == end_li,
            )
        )

    return SecretFinding(
        rule_id=rule.id,
        category=rule.category,
        severity=rule.severity.value if isinstance(rule.severity, Severity) else str(rule.severity),
        title=rule.title,
        start_line=start_line,
        end_line=end_line,
        match=match_text,
        code=Code(lines=code_lines),
        offset=loc.start,
    )
