"""Secret rule model and built-in ruleset.

Rule semantics follow the reference's model (ref: pkg/fanal/secret/scanner.go:89-100):
each rule has an ID/category/severity/title, a detection regex, a keyword
prefilter list (cheap lowercase substring check before the regex runs), an
optional path regex restricting which files it applies to, optional per-rule
allow rules, an optional exclude-block regex suppressing matches inside
matching block spans, and an optional named group selecting the secret span
within the regex match.

The built-in ruleset covers the same secret families as the reference's 87
built-in rules (ref: pkg/fanal/secret/builtin-rules.go) — cloud provider keys,
VCS tokens, SaaS API keys, private-key blocks — written independently from the
public token formats. Keywords are chosen to be substrings of any match so the
TPU keyword prefilter (exact substring search on device) is sound: a chunk
with no keyword hit can be skipped without running the regex at all.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import cached_property

from trivy_tpu.types import Severity

try:  # 3.11+ spelling of the sre internals
    import re._compiler as _sre_compile
    import re._constants as _sre_c
    import re._parser as _sre_parse
except ImportError:  # 3.10 and earlier expose them top-level
    import sre_compile as _sre_compile
    import sre_constants as _sre_c
    import sre_parse as _sre_parse
# 3.10 getwidth() saturates at MAXREPEAT; 3.11+ renamed it MAXWIDTH
_SRE_MAXWIDTH = getattr(_sre_parse, "MAXWIDTH", _sre_c.MAXREPEAT)

# Matches must not start mid-word: a token preceded by [0-9a-zA-Z] is part of a
# longer word and not a credential boundary (ref: builtin-rules.go:81 startWord).
_WORD_PREFIX = r"(?:^|[^0-9a-zA-Z])"

# Name of the regex group holding the secret when a rule wraps its payload.
SECRET_GROUP = "secret"

# ASCII-only case fold (bytes A-Z -> a-z, nothing else). The device keyword
# prefilter can only fold bytes 0x41-0x5A, so the host pre-lowering of
# keywords AND the content lowering the keyword test runs against must use
# the exact same fold — str.lower()'s unicode/locale folds (e.g. 'À'→'à',
# 'İ'→'i̇') would make host and device disagree on non-ASCII bytes, which
# for a custom rule is a silent device false negative.
_ASCII_LOWER_BYTES = bytes(
    c + 32 if 0x41 <= c <= 0x5A else c for c in range(256)
)


def ascii_lower(s: str) -> str:
    """Fold A-Z to a-z byte-wise; all other characters (including latin-1
    accented letters) pass through unchanged. ``s`` must be latin-1-safe
    (scan content is latin-1-decoded bytes, so it always is); the bytes
    round-trip keeps the fold C-speed on multi-MB content."""
    return s.encode("latin-1").translate(_ASCII_LOWER_BYTES).decode("latin-1")


def ascii_lower_any(s: str) -> str:
    """:func:`ascii_lower` for strings that may contain non-latin-1 chars
    (user-supplied keywords): folds A-Z, passes everything else through."""
    return "".join(
        chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s
    )


def ws(pattern: str) -> str:
    """Wrap ``pattern`` so it only matches at a word start, capturing the
    payload in the ``secret`` group. Mirrors the reference's
    ``MustCompileWithoutWordPrefix`` (ref: pkg/fanal/secret/scanner.go:66-68)."""
    return f"{_WORD_PREFIX}(?P<{SECRET_GROUP}>{pattern})"


def kw(name: str, secret: str, guard: str | None = None) -> str:
    """Keyword-context rule: ``name`` within ~25 chars of an assignment
    operator, payload captured in the ``secret`` group. ``guard`` is a
    character-class body asserting the payload is not a prefix of a longer
    run (compiles to ``(?:[^guard]|$)`` — the end alternative makes the
    rule end-anchored, so the engine gives it the full-content scan path).
    One definition for all keyword-context rules so the window/guard shape
    has a single audit point."""
    g = f"(?:[^{guard}]|$)" if guard else ""
    return (
        rf"(?i){name}[a-z0-9_\-\s\"']{{0,25}}[=:][\s\"']{{0,5}}"
        rf"(?P<{SECRET_GROUP}>{secret}){g}"
    )


@dataclass
class AllowRule:
    """Suppression rule (ref: pkg/fanal/secret/builtin-allow-rules.go).

    ``path``: files whose path matches are skipped entirely.
    ``regex``: tested against the *extracted secret text* of each candidate
    location (ref: scanner.go AllowLocation semantics); a match suppresses the
    finding. Anchors (``^``/``$``) therefore refer to the secret's own bounds.
    """

    id: str
    description: str = ""
    path: str | None = None
    regex: str | None = None

    @cached_property
    def path_re(self) -> re.Pattern | None:
        return re.compile(self.path) if self.path else None

    @cached_property
    def regex_re(self) -> re.Pattern | None:
        return re.compile(self.regex) if self.regex else None


@dataclass
class Rule:
    id: str
    category: str
    title: str
    severity: Severity
    regex: str
    keywords: list[str] = field(default_factory=list)
    path: str | None = None
    secret_group_name: str | None = None
    allow_rules: list[AllowRule] = field(default_factory=list)
    exclude_blocks: list[str] = field(default_factory=list)

    @cached_property
    def regex_re(self) -> re.Pattern:
        return re.compile(self.regex)

    @cached_property
    def path_re(self) -> re.Pattern | None:
        return re.compile(self.path) if self.path else None

    @cached_property
    def exclude_block_res(self) -> list[re.Pattern]:
        return [re.compile(p) for p in self.exclude_blocks]

    @cached_property
    def max_match_width(self) -> int | None:
        """Upper bound on a match's length in chars, or None if unbounded
        (used to size span-restricted confirmation windows)."""
        try:
            sre_parse = _sre_parse

            _, hi = sre_parse.parse(self.regex).getwidth()
            return None if hi >= _SRE_MAXWIDTH else int(hi)
        except Exception:
            return None

    @cached_property
    def start_detector(self) -> tuple[re.Pattern, int] | None:
        """Bounded *match-start detector* for unbounded-width rules.

        A compiled prefix of the pattern — truncated at the first
        unbounded repeat — such that any full-pattern match at position
        ``p`` implies the detector matches at ``p``, with finite max
        width.  The windowed confirm path (engine.find_rule_locations_in
        _windows) uses it to locate candidate starts inside device-flagged
        chunks and then runs the true regex via ``match(content, start)``
        for the exact unbounded extent, instead of rescanning the whole
        file (ref: the full-scan hot loop pkg/fanal/secret/scanner.go:377
        that this replaces).

        Returns ``(pattern, max_width)`` or None when no useful bounded
        prefix exists (unbounded from the first element) — callers then
        fall back to a full-content scan.
        """
        try:
            sre_compile = _sre_compile
            sre_parse = _sre_parse

            MAXW = _SRE_MAXWIDTH

            def item_width(state, op, av) -> int:
                probe = sre_parse.SubPattern(state, [(op, av)])
                return probe.getwidth()[1]

            def truncate(sub):
                """Longest bounded prefix of ``sub``'s concatenation;
                second value False when truncation happened (stop after)."""
                out = sre_parse.SubPattern(sub.state)
                for op, av in sub.data:
                    if item_width(sub.state, op, av) < MAXW:
                        out.data.append((op, av))
                        continue
                    name = str(op)
                    if name == "SUBPATTERN":
                        group, add_f, del_f, inner = av
                        tin, _ = truncate(inner)
                        if tin.data:
                            out.data.append((op, (group, add_f, del_f, tin)))
                    elif name in ("MAX_REPEAT", "MIN_REPEAT"):
                        lo, _hi, item = av
                        if lo > 0 and item.getwidth()[1] < MAXW:
                            out.data.append((op, (lo, lo, item)))
                    # anything else unbounded (branch, conditional): stop
                    # before it — the kept prefix is still a sound anchor
                    return out, False
                return out, True

            parsed = sre_parse.parse(self.regex)
            out, _ = truncate(parsed)
            if not out.data:
                return None
            _, width = out.getwidth()
            if width == 0 or width >= MAXW:
                return None
            return sre_compile.compile(out), int(width)
        except Exception:
            return None

    @cached_property
    def keyword_in_match(self) -> bool:
        """True when every match provably contains one of the rule's
        keywords (case-insensitively).

        Decides whether chunk-windowed confirmation is sound for the
        keyword device lane: the device flags chunks where a *keyword*
        occurs, so windows only cover match starts when the keyword is
        guaranteed to sit inside the match (within ``max_match_width`` of
        its start).  Proved by folding the pattern into mandatory
        lowercased character runs — a keyword inside a mandatory run is
        present in every match; anything unprovable returns False and the
        confirm falls back to a full-content scan (the reference's
        file-level keyword semantics, pkg/fanal/secret/scanner.go:174-186).
        """
        if not self.lower_keywords:
            return False
        try:
            sre_c, sre_parse = _sre_c, _sre_parse

            def fold_char(chars: frozenset) -> str | None:
                """Single char every member ASCII-folds to, or None — the
                same A-Z-only fold the keyword test uses."""
                folded = {
                    chr(_ASCII_LOWER_BYTES[c]) for c in chars if c < 256
                }
                return folded.pop() if len(folded) == 1 else None

            def single(op, av) -> frozenset | None:
                if op is sre_c.LITERAL:
                    return frozenset({av}) if av < 256 else None
                if op is sre_c.IN:
                    chars: set[int] = set()
                    for iop, iav in av:
                        if iop is sre_c.LITERAL and iav < 256:
                            chars.add(iav)
                        elif iop is sre_c.RANGE:
                            lo, hi = iav
                            chars.update(range(lo, min(hi, 255) + 1))
                        else:
                            return None
                    return frozenset(chars)
                return None

            MAX_PATHS = 64

            def walk(nodes, paths: list[list[str]]) -> None:
                """Accumulate mandatory folded fragments per alternation
                path; un-foldable constructs end the current fragment."""

                def append(text: str | None) -> None:
                    for p in paths:
                        if text is None:
                            if p[-1]:
                                p.append("")
                        else:
                            p[-1] += text

                for op, av in nodes:
                    name = str(op)
                    if name in ("LITERAL", "IN"):
                        cs = single(op, av)
                        append(fold_char(cs) if cs else None)
                    elif name in ("MAX_REPEAT", "MIN_REPEAT"):
                        lo, hi, sub = av
                        sub = list(sub)
                        ch = None
                        if lo > 0 and lo <= 256 and len(sub) == 1:
                            cs = single(*sub[0])
                            ch = fold_char(cs) if cs else None
                        append(ch * lo if ch else None)
                        if hi != lo:
                            append(None)
                    elif name == "SUBPATTERN":
                        _g, _af, _df, sub = av
                        walk(list(sub), paths)
                    elif name == "BRANCH":
                        _, alts = av
                        if len(paths) * len(alts) > MAX_PATHS:
                            append(None)
                            continue
                        forked: list[list[str]] = []
                        for alt in alts:
                            alt_paths = [list(p) for p in paths]
                            walk(list(alt), alt_paths)
                            forked.extend(alt_paths)
                        paths[:] = forked
                    else:
                        # AT/ASSERT/GROUPREF/...: conservatively break
                        append(None)

            paths: list[list[str]] = [[""]]
            walk(list(sre_parse.parse(self.regex)), paths)
            kws = self.lower_keywords
            return all(
                any(k in frag for frag in p for k in kws) for p in paths
            )
        except Exception:
            return False

    @cached_property
    def has_lookaround(self) -> bool:
        """True when the pattern contains lookahead/lookbehind assertions.
        Lookarounds contribute zero to getwidth(), so window-restricted
        scanning cannot bound the context they examine — such rules must take
        the full-content scan path to stay parity-identical."""
        try:
            sre_parse = _sre_parse

            def walk(items) -> bool:
                for op, av in items:
                    name = str(op)
                    if name in ("ASSERT", "ASSERT_NOT"):
                        return True
                    if isinstance(av, tuple):
                        for part in av:
                            if isinstance(part, sre_parse.SubPattern) and walk(part):
                                return True
                            if isinstance(part, (list, tuple)):
                                for sub in part:
                                    if isinstance(sub, sre_parse.SubPattern) and walk(sub):
                                        return True
                    elif isinstance(av, sre_parse.SubPattern) and walk(av):
                        return True
                return False

            return walk(sre_parse.parse(self.regex))
        except Exception:
            return True

    @cached_property
    def has_end_anchor(self) -> bool:
        """True when the pattern can match ``$``/``\\Z``. ``search(pos,
        endpos)`` treats endpos as end-of-string, so an end anchor matches at
        a window edge where the full scan (with real trailing content) would
        not — window-restricted scanning re-verifies such edge matches
        against the real string end (engine.find_rule_locations_in_windows)."""
        try:
            sre_c, sre_parse = _sre_c, _sre_parse

            def walk(items) -> bool:
                for op, av in items:
                    if op is sre_c.AT and av in (
                        sre_c.AT_END, sre_c.AT_END_STRING, sre_c.AT_END_LINE
                    ):
                        return True
                    if isinstance(av, tuple):
                        for part in av:
                            if isinstance(part, sre_parse.SubPattern) and walk(part):
                                return True
                            if isinstance(part, (list, tuple)):
                                for sub in part:
                                    if isinstance(sub, sre_parse.SubPattern) and walk(sub):
                                        return True
                    elif isinstance(av, sre_parse.SubPattern) and walk(av):
                        return True
                return False

            return walk(sre_parse.parse(self.regex))
        except Exception:
            return True

    @cached_property
    def lower_keywords(self) -> list[str]:
        # ASCII fold only — must equal the device prefilter's A-Z fold (see
        # ascii_lower); keywords are matched against ascii_lower(content)
        return [ascii_lower_any(k) for k in self.keywords]

    def match_path(self, path: str) -> bool:
        return self.path_re is None or self.path_re.search(path) is not None

    def allow_path(self, path: str) -> bool:
        return any(a.path_re and a.path_re.search(path) for a in self.allow_rules)

    def match_keywords(self, lower_content: str) -> bool:
        """Cheap prefilter: any keyword present (lowercased substring), or no
        keywords at all (ref: scanner.go:174-186)."""
        if not self.lower_keywords:
            return True
        return any(k in lower_content for k in self.lower_keywords)


def _r(
    id: str,
    category: str,
    title: str,
    severity: Severity,
    regex: str,
    keywords: list[str],
    **kw,
) -> Rule:
    return Rule(
        id=id, category=category, title=title, severity=severity, regex=regex,
        keywords=keywords, **kw,
    )


CategoryAWS = "AWS"
CategoryGitHub = "GitHub"
CategoryGitLab = "GitLab"
CategoryAsymmetricPrivateKey = "AsymmetricPrivateKey"
CategoryGoogle = "Google"
CategorySlack = "Slack"
CategoryStripe = "Stripe"
CategoryShopify = "Shopify"
CategoryGeneric = "Generic"


def builtin_rules() -> list[Rule]:
    """The built-in ruleset. Order is significant only for output sorting."""
    S = Severity
    rules: list[Rule] = [
        # ----- cloud providers -------------------------------------------------
        _r("aws-access-key-id", CategoryAWS, "AWS Access Key ID", S.CRITICAL,
           ws(r"(?:A3T[0-9A-Z]|AKIA|AGPA|AIDA|AROA|AIPA|ANPA|ANVA|ASIA)[0-9A-Z]{16}"),
           ["AKIA", "AGPA", "AIDA", "AROA", "AIPA", "ANPA", "ANVA", "ASIA"],
           secret_group_name=SECRET_GROUP,
           allow_rules=[AllowRule(id="aws-example-key",
                                  description="AWS documentation example keys",
                                  regex=r"EXAMPLE")]),
        _r("aws-secret-access-key", CategoryAWS, "AWS Secret Access Key", S.CRITICAL,
           r"(?i)(?:^|[^0-9a-zA-Z])aws[_\-\.]{0,25}(?:secret|sk)?[_\-\.]{0,25}"
           r"(?:access)?[_\-\.]{0,25}key(?:[_\-\.]{0,2}id)?[\s:=\"']{1,10}"
           r"(?P<secret>[0-9a-zA-Z/+]{40})(?:[^0-9a-zA-Z/+]|$)",
           ["aws"], secret_group_name=SECRET_GROUP,
           allow_rules=[AllowRule(id="aws-example-secret",
                                  description="AWS documentation example secrets",
                                  regex=r"EXAMPLEKEY")]),
        _r("aws-mws-key", CategoryAWS, "AWS Marketplace Web Service key", S.HIGH,
           ws(r"amzn\.mws\.[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}"),
           ["amzn.mws"], secret_group_name=SECRET_GROUP),
        _r("gcp-api-key", CategoryGoogle, "Google API key", S.HIGH,
           ws(r"AIza[0-9A-Za-z_\-]{35}"), ["AIza"], secret_group_name=SECRET_GROUP),
        _r("gcp-service-account", CategoryGoogle, "Google service account credentials", S.CRITICAL,
           r"\"type\"\s*:\s*\"service_account\"", ["service_account"]),
        _r("alibaba-access-key-id", "Alibaba", "Alibaba Cloud AccessKey ID", S.HIGH,
           ws(r"LTAI[0-9a-zA-Z]{12,24}"), ["LTAI"], secret_group_name=SECRET_GROUP),
        _r("azure-storage-account-key", "Azure", "Azure Storage account key", S.CRITICAL,
           r"(?i)AccountKey\s*=\s*(?P<secret>[0-9a-zA-Z+/=]{88})",
           ["AccountKey"], secret_group_name=SECRET_GROUP),
        _r("digitalocean-pat", "DigitalOcean", "DigitalOcean personal access token", S.CRITICAL,
           ws(r"dop_v1_[a-f0-9]{64}"), ["dop_v1_"], secret_group_name=SECRET_GROUP),
        _r("digitalocean-oauth-token", "DigitalOcean", "DigitalOcean OAuth token", S.CRITICAL,
           ws(r"doo_v1_[a-f0-9]{64}"), ["doo_v1_"], secret_group_name=SECRET_GROUP),
        _r("digitalocean-refresh-token", "DigitalOcean", "DigitalOcean refresh token", S.HIGH,
           ws(r"dor_v1_[a-f0-9]{64}"), ["dor_v1_"], secret_group_name=SECRET_GROUP),
        _r("heroku-api-key", "Heroku", "Heroku API key", S.HIGH,
           r"(?i)heroku[a-z0-9_\-\s\"']{0,25}(?:=|>|:=|\|\|:|<=|=>|:)[\s\"']{0,5}"
           r"(?P<secret>[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12})",
           ["heroku"], secret_group_name=SECRET_GROUP),
        # ----- VCS / forges ----------------------------------------------------
        _r("github-pat", CategoryGitHub, "GitHub personal access token", S.CRITICAL,
           ws(r"ghp_[0-9a-zA-Z]{36}"), ["ghp_"], secret_group_name=SECRET_GROUP),
        _r("github-oauth", CategoryGitHub, "GitHub OAuth access token", S.CRITICAL,
           ws(r"gho_[0-9a-zA-Z]{36}"), ["gho_"], secret_group_name=SECRET_GROUP),
        _r("github-app-token", CategoryGitHub, "GitHub app token", S.CRITICAL,
           ws(r"(?:ghu|ghs)_[0-9a-zA-Z]{36}"), ["ghu_", "ghs_"], secret_group_name=SECRET_GROUP),
        _r("github-refresh-token", CategoryGitHub, "GitHub refresh token", S.CRITICAL,
           ws(r"ghr_[0-9a-zA-Z]{76}"), ["ghr_"], secret_group_name=SECRET_GROUP),
        _r("github-fine-grained-pat", CategoryGitHub, "GitHub fine-grained personal access token",
           S.CRITICAL, ws(r"github_pat_[0-9a-zA-Z_]{82}"), ["github_pat_"],
           secret_group_name=SECRET_GROUP),
        _r("gitlab-pat", CategoryGitLab, "GitLab personal access token", S.CRITICAL,
           ws(r"glpat-[0-9a-zA-Z_\-]{20}"), ["glpat-"], secret_group_name=SECRET_GROUP),
        _r("gitlab-runner-token", CategoryGitLab, "GitLab runner registration token", S.HIGH,
           ws(r"GR1348941[0-9a-zA-Z_\-]{20}"), ["GR1348941"], secret_group_name=SECRET_GROUP),
        _r("gitlab-pipeline-trigger-token", CategoryGitLab, "GitLab pipeline trigger token", S.HIGH,
           ws(r"glptt-[0-9a-f]{40}"), ["glptt-"], secret_group_name=SECRET_GROUP),
        # ----- key material ----------------------------------------------------
        _r("private-key", CategoryAsymmetricPrivateKey, "Asymmetric private key block", S.HIGH,
           r"-----BEGIN (?:RSA |EC |DSA |OPENSSH |PGP |ENCRYPTED )?PRIVATE KEY(?: BLOCK)?-----"
           r"(?P<secret>[\s\S]*?)-----END",
           ["-----BEGIN"], secret_group_name=SECRET_GROUP),
        _r("age-secret-key", "Age", "age encryption secret key", S.MEDIUM,
           ws(r"AGE-SECRET-KEY-1[0-9A-Z]{58}"), ["AGE-SECRET-KEY-1"],
           secret_group_name=SECRET_GROUP),
        _r("jwt-token", CategoryGeneric, "JSON Web Token", S.MEDIUM,
           ws(r"ey[a-zA-Z0-9_=]{14,}\.ey[a-zA-Z0-9_/+\-=]{14,}\.[a-zA-Z0-9_/+\-=]{10,}"),
           ["eyJ"], secret_group_name=SECRET_GROUP),
        # ----- chat / collaboration -------------------------------------------
        _r("slack-access-token", CategorySlack, "Slack token", S.HIGH,
           ws(r"xox[baprs]-(?:[0-9]{8,14}-){2,3}[0-9a-zA-Z]{18,34}"), ["xoxb-",
           "xoxa-", "xoxp-", "xoxr-", "xoxs-"], secret_group_name=SECRET_GROUP),
        _r("slack-app-token", CategorySlack, "Slack app-level token", S.HIGH,
           ws(r"xapp-[0-9]-[0-9A-Z]{8,12}-[0-9]{10,14}-[0-9a-f]{60,70}"), ["xapp-"],
           secret_group_name=SECRET_GROUP),
        _r("slack-web-hook", CategorySlack, "Slack incoming webhook URL", S.MEDIUM,
           r"https://hooks\.slack\.com/(?:services|workflows)/"
           r"[0-9A-Z]{8,12}/[0-9A-Z]{8,12}/[0-9a-zA-Z]{20,26}",
           ["hooks.slack.com"]),
        _r("discord-bot-token", "Discord", "Discord bot token", S.HIGH,
           r"(?i)discord[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}"
           r"(?P<secret>[MNO][a-zA-Z0-9_\-]{23,25}\.[a-zA-Z0-9_\-]{6}\.[a-zA-Z0-9_\-]{27,38})",
           ["discord"], secret_group_name=SECRET_GROUP),
        _r("telegram-bot-token", "Telegram", "Telegram bot token", S.HIGH,
           r"(?i)telegram[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}"
           r"(?P<secret>[0-9]{8,10}:[0-9A-Za-z_\-]{35})",
           ["telegram"], secret_group_name=SECRET_GROUP),
        # ----- payments --------------------------------------------------------
        _r("stripe-secret-token", CategoryStripe, "Stripe secret key", S.CRITICAL,
           ws(r"sk_(?:test|live)_[0-9a-zA-Z]{24,99}"), ["sk_test_", "sk_live_"],
           secret_group_name=SECRET_GROUP),
        _r("stripe-publishable-token", CategoryStripe, "Stripe publishable key", S.LOW,
           ws(r"pk_(?:test|live)_[0-9a-zA-Z]{24,99}"), ["pk_test_", "pk_live_"],
           secret_group_name=SECRET_GROUP),
        _r("square-access-token", "Square", "Square access token", S.HIGH,
           ws(r"sq0atp-[0-9A-Za-z_\-]{22}"), ["sq0atp-"], secret_group_name=SECRET_GROUP),
        _r("square-oauth-secret", "Square", "Square OAuth secret", S.HIGH,
           ws(r"sq0csp-[0-9A-Za-z_\-]{43}"), ["sq0csp-"], secret_group_name=SECRET_GROUP),
        _r("paypal-braintree-token", "PayPal", "Braintree access token", S.HIGH,
           ws(r"access_token\$production\$[0-9a-z]{16}\$[0-9a-f]{32}"),
           ["access_token$production$"], secret_group_name=SECRET_GROUP),
        _r("shopify-token", CategoryShopify, "Shopify token", S.CRITICAL,
           ws(r"shp(?:at|ca|pa|ss)_[0-9a-fA-F]{32}"),
           ["shpat_", "shpca_", "shppa_", "shpss_"], secret_group_name=SECRET_GROUP),
        # ----- email / messaging SaaS -----------------------------------------
        _r("sendgrid-api-token", "SendGrid", "SendGrid API key", S.HIGH,
           ws(r"SG\.[0-9A-Za-z_\-]{22}\.[0-9A-Za-z_\-]{43}"), ["SG."],
           secret_group_name=SECRET_GROUP),
        _r("mailgun-token", "Mailgun", "Mailgun private API token", S.HIGH,
           ws(r"key-[0-9a-f]{32}"), ["key-"], secret_group_name=SECRET_GROUP),
        _r("mailchimp-api-key", "Mailchimp", "Mailchimp API key", S.HIGH,
           ws(r"[0-9a-f]{32}-us[0-9]{1,2}"), ["-us"], secret_group_name=SECRET_GROUP),
        _r("twilio-api-key", "Twilio", "Twilio API key SID", S.HIGH,
           r"(?i)twilio[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}(?P<secret>SK[0-9a-f]{32})",
           ["twilio"], secret_group_name=SECRET_GROUP),
        # ----- package registries ---------------------------------------------
        _r("npm-access-token", "npm", "npm access token", S.CRITICAL,
           ws(r"npm_[0-9a-zA-Z]{36}"), ["npm_"], secret_group_name=SECRET_GROUP),
        _r("pypi-upload-token", "PyPI", "PyPI upload token", S.HIGH,
           r"pypi-AgEIcHlwaS5vcmc[0-9A-Za-z_\-]{50,1000}", ["pypi-AgEIcHlwaS5vcmc"]),
        _r("rubygems-api-token", "RubyGems", "RubyGems API key", S.HIGH,
           ws(r"rubygems_[0-9a-f]{48}"), ["rubygems_"], secret_group_name=SECRET_GROUP),
        _r("clojars-api-token", "Clojars", "Clojars API token", S.HIGH,
           r"CLOJARS_[0-9a-z]{60}", ["CLOJARS_"]),
        # ----- CI / infra SaaS -------------------------------------------------
        _r("databricks-api-token", "Databricks", "Databricks API token", S.HIGH,
           ws(r"dapi[0-9a-h]{32}"), ["dapi"], secret_group_name=SECRET_GROUP),
        _r("hashicorp-tf-api-token", "HashiCorp", "Terraform Cloud / Vault API token", S.HIGH,
           ws(r"[0-9a-zA-Z]{14}\.atlasv1\.[0-9a-zA-Z_\-]{60,70}"), [".atlasv1."],
           secret_group_name=SECRET_GROUP),
        _r("dockerhub-pat", "Docker", "Docker Hub personal access token", S.HIGH,
           ws(r"dckr_pat_[0-9a-zA-Z_\-]{27}"), ["dckr_pat_"], secret_group_name=SECRET_GROUP),
        _r("grafana-api-token", "Grafana", "Grafana API token", S.MEDIUM,
           ws(r"eyJrIjoi[0-9a-zA-Z_=\-]{60,100}"), ["eyJrIjoi"], secret_group_name=SECRET_GROUP),
        _r("grafana-service-account-token", "Grafana", "Grafana service account token", S.MEDIUM,
           ws(r"glsa_[0-9a-zA-Z_]{32}_[0-9a-f]{8}"), ["glsa_"], secret_group_name=SECRET_GROUP),
        _r("new-relic-user-api-key", "NewRelic", "New Relic user API key", S.MEDIUM,
           ws(r"NRAK-[0-9A-Z]{27}"), ["NRAK-"], secret_group_name=SECRET_GROUP),
        _r("datadog-access-token", "Datadog", "Datadog access token", S.MEDIUM,
           r"(?i)datadog[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}(?P<secret>[0-9a-f]{40})",
           ["datadog"], secret_group_name=SECRET_GROUP),
        _r("pulumi-api-token", "Pulumi", "Pulumi API token", S.HIGH,
           ws(r"pul-[0-9a-f]{40}"), ["pul-"], secret_group_name=SECRET_GROUP),
        _r("doppler-api-token", "Doppler", "Doppler API token", S.HIGH,
           ws(r"dp\.pt\.[0-9a-zA-Z]{43}"), ["dp.pt."], secret_group_name=SECRET_GROUP),
        _r("flyio-access-token", "Fly.io", "Fly.io access token", S.HIGH,
           ws(r"fo1_[0-9a-zA-Z_\-]{43}"), ["fo1_"], secret_group_name=SECRET_GROUP),
        # ----- AI / data SaaS --------------------------------------------------
        _r("openai-api-key", "OpenAI", "OpenAI API key", S.HIGH,
           ws(r"sk-[0-9a-zA-Z]{20}T3BlbkFJ[0-9a-zA-Z]{20}"), ["T3BlbkFJ"],
           secret_group_name=SECRET_GROUP),
        _r("hugging-face-access-token", "HuggingFace", "Hugging Face access token", S.HIGH,
           ws(r"hf_[a-zA-Z]{34}"), ["hf_"], secret_group_name=SECRET_GROUP),
        _r("anthropic-api-key", "Anthropic", "Anthropic API key", S.HIGH,
           ws(r"sk-ant-[a-zA-Z0-9_\-]{20,120}"), ["sk-ant-"], secret_group_name=SECRET_GROUP),
        # ----- misc SaaS -------------------------------------------------------
        _r("atlassian-api-token", "Atlassian", "Atlassian API token", S.HIGH,
           r"(?i)(?:atlassian|jira|confluence)[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}"
           r"(?P<secret>[a-zA-Z0-9]{24})(?:[^a-zA-Z0-9]|$)",
           ["atlassian", "jira", "confluence"], secret_group_name=SECRET_GROUP),
        _r("asana-access-token", "Asana", "Asana personal access token", S.MEDIUM,
           r"(?i)asana[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}"
           r"(?P<secret>[0-9]/[0-9]{10,16}:[0-9a-f]{32})",
           ["asana"], secret_group_name=SECRET_GROUP),
        _r("dropbox-short-lived-api-token", "Dropbox", "Dropbox short-lived API token", S.MEDIUM,
           ws(r"sl\.[0-9a-zA-Z_\-]{130,152}"), ["sl."], secret_group_name=SECRET_GROUP),
        _r("netlify-access-token", "Netlify", "Netlify access token", S.MEDIUM,
           r"(?i)netlify[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}"
           r"(?P<secret>[0-9a-zA-Z_\-]{40,46})",
           ["netlify"], secret_group_name=SECRET_GROUP),
        _r("linear-api-token", "Linear", "Linear API token", S.MEDIUM,
           ws(r"lin_api_[0-9a-zA-Z]{40}"), ["lin_api_"], secret_group_name=SECRET_GROUP),
        _r("postman-api-token", "Postman", "Postman API token", S.MEDIUM,
           ws(r"PMAK-[0-9a-f]{24}-[0-9a-f]{34}"), ["PMAK-"], secret_group_name=SECRET_GROUP),
        _r("sentry-access-token", "Sentry", "Sentry auth token", S.MEDIUM,
           r"(?i)sentry[a-z0-9_\-\s\"']{0,25}[=:][\s\"']{0,5}(?P<secret>[0-9a-f]{64})",
           ["sentry"], secret_group_name=SECRET_GROUP),
        _r("facebook-token", "Facebook", "Facebook access token", S.HIGH,
           ws(r"EAACEdEose0cBA[0-9A-Za-z]+"), ["EAACEdEose0cBA"], secret_group_name=SECRET_GROUP),
        _r("twitter-bearer-token", "Twitter", "Twitter/X bearer token", S.MEDIUM,
           ws(r"AAAAAAAAAAAAAAAAAAAAA[0-9a-zA-Z%]{60,120}"), ["AAAAAAAAAAAAAAAAAAAAA"],
           secret_group_name=SECRET_GROUP),
        # ----- SaaS breadth (reference rule-ID parity set) --------------------
        _r("adobe-client-id", "Adobe", "Adobe client ID (OAuth web)", S.MEDIUM,
           kw("adobe", r"[a-f0-9]{32}", "a-f0-9"),
           ["adobe"], secret_group_name=SECRET_GROUP),
        _r("adobe-client-secret", "Adobe", "Adobe client secret", S.HIGH,
           ws(r"p8e-[a-z0-9]{32}"), ["p8e-"], secret_group_name=SECRET_GROUP),
        _r("alibaba-secret-key", "Alibaba", "Alibaba Cloud AccessKey secret", S.CRITICAL,
           kw("alibaba", r"[a-zA-Z0-9]{30}", "a-zA-Z0-9"),
           ["alibaba"], secret_group_name=SECRET_GROUP),
        _r("asana-client-id", "Asana", "Asana client ID", S.MEDIUM,
           kw("asana", r"[0-9]{16}", "0-9"),
           ["asana"], secret_group_name=SECRET_GROUP),
        _r("asana-client-secret", "Asana", "Asana client secret", S.HIGH,
           kw("asana", r"[a-z0-9]{32}", "a-z0-9"),
           ["asana"], secret_group_name=SECRET_GROUP),
        _r("beamer-api-token", "Beamer", "Beamer API token", S.MEDIUM,
           kw("beamer", r"b_[a-z0-9=_\-]{44}"),
           ["beamer"], secret_group_name=SECRET_GROUP),
        _r("bitbucket-client-id", "Bitbucket", "Bitbucket client ID", S.MEDIUM,
           kw("bitbucket", r"[a-zA-Z0-9]{32}", "a-zA-Z0-9"),
           ["bitbucket"], secret_group_name=SECRET_GROUP),
        _r("bitbucket-client-secret", "Bitbucket", "Bitbucket client secret", S.HIGH,
           kw("bitbucket", r"[a-zA-Z0-9=_\-]{64}", r"a-zA-Z0-9=_\-"),
           ["bitbucket"], secret_group_name=SECRET_GROUP),
        _r("contentful-delivery-api-token", "Contentful", "Contentful delivery API token",
           S.MEDIUM, ws(r"CFPAT-[a-zA-Z0-9_\-]{43}"), ["CFPAT-"],
           secret_group_name=SECRET_GROUP),
        _r("discord-api-token", "Discord", "Discord API key", S.HIGH,
           kw("discord", r"[a-f0-9]{64}", "a-f0-9"),
           ["discord"], secret_group_name=SECRET_GROUP),
        _r("discord-client-id", "Discord", "Discord client ID", S.LOW,
           kw("discord", r"[0-9]{18}", "0-9"),
           ["discord"], secret_group_name=SECRET_GROUP),
        _r("discord-client-secret", "Discord", "Discord client secret", S.HIGH,
           kw("discord", r"[a-zA-Z0-9=_\-]{32}", r"a-zA-Z0-9=_\-"),
           ["discord"], secret_group_name=SECRET_GROUP),
        _r("dockerconfig-secret", "Docker", "Dockerconfig secret", S.HIGH,
           r"(?i)(?:\.dockerconfigjson|\.dockercfg)[\s\"']{0,5}:[\s\"']{0,5}"
           r"(?P<secret>[A-Za-z0-9+/=]{40,4000})",
           [".dockerconfigjson", ".dockercfg"], secret_group_name=SECRET_GROUP),
        _r("dropbox-api-secret", "Dropbox", "Dropbox API secret", S.HIGH,
           kw("dropbox", r"[a-z0-9]{15}", "a-z0-9"),
           ["dropbox"], secret_group_name=SECRET_GROUP),
        _r("dropbox-long-lived-api-token", "Dropbox", "Dropbox long-lived API token", S.HIGH,
           kw("dropbox", r"[a-z0-9]{11}(?:AAAAAAAAAA)[a-z0-9\-_=]{43}"),
           ["dropbox"], secret_group_name=SECRET_GROUP),
        _r("duffel-api-token", "Duffel", "Duffel API token", S.HIGH,
           ws(r"duffel_(?:test|live)_[a-zA-Z0-9_\-=]{43}"), ["duffel_"],
           secret_group_name=SECRET_GROUP),
        _r("dynatrace-api-token", "Dynatrace", "Dynatrace API token", S.HIGH,
           ws(r"dt0c01\.[a-zA-Z0-9]{24}\.[a-z0-9]{64}"), ["dt0c01."],
           secret_group_name=SECRET_GROUP),
        _r("easypost-api-token", "EasyPost", "EasyPost API token", S.HIGH,
           ws(r"EZ[AT]K[a-zA-Z0-9]{54}"), ["EZAK", "EZTK"],
           secret_group_name=SECRET_GROUP),
        _r("fastly-api-token", "Fastly", "Fastly API token", S.HIGH,
           kw("fastly", r"[a-zA-Z0-9=_\-]{32}", r"a-zA-Z0-9=_\-"),
           ["fastly"], secret_group_name=SECRET_GROUP),
        _r("finicity-api-token", "Finicity", "Finicity API token", S.HIGH,
           kw("finicity", r"[a-f0-9]{32}", "a-f0-9"),
           ["finicity"], secret_group_name=SECRET_GROUP),
        _r("finicity-client-secret", "Finicity", "Finicity client secret", S.HIGH,
           kw("finicity", r"[a-z0-9]{20}", "a-z0-9"),
           ["finicity"], secret_group_name=SECRET_GROUP),
        _r("flutterwave-enc-key", "Flutterwave", "Flutterwave encryption key", S.HIGH,
           ws(r"FLWSECK_TEST-[a-h0-9]{12}"), ["FLWSECK_TEST"],
           secret_group_name=SECRET_GROUP),
        _r("flutterwave-public-key", "Flutterwave", "Flutterwave public key", S.MEDIUM,
           ws(r"FLWPUBK_TEST-[a-h0-9]{32}-X"), ["FLWPUBK_TEST"],
           secret_group_name=SECRET_GROUP),
        _r("frameio-api-token", "Frame.io", "Frame.io API token", S.HIGH,
           ws(r"fio-u-[a-zA-Z0-9\-_=]{64}"), ["fio-u-"], secret_group_name=SECRET_GROUP),
        _r("gocardless-api-token", "GoCardless", "GoCardless API token", S.HIGH,
           kw("gocardless", r"live_[a-zA-Z0-9\-_=]{40}"),
           ["gocardless"], secret_group_name=SECRET_GROUP),
        _r("hubspot-api-token", "HubSpot", "HubSpot API token", S.HIGH,
           kw("hubspot",
              r"[a-h0-9]{8}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{12}"),
           ["hubspot"], secret_group_name=SECRET_GROUP),
        _r("intercom-api-token", "Intercom", "Intercom API token", S.HIGH,
           kw("intercom", r"[a-zA-Z0-9=_]{60}", "a-zA-Z0-9=_"),
           ["intercom"], secret_group_name=SECRET_GROUP),
        _r("intercom-client-secret", "Intercom", "Intercom client secret/ID", S.HIGH,
           kw("intercom",
              r"[a-h0-9]{8}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{12}"),
           ["intercom"], secret_group_name=SECRET_GROUP),
        _r("ionic-api-token", "Ionic", "Ionic API token", S.HIGH,
           ws(r"ion_[a-z0-9]{42}"), ["ion_"], secret_group_name=SECRET_GROUP),
        _r("linear-client-secret", "Linear", "Linear client secret", S.HIGH,
           kw("linear", r"[a-f0-9]{32}", "a-f0-9"),
           ["linear"], secret_group_name=SECRET_GROUP),
        _r("linkedin-client-id", "LinkedIn", "LinkedIn client ID", S.MEDIUM,
           kw(r"linked[_\-]?in", r"[a-z0-9]{14}", "a-z0-9"),
           ["linkedin", "linked_in", "linked-in"], secret_group_name=SECRET_GROUP),
        _r("linkedin-client-secret", "LinkedIn", "LinkedIn client secret", S.HIGH,
           kw(r"linked[_\-]?in", r"[a-z0-9]{16}", "a-z0-9"),
           ["linkedin", "linked_in", "linked-in"], secret_group_name=SECRET_GROUP),
        _r("lob-api-key", "Lob", "Lob API key", S.HIGH,
           kw("lob", r"(?:live|test)_[a-f0-9]{35}"),
           ["lob"], secret_group_name=SECRET_GROUP),
        _r("lob-pub-api-key", "Lob", "Lob publishable API key", S.MEDIUM,
           kw("lob", r"(?:test|live)_pub_[a-f0-9]{31}"),
           ["lob"], secret_group_name=SECRET_GROUP),
        _r("mailgun-signing-key", "Mailgun", "Mailgun webhook signing key", S.HIGH,
           kw("mailgun", r"[a-h0-9]{32}-[a-h0-9]{8}-[a-h0-9]{8}"),
           ["mailgun"], secret_group_name=SECRET_GROUP),
        _r("mapbox-api-token", "Mapbox", "Mapbox API token", S.MEDIUM,
           kw("mapbox", r"pk\.[a-z0-9]{60}\.[a-z0-9]{22}"),
           ["mapbox"], secret_group_name=SECRET_GROUP),
        _r("messagebird-api-token", "MessageBird", "MessageBird API token", S.HIGH,
           kw(r"message[_\-]?bird", r"[a-z0-9]{25}", "a-z0-9"),
           ["messagebird", "message_bird", "message-bird"],
           secret_group_name=SECRET_GROUP),
        _r("messagebird-client-id", "MessageBird", "MessageBird client ID", S.MEDIUM,
           kw(r"message[_\-]?bird",
              r"[a-h0-9]{8}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{4}-[a-h0-9]{12}"),
           ["messagebird", "message_bird", "message-bird"],
           secret_group_name=SECRET_GROUP),
        _r("new-relic-browser-api-token", "NewRelic", "New Relic ingest browser API token",
           S.MEDIUM, ws(r"NRJS-[a-f0-9]{19}"), ["NRJS-"], secret_group_name=SECRET_GROUP),
        _r("new-relic-user-api-id", "NewRelic", "New Relic user API ID", S.MEDIUM,
           kw(r"(?:new[_\-]?relic|nrak)", r"[A-Z0-9]{64}", "A-Z0-9"),
           ["newrelic", "new_relic", "new-relic", "nrak"],
           secret_group_name=SECRET_GROUP),
        _r("planetscale-api-token", "PlanetScale", "PlanetScale API token", S.HIGH,
           ws(r"pscale_tkn_[a-zA-Z0-9\-_\.]{43}"), ["pscale_tkn_"],
           secret_group_name=SECRET_GROUP),
        _r("planetscale-password", "PlanetScale", "PlanetScale password", S.HIGH,
           ws(r"pscale_pw_[a-zA-Z0-9\-_\.]{43}"), ["pscale_pw_"],
           secret_group_name=SECRET_GROUP),
        _r("private-packagist-token", "Packagist", "Private Packagist token", S.HIGH,
           ws(r"packagist_[ou][ru]t_[a-f0-9]{68}"), ["packagist_"],
           secret_group_name=SECRET_GROUP),
        _r("sendinblue-api-token", "Sendinblue", "Sendinblue API token", S.HIGH,
           ws(r"xkeysib-[a-f0-9]{64}-[a-zA-Z0-9]{16}"), ["xkeysib-"],
           secret_group_name=SECRET_GROUP),
        _r("shippo-api-token", "Shippo", "Shippo API token", S.HIGH,
           ws(r"shippo_(?:live|test)_[a-f0-9]{40}"), ["shippo_"],
           secret_group_name=SECRET_GROUP),
        _r("twitch-api-token", "Twitch", "Twitch API token", S.HIGH,
           kw("twitch", r"[a-z0-9]{30}", "a-z0-9"),
           ["twitch"], secret_group_name=SECRET_GROUP),
        _r("twitter-token", "Twitter", "Twitter token", S.MEDIUM,
           kw("twitter", r"[a-z0-9]{35,44}", "a-z0-9"),
           ["twitter"], secret_group_name=SECRET_GROUP),
        _r("typeform-api-token", "Typeform", "Typeform API token", S.MEDIUM,
           ws(r"tfp_[a-z0-9\-_\.=]{59}"), ["tfp_"], secret_group_name=SECRET_GROUP),
        # ----- generic fallbacks ----------------------------------------------
        _r("basic-auth-url", CategoryGeneric, "Credentials embedded in URL", S.HIGH,
           r"[a-zA-Z][a-zA-Z0-9+.\-]{1,9}://[^/\s:@\"']{1,64}:(?P<secret>[^/\s:@\"']{3,64})@"
           r"[0-9a-zA-Z\-_.]{1,128}",
           ["://"], secret_group_name=SECRET_GROUP,
           allow_rules=[
               AllowRule(id="url-placeholder-password",
                         description="templated / placeholder credentials",
                         regex=r"^(?:\$|%s|%v|\{\{|<|\[)"),
           ]),
        _r("generic-api-key", CategoryGeneric, "Generic API key assignment", S.MEDIUM,
           r"(?i)(?:api[_\-]?key|apikey|secret[_\-]?key|auth[_\-]?token|access[_\-]?token)"
           r"[a-z0-9_\-\s\"']{0,10}[=:][\s\"']{0,5}"
           r"(?P<secret>[0-9a-zA-Z_\-]{20,64})(?:[\"'\s]|$)",
           ["api_key", "apikey", "api-key", "secret_key", "secret-key",
            "auth_token", "auth-token", "access_token", "access-token"],
           secret_group_name=SECRET_GROUP,
           allow_rules=[
               AllowRule(id="generic-placeholder",
                         description="placeholder values (matched against the extracted secret)",
                         regex=r"(?i)^(?:x{8,}|\*{8,}|(?:your|my|the|an?|some|this|change|replace|dummy|fake|test|example|sample|placeholder|insert)[_\-]?[a-z_\-]*|[0-9a-zA-Z_\-]*(?:example|sample|placeholder|changeme|xxxxx)[0-9a-zA-Z_\-]*)$"),
           ]),
    ]
    return rules


def builtin_allow_rules() -> list[AllowRule]:
    """Global path allowlist (ref: pkg/fanal/secret/builtin-allow-rules.go:3-65):
    test/example/vendored/system trees where findings are overwhelmingly noise."""
    return [
        AllowRule(id="tests", description="test fixtures",
                  path=r"(?:^|/)(?:tests?|testing|testdata|spec|specs)/"),
        AllowRule(id="examples", description="example code",
                  path=r"(?:^|/)examples?/"),
        AllowRule(id="vendor", description="vendored dependencies",
                  path=r"(?:^|/)(?:vendor|third_party|thirdparty|node_modules)/"),
        AllowRule(id="usr-dirs", description="system binary/library trees",
                  path=r"^usr/(?:share|include|lib)/"),
        AllowRule(id="locale-dir", description="locale data",
                  path=r"(?:^|/)locale/"),
        AllowRule(id="markdown", description="documentation",
                  path=r"\.(?:md|markdown|rst)$"),
        AllowRule(id="golang-dir", description="go module cache",
                  path=r"(?:^|/)go/pkg/mod/"),
        AllowRule(id="python-dist", description="python runtime/dist dirs",
                  path=r"(?:^|/)(?:site-packages|dist-packages|\.venv|venv)/"),
        AllowRule(id="ruby-gems", description="installed ruby gems",
                  path=r"(?:^|/)gems/[^/]+/(?:lib|spec|test)/"),
        AllowRule(id="wordpress-core", description="wordpress core", path=r"(?:^|/)wp-includes/"),
        AllowRule(id="anaconda-dir", description="conda packages", path=r"(?:^|/)pkgs/[^/]+/info/"),
        AllowRule(id="minified-js", description="minified/bundled javascript",
                  path=r"\.(?:min\.js|js\.map)$"),
    ]
