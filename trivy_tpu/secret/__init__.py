"""Secret scanning engine.

Behavioral model: the reference's ``pkg/fanal/secret`` (scanner at ref:
pkg/fanal/secret/scanner.go:377-463): per-file keyword prefilter, per-rule
regex matching with allow-rules, exclude blocks, censoring and ±2-line code
context. Here the hot loop is re-architected for TPU: a batched keyword
prefilter (one-hot matmul on the MXU) plus a multi-pattern DFA over fixed-size
overlapping chunks, with exact host-side confirmation so findings stay
byte-identical to the pure-CPU engine.
"""

from trivy_tpu.secret.rules import AllowRule, Rule, builtin_allow_rules, builtin_rules
from trivy_tpu.secret.engine import SecretScanner, ScannerConfig

__all__ = [
    "AllowRule",
    "Rule",
    "builtin_allow_rules",
    "builtin_rules",
    "SecretScanner",
    "ScannerConfig",
]
