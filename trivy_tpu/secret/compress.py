"""Compressed slab wire format: host-side codec for the secret feed.

The e2e ceiling is the serialized host→device link (BENCH_r05: kernel
~900 MB/s, link ~10 MB/s), so the only remaining multiplier on that
harness is shipping fewer bytes per scanned byte. This module is the
host half of that lever: the feeder compresses assembled arena slabs
with a deliberately *decoder-shaped* codec — every row decodes with a
fixed-shape vectorizable kernel (``ops/decompress.py``), which rules out
general LZ (back-references serialize the decode) in favor of three
per-row modes a byte-class gate picks between:

- **TOKEN** (RLE + static byte-pair dictionary): one output byte per
  token. Tokens 0x00–0x7F are literals; 0x80–0x87 expand to a run of 8
  of a common filler byte (zero guard gaps / pack-row tails, NUL pages,
  indentation); 0x88–0xFF expand to one of 120 static common byte pairs
  (English + source-code digraphs). The decoder is a per-token length
  table, an exclusive cumsum for output positions, and
  ``max-expansion``-many masked scatters — fixed shape, no data-dependent
  control flow. Wins on real text and on packed/tail rows that are
  mostly zeros (a zero row compresses 8×).
- **PACK7** (printable-class 7-bit packing): rows whose every byte is
  < 0x80 pack 8 bytes into 7 — a guaranteed 0.875 ratio even on
  incompressible printable data (the bench lure corpus is uniform random
  printable, where a pair dictionary alone saves ~1%). Decode is a pure
  fixed-position gather + shift.
- **RAW**: rows with any byte ≥ 0x80 (the binary gate) ship verbatim
  inside the compressed frame; a whole batch whose total wire size
  can't beat the configured ratio budget ships as a plain raw slab
  (per-batch fallback — the decompress stage never runs for it).

The codec is *framing only*: compressed rows hash (dedup) and resolve
against their **uncompressed** content, so dedup keys, the hit cache,
and every verdict are codec-invariant. Any encode error degrades the
batch to a raw slab; any irrecoverable device state degrades through
the existing retry/OOM-split/host-fallback ladder with the batch
host-decoded back to raw rows first (``SlabCodec.decode_slab`` is the
reference decoder the device kernel must match byte-for-byte — the
fuzz tests in ``tests/test_compress.py`` pin both).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "MODE_RAW", "MODE_PACK7", "MODE_TOKEN", "MAX_EXPANSION",
    "COMPRESS_MIN_RATIO", "CompressedSlab", "SlabCodec",
]

MODE_RAW = 0
MODE_PACK7 = 1
MODE_TOKEN = 2

# widest token expansion (the run tokens): bounds the decoder's scatter
# unroll and the host reference decoder's per-position loop
MAX_EXPANSION = 8

# default per-batch wire budget as a fraction of the raw (bucketed) slab:
# a compressed batch must fit in min_ratio * rows * chunk_len or the whole
# batch ships raw. 0.875 is the PACK7 line — an all-printable batch always
# fits exactly, so "compression must beat raw by >= 12.5% or not bother"
COMPRESS_MIN_RATIO = 0.875

# filler bytes worth a run-of-8 token (0x80..0x87): zero pages / guard
# gaps / pack tails, then the common text/source fillers
RUN_BYTES = (0x00, 0x20, 0x0A, 0x09, 0x2D, 0x3D, 0x23, 0x2A)

# 120 static byte pairs (0x88..0xFF): English digraphs + source/config
# idiom. Static by design — a per-corpus dictionary would have to ship
# with every batch and flip dedup keys; this one is part of the codec.
_PAIRS = (
    "e ", " t", "th", "he", "s ", " a", "in", "er", "an", "re",
    "on", " s", "t ", "en", "at", "or", "es", " c", "it", "is",
    "te", "d ", "ar", "nd", " o", "al", " p", "st", "to", "nt",
    "ng", "se", "ha", "as", "ou", "io", "le", "o ", " m", " f",
    " w", "ve", "co", "me", "de", "hi", "ri", "ro", "ic", "ne",
    "ea", "ra", "ce", "li", "ch", "ll", " b", " d", "ma", "n ",
    "ti", "om", "ur", "r ", "la", "ed", "y ", "el", "ec", "un",
    " i", "no", "ns", "et", "il", "pe", "us", "na", "ss", "ni",
    "ol", "ot", "tr", "lo", "ac", "ca", "ut", "g ", "ly", "sa",
    "em", "po", "ke", "ey", "id", "ge", "ia", "so", "fo", "mo",
    "rt", "we", "ho", "wa", "pr", "ad", "ai", "di", "si", "ul",
    '="', '":', '",', "//", "--", "==", "()", "{}", "[]", ";\n",
)

_SENT = np.uint16(0xFFFF)  # suppressed slot in the token-stream layout


def _build_tables():
    """Static expansion/lookup tables shared by the encoder, the host
    reference decoder, and the device kernel (which closes over copies)."""
    assert len(RUN_BYTES) == 8 and len(_PAIRS) == 120
    tab_bytes = np.zeros((256, MAX_EXPANSION), dtype=np.uint8)
    tab_len = np.zeros(256, dtype=np.int32)
    for t in range(128):  # literals
        tab_bytes[t, 0] = t
        tab_len[t] = 1
    run_map = np.zeros(256, dtype=np.uint8)  # byte -> run token (0 = none)
    for i, b in enumerate(RUN_BYTES):
        tok = 0x80 + i
        tab_bytes[tok, :] = b
        tab_len[tok] = MAX_EXPANSION
        run_map[b] = tok
    pair_map = np.zeros(65536, dtype=np.uint8)  # (b0<<8)|b1 -> token
    for j, p in enumerate(_PAIRS):
        tok = 0x88 + j
        b0, b1 = ord(p[0]), ord(p[1])
        assert b0 < 0x80 and b1 < 0x80
        tab_bytes[tok, 0] = b0
        tab_bytes[tok, 1] = b1
        tab_len[tok] = 2
        pair_map[(b0 << 8) | b1] = tok
    return tab_bytes, tab_len, run_map, pair_map


@dataclass
class CompressedSlab:
    """One batch in wire form: a flat compressed buffer (bucketed to a
    compile-once rung) plus per-row framing. Rows past ``n_rows`` are
    bucket padding (``clen`` 0 → they decode to zero rows, exactly like
    raw-path pad rows). ``shape`` mirrors the raw batch the decompress
    stage expands to, so shape-keyed call sites need no special case."""

    buf: np.ndarray    # uint8 [wire_rung] — concatenated per-row streams
    offs: np.ndarray   # int32 [rows_pad] — row start offsets into buf
    clen: np.ndarray   # int32 [rows_pad] — per-row compressed length
    mode: np.ndarray   # uint8 [rows_pad] — MODE_RAW / MODE_PACK7 / MODE_TOKEN
    n_rows: int        # live rows (== len(batch meta))
    rows_pad: int      # bucketed row count
    chunk_len: int
    wire_bytes: int    # actual compressed payload (sum of clen)

    @property
    def shape(self) -> tuple[int, int]:
        return (self.rows_pad, self.chunk_len)

    def frame_bytes(self) -> int:
        """Link bytes of the per-row framing arrays themselves."""
        return self.offs.nbytes + self.clen.nbytes + self.mode.nbytes

    def arrays(self) -> tuple[np.ndarray, ...]:
        return (self.buf, self.offs, self.clen, self.mode)


@dataclass
class _Plan:
    """Per-row encode decision for one slab (phase 1 of 2): everything
    needed to size the wire before any byte is written, so the raw
    fallback costs no stream build and no destination buffer."""

    rows: np.ndarray
    binary: np.ndarray   # bool [n] — binary-gated rows (ship RAW)
    is_run: np.ndarray   # bool [n, C/8] — all-equal runnable blocks
    ptok: np.ndarray     # uint8 [n, C/2] — pair token per even pair (0=none)
    clen: np.ndarray     # int64 [n] — chosen wire length per row
    mode: np.ndarray     # uint8 [n]

    def total(self) -> int:
        return int(self.clen.sum())


class SlabCodec:
    """Vectorized slab encoder + host reference decoder.

    One instance per scanner (the zero-cost-when-off bar: a scanner with
    compression off never builds these tables). ``chunk_len`` must be a
    multiple of 8 (PACK7 packs bit-exact octets; every shipped chunk_len
    is).
    """

    def __init__(self, chunk_len: int):
        if chunk_len % 8:
            raise ValueError(
                f"SlabCodec needs chunk_len % 8 == 0, got {chunk_len}"
            )
        self.chunk_len = chunk_len
        self.pack7_len = chunk_len * 7 // 8
        self.tab_bytes, self.tab_len, self.run_map, self.pair_map = (
            _build_tables()
        )

    # -- encode -----------------------------------------------------------

    def plan(self, rows: np.ndarray) -> _Plan:
        """Phase 1: pick a mode and wire length per row (no bytes moved).
        ``rows`` is the live [n, chunk_len] uint8 slab prefix."""
        n, C = rows.shape
        binary = (rows > 0x7F).any(axis=1)
        blocks = rows.reshape(n, C // 8, 8)
        first = blocks[:, :, 0]
        is_run = (blocks == first[:, :, None]).all(axis=2) & (
            self.run_map[first] != 0
        )
        pair = (rows[:, 0::2].astype(np.uint16) << 8) | rows[:, 1::2]
        ptok = self.pair_map[pair]  # [n, C/2]
        pair_len = np.where(ptok != 0, 1, 2).astype(np.int32)
        blk_len = np.where(
            is_run, 1, pair_len.reshape(n, C // 8, 4).sum(axis=2)
        )
        token_len = blk_len.sum(axis=1, dtype=np.int64)
        clen = np.where(
            binary, C, np.minimum(token_len, self.pack7_len)
        ).astype(np.int64)
        mode = np.where(
            binary,
            MODE_RAW,
            np.where(token_len < self.pack7_len, MODE_TOKEN, MODE_PACK7),
        ).astype(np.uint8)
        return _Plan(rows, binary, is_run, ptok, clen, mode)

    def emit(
        self, plan: _Plan, rows_pad: int, rung: int, out: np.ndarray
    ) -> CompressedSlab:
        """Phase 2: write every row's stream into ``out`` (a flat uint8
        buffer of >= ``rung`` bytes — the feeder hands a spare arena
        slab's flat view, so the wire stays in pinned, reused memory)
        and return the framed batch. ``rung`` is the compile-once wire
        bucket the caller picked (>= plan.total())."""
        rows = plan.rows
        n, C = rows.shape
        total = plan.total()
        if total > rung or rung > out.size:
            raise ValueError(
                f"wire rung {rung} cannot hold {total} bytes "
                f"(out buffer: {out.size})"
            )
        offs = np.zeros(rows_pad, dtype=np.int32)
        clen = np.zeros(rows_pad, dtype=np.int32)
        mode = np.zeros(rows_pad, dtype=np.uint8)
        clen[:n] = plan.clen
        mode[:n] = plan.mode
        offs[1 : n + 1 if n < rows_pad else n] = np.cumsum(plan.clen)[
            : rows_pad - 1 if n == rows_pad else n
        ]
        # (pad rows keep offs 0 / clen 0: they decode to zero rows)

        sel_p = np.nonzero(plan.mode == MODE_PACK7)[0]
        packed = self._pack7(rows[sel_p]) if len(sel_p) else None
        stream = self._token_streams(plan) if (plan.mode == MODE_TOKEN).any() else None
        for i in range(n):
            o, c = offs[i], clen[i]
            m = plan.mode[i]
            if m == MODE_RAW:
                out[o : o + C] = rows[i]
            elif m == MODE_PACK7:
                out[o : o + c] = packed[np.searchsorted(sel_p, i)]
            else:
                flat, keep = stream
                out[o : o + c] = flat[i][keep[i]].astype(np.uint8)
        return CompressedSlab(
            buf=out[:rung], offs=offs, clen=clen, mode=mode,
            n_rows=n, rows_pad=rows_pad, chunk_len=C, wire_bytes=total,
        )

    def _token_streams(self, plan: _Plan):
        """Slot layout for the TOKEN rows of a slab, fully vectorized:
        each even byte pair owns two uint16 slots — ``[pair_token, ✗]``
        or ``[lit0, lit1]`` — and a run block's first pair carries the
        run token with every other slot suppressed. The per-row stream
        is the unsuppressed slots in order (one boolean take per row)."""
        rows, ptok, is_run = plan.rows, plan.ptok, plan.is_run
        n, C = rows.shape
        e0 = np.where(ptok != 0, ptok.astype(np.uint16), rows[:, 0::2])
        e1 = np.where(ptok != 0, _SENT, rows[:, 1::2].astype(np.uint16))
        run_pair = np.repeat(is_run, 4, axis=1)  # [n, C/2]
        first_pair = np.zeros(C // 2, dtype=bool)
        first_pair[0::4] = True
        run_tok = np.repeat(
            self.run_map[rows[:, 0::8]], 4, axis=1
        ).astype(np.uint16)
        e0 = np.where(run_pair, np.where(first_pair, run_tok, _SENT), e0)
        e1 = np.where(run_pair, _SENT, e1)
        flat = np.stack([e0, e1], axis=2).reshape(n, C)
        return flat, flat != _SENT

    def _pack7(self, rows: np.ndarray) -> np.ndarray:
        """[m, C] printable rows -> [m, 7C/8]: drop every byte's MSB
        (guaranteed 0 by the binary gate) and repack big-endian."""
        m, C = rows.shape
        bits = np.unpackbits(rows, axis=1).reshape(m, C, 8)[:, :, 1:]
        return np.packbits(bits.reshape(m, C * 7), axis=1)

    # -- host reference decode --------------------------------------------

    def _unpack7(self, comp: np.ndarray) -> np.ndarray:
        C = self.chunk_len
        bits = np.unpackbits(comp)[: C * 7].reshape(C, 7)
        full = np.concatenate(
            [np.zeros((C, 1), dtype=np.uint8), bits], axis=1
        )
        return np.packbits(full, axis=1).ravel()

    def _untoken(self, comp: np.ndarray) -> np.ndarray:
        C = self.chunk_len
        lens = self.tab_len[comp]
        pos = np.cumsum(lens) - lens
        out = np.zeros(C + MAX_EXPANSION, dtype=np.uint8)
        for k in range(MAX_EXPANSION):
            sel = lens > k
            out[pos[sel] + k] = self.tab_bytes[comp[sel], k]
        return out[:C]

    def decode_rows(
        self, buf: np.ndarray, offs, clen, mode, n_rows: int | None = None
    ) -> np.ndarray:
        """Reference decoder: the pure-numpy mirror of the device kernel.
        Used by the retry ladder (a failed compressed batch re-dispatches
        as raw rows) and as the parity oracle in the codec fuzz tests."""
        rows_pad = len(offs)
        n = rows_pad if n_rows is None else n_rows
        out = np.zeros((rows_pad, self.chunk_len), dtype=np.uint8)
        for i in range(n):
            c = np.asarray(buf[offs[i] : offs[i] + clen[i]])
            if clen[i] == 0:
                continue
            if mode[i] == MODE_RAW:
                out[i, : len(c)] = c
            elif mode[i] == MODE_PACK7:
                out[i] = self._unpack7(c)
            else:
                out[i] = self._untoken(c)
        return out

    def decode_slab(self, cs: CompressedSlab) -> np.ndarray:
        return self.decode_rows(
            cs.buf, cs.offs, cs.clen, cs.mode, n_rows=cs.n_rows
        )
