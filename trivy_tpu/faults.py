"""Deterministic fault-injection registry (the chaos harness).

PAPER.md §7's accelerator path adds failure domains the reference never
had — device OOM, tunnel resets, preemption, dropped cache connections —
and the retry/breaker/fallback ladder that survives them needs a way to be
*proven* without real hardware faults. This registry arms scripted failures
at named sites: instrumented code calls :func:`check(site, key=...)` on its
hot path (one module-global ``None`` check when disarmed), and an armed plan
raises the scripted exception at exactly the Nth hit of that site, so chaos
tests and the bench chaos rep are deterministic and replayable.

Instrumented sites (key in parentheses):

- ``device.dispatch`` (``d<i>`` per device stream, ``license`` for the
  license scorer) — host→device batch dispatch
- ``device.fetch`` (``d<i>``) — blocking device-result fetch
- ``cache.redis.get`` / ``cache.redis.set`` (cache key) — redis commands
- ``rpc.post`` (route path) — one client HTTP attempt
- ``walker.read`` (relative path) — file read between walk and analysis
- ``misconf.eval`` (file path) — per-file misconfiguration evaluation
- ``admission.enqueue`` (tenant name) — job enqueue into the server's
  admission queue (a fault here must shed loudly, never crash the server)
- ``admission.dequeue`` (tenant name) — job handoff from queue to worker
  (a fault here must fail that one job terminally, never wedge the queue)
- ``job.result.fetch`` (job id) — async job result lookup
- ``fleet.dispatch`` (replica address) — coordinator-side shard dispatch
  to one fleet replica (a fault here must re-dispatch to a survivor via
  the per-replica breaker, never fail the scan)
- ``fleet.steal`` (stealing replica address) — work-steal handoff of a
  queued shard (a fault here must requeue the shard, never lose it)
- ``fleet.result`` (shard index) — coordinator-side shard result fold (a
  fault here counts as a failed attempt and re-dispatches that one shard)
- ``fleet.register`` (joining replica address) — live replica join on the
  elastic control plane (a fault here must refuse the join loudly and
  leave the running fan-out untouched)
- ``fleet.drain`` (draining replica address) — queued-shard hand-back
  when a replica reports draining (a fault here must fall back to the
  breaker ladder — the shard re-dispatches as a plain failure, never
  lost, never double-completed)
- ``fleet.split`` (shard index) — mid-scan straggler split at a
  directory boundary (a fault here must abandon the split and leave the
  original in-flight attempt racing as before)

Spec grammar (``--fault-inject`` / ``TRIVY_TPU_FAULT_INJECT``), clauses
comma-separated::

    site[@key][:at=N][:times=M][:rate=P][:error=KIND]   |   seed=N

- ``@key``    only hits with this key fault (omitted = every key)
- ``at=N``    first faulting hit, 1-based per (site, key) counter (default 1)
- ``times=M`` consecutive faulting hits from ``at`` (default 1; -1 = forever)
- ``rate=P``  instead of at/times: fault each hit with probability P,
  decided by a keyed hash of (seed, site, key, hit#) — deterministic for a
  fixed seed, independent of thread interleaving within one (site, key)
- ``error=KIND`` — ``fault`` (RuntimeError, default), ``oom`` (an
  RESOURCE_EXHAUSTED-shaped RuntimeError the retry ladder answers with
  batch halving), ``conn`` (ConnectionError), ``io`` (OSError)

Examples::

    device.dispatch:at=3            # 3rd dispatch anywhere fails once
    device.dispatch@d3:times=-1     # device 3 is permanently dead
    device.dispatch:at=1:error=oom  # first batch OOMs (ladder must split)
    cache.redis.get:times=-1        # every redis GET fails (must degrade)
    rpc.post:rate=0.2:error=conn seed=7
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field

ENV_VAR = "TRIVY_TPU_FAULT_INJECT"


class InjectedFault(RuntimeError):
    """Generic scripted failure."""


class InjectedOom(RuntimeError):
    """RESOURCE_EXHAUSTED-shaped scripted failure (device OOM analog)."""


class InjectedConnError(ConnectionError):
    """Scripted connection failure (tunnel reset / dropped socket analog)."""


class InjectedIOError(OSError):
    """Scripted I/O failure (vanished/unreadable file analog)."""


_ERROR_KINDS = {
    "fault": lambda msg: InjectedFault(msg),
    "oom": lambda msg: InjectedOom(f"RESOURCE_EXHAUSTED: out of memory: {msg}"),
    "conn": lambda msg: InjectedConnError(msg),
    "io": lambda msg: InjectedIOError(msg),
}


@dataclass
class FaultRule:
    site: str
    key: str | None = None  # None matches every key at the site
    at: int = 1  # first faulting hit (1-based)
    times: int = 1  # consecutive faulting hits; -1 = forever
    rate: float = 0.0  # when > 0: probabilistic mode (seeded hash)
    error: str = "fault"
    fired: int = 0  # times this rule actually raised

    def should_fire(self, hit: int, key: str | None, seed: int) -> bool:
        if self.rate > 0.0:
            h = hashlib.blake2b(
                f"{seed}:{self.site}:{key or ''}:{hit}".encode(), digest_size=8
            ).digest()
            return int.from_bytes(h, "big") / float(1 << 64) < self.rate
        if hit < self.at:
            return False
        return self.times < 0 or hit < self.at + self.times


@dataclass
class FaultPlan:
    """An armed set of rules plus per-(site, key) hit counters."""

    rules: list[FaultRule] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        self._lock = threading.Lock()
        self._site_hits: dict[str, int] = {}
        self._key_hits: dict[tuple[str, str], int] = {}
        self._by_site: dict[str, list[FaultRule]] = {}
        for r in self.rules:
            self._by_site.setdefault(r.site, []).append(r)

    def check(self, site: str, key: str | None = None) -> None:
        rules = self._by_site.get(site)
        err = None
        with self._lock:
            # count every visit (even unmatched sites don't need counting,
            # but a rule added for this site does)
            if rules is None:
                return
            n_site = self._site_hits[site] = self._site_hits.get(site, 0) + 1
            n_key = n_site
            if key is not None:
                kk = (site, key)
                n_key = self._key_hits[kk] = self._key_hits.get(kk, 0) + 1
            for r in rules:
                if r.key is not None and r.key != key:
                    continue
                hit = n_key if r.key is not None else n_site
                if r.should_fire(hit, key, self.seed):
                    r.fired += 1
                    err = _ERROR_KINDS[r.error](
                        f"injected fault at {site}"
                        f"{f'[{key}]' if key else ''} hit {hit}"
                    )
                    break
        if err is not None:
            # flight-recorder breadcrumb: the injected site lands in the
            # ring BEFORE the raise, so a failure bundle's machine verdict
            # names the faulted site directly
            from trivy_tpu.obs import recorder as flight

            flight.record(
                "fault", f"{site}@{key}" if key else site,
                {"error": type(err).__name__},
            )
            raise err

    def fired(self) -> dict[str, int]:
        """site[@key] -> raise count, for tests and chaos-rep reporting."""
        with self._lock:
            out: dict[str, int] = {}
            for r in self.rules:
                name = r.site + (f"@{r.key}" if r.key else "")
                out[name] = out.get(name, 0) + r.fired
            return out


_OPTION_NAMES = ("at", "times", "rate", "error")


def parse(spec: str) -> FaultPlan:
    """Parse a ``--fault-inject`` spec string into a :class:`FaultPlan`.

    Options are the trailing ``:``-separated parts that start with a known
    option name, so keys containing ``:`` (redis keys like
    ``fanal::artifact::<digest>``) stay addressable:
    ``cache.redis.get@fanal::artifact::abc:times=-1`` parses as key
    ``fanal::artifact::abc``. Keys containing ``,`` are not expressible
    (it is the clause separator).
    """
    rules: list[FaultRule] = []
    seed = 0
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            seed = int(clause[5:])
            continue
        parts = clause.split(":")
        key = None
        if "@" in parts[0]:
            # only the key may contain ':' — options are the trailing parts
            # that start with a known option name
            site, key0 = parts[0].split("@", 1)
            opt_start = next(
                (
                    i
                    for i in range(1, len(parts))
                    if parts[i].split("=", 1)[0] in _OPTION_NAMES
                ),
                len(parts),
            )
            key = ":".join([key0] + parts[1:opt_start])
        else:
            site = parts[0]
            opt_start = 1
        rule = FaultRule(site=site, key=key)
        for p in parts[opt_start:]:
            if "=" not in p:
                raise ValueError(f"--fault-inject: bad clause part {p!r}")
            k, v = p.split("=", 1)
            if k == "at":
                rule.at = int(v)
            elif k == "times":
                rule.times = int(v)
            elif k == "rate":
                rule.rate = float(v)
            elif k == "error":
                if v not in _ERROR_KINDS:
                    raise ValueError(
                        f"--fault-inject: unknown error kind {v!r}; "
                        f"allowed: {sorted(_ERROR_KINDS)}"
                    )
                rule.error = v
            else:
                raise ValueError(f"--fault-inject: unknown option {k!r}")
        if rule.at < 1:
            raise ValueError("--fault-inject: at must be >= 1")
        rules.append(rule)
    return FaultPlan(rules=rules, seed=seed)


# the armed plan; None = disarmed (the hot-path fast case)
_PLAN: FaultPlan | None = None


def configure(spec: str | FaultPlan | None) -> FaultPlan | None:
    """Arm a plan from a spec string (or an explicit plan). ``None``/empty
    disarms. Returns the armed plan."""
    global _PLAN
    if spec is None or spec == "":
        _PLAN = None
    elif isinstance(spec, FaultPlan):
        _PLAN = spec
    else:
        _PLAN = parse(spec)
    return _PLAN


def configure_from_env() -> FaultPlan | None:
    """Arm from ``TRIVY_TPU_FAULT_INJECT`` when set (harness processes that
    never pass CLI flags, e.g. the bench chaos child)."""
    spec = os.environ.get(ENV_VAR)
    return configure(spec) if spec else _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def active() -> bool:
    return _PLAN is not None


def plan() -> FaultPlan | None:
    return _PLAN


def check(site: str, key: str | None = None) -> None:
    """Raise the scripted failure if an armed rule matches this hit.

    The disarmed fast path is one global read — cheap enough for per-file
    and per-batch call sites.
    """
    p = _PLAN
    if p is not None:
        p.check(site, key)
