"""Alpine apk version comparison.

Semantics per apk-tools' version.c (the reference depends on
knqyf263/go-apk-version): ``digits[.digits...][letter][_suffix[num]][-r#]``
where pre-suffixes (_alpha,_beta,_pre,_rc) sort before the bare version and
post-suffixes (_cvs,_svn,_git,_hg,_p) after.
"""

from __future__ import annotations

import re

_PRE = {"alpha": -4, "beta": -3, "pre": -2, "rc": -1}
_POST = {"cvs": 1, "svn": 2, "git": 3, "hg": 4, "p": 5}

_TOKEN = re.compile(
    r"^(?P<digits>\d+(?:\.\d+)*)"
    r"(?P<letter>[a-z])?"
    r"(?P<suffixes>(?:_(?:alpha|beta|pre|rc|cvs|svn|git|hg|p)\d*)*)"
    r"(?:-r(?P<rev>\d+))?$"
)


def parse(v: str):
    m = _TOKEN.match(v.strip())
    if not m:
        return None
    nums = [int(x) for x in m.group("digits").split(".")]
    letter = m.group("letter") or ""
    suffixes = []
    for s in re.findall(r"_([a-z]+)(\d*)", m.group("suffixes") or ""):
        name, num = s
        rank = _PRE.get(name) if name in _PRE else _POST.get(name)
        suffixes.append((rank, int(num) if num else 0))
    rev = int(m.group("rev")) if m.group("rev") else 0
    return nums, letter, suffixes, rev


def compare(a: str, b: str) -> int:
    pa, pb = parse(a), parse(b)
    if pa is None or pb is None:
        # invalid versions: fall back to string compare (stable, arbitrary)
        return -1 if a < b else (0 if a == b else 1)
    na, la, sa, ra = pa
    nb, lb, sb, rb = pb
    # numeric components: first component numeric, later components compare
    # numerically when both lack leading zeros; apk actually compares
    # component-wise numerically
    for xa, xb in zip(na, nb):
        if xa != xb:
            return -1 if xa < xb else 1
    if len(na) != len(nb):
        return -1 if len(na) < len(nb) else 1
    if la != lb:
        return -1 if la < lb else 1
    # suffix lists: compare pairwise; missing suffix = 0 (bare) which sorts
    # after pre-suffixes and before post-suffixes
    for i in range(max(len(sa), len(sb))):
        ta = sa[i] if i < len(sa) else (0, 0)
        tb = sb[i] if i < len(sb) else (0, 0)
        if ta != tb:
            return -1 if ta < tb else 1
    if ra != rb:
        return -1 if ra < rb else 1
    return 0
