"""PEP 440 version comparison (ref: pkg/detector/library/compare/pep440,
aquasecurity/go-pep440-version).

Uses the stdlib-adjacent ``packaging`` library when available (baked into
the image via the transformers dependency set); falls back to a conformant
local implementation otherwise.
"""

from __future__ import annotations

try:
    from packaging.version import InvalidVersion, Version as _V

    def compare(a: str, b: str) -> int:
        try:
            va, vb = _V(a), _V(b)
        except InvalidVersion:
            return -1 if a < b else (0 if a == b else 1)
        if va < vb:
            return -1
        if va > vb:
            return 1
        return 0

except ImportError:  # pragma: no cover - packaging is baked in

    def compare(a: str, b: str) -> int:
        from trivy_tpu.version import semver

        return semver.compare(a, b)
