"""Semantic versioning compare + node-semver style ranges.

Backs the generic comparer and npm ranges (ref:
pkg/detector/library/compare/compare.go GenericComparer,
compare/npm — masahiro331/go-semver hashicorp-style constraints).
Tolerant parsing: missing minor/patch treated as 0, leading 'v' stripped,
extra numeric components preserved for compare.
"""

from __future__ import annotations

import re
from functools import lru_cache

_NUM = re.compile(r"^\d+$")


@lru_cache(maxsize=65536)
def parse(v: str):
    """-> (nums tuple, prerelease tuple, had_prerelease)."""
    v = v.strip().lstrip("vV")
    build = v.split("+", 1)[0]
    core, _, pre = build.partition("-")
    nums = []
    for part in core.split("."):
        if _NUM.match(part):
            nums.append(int(part))
        else:
            # tolerate junk like "1.0.0a" -> numeric prefix + move rest to pre
            m = re.match(r"^(\d+)(.*)$", part)
            if m:
                nums.append(int(m.group(1)))
                if m.group(2):
                    pre = m.group(2).lstrip(".-") + ("." + pre if pre else "")
            else:
                pre = part + ("." + pre if pre else "")
                break
    while len(nums) < 3:
        nums.append(0)
    pre_ids = tuple(pre.split(".")) if pre else ()
    return tuple(nums), pre_ids


def _cmp_pre(a: tuple, b: tuple) -> int:
    """SemVer rule: no prerelease > any prerelease; ids compare numerically
    when both numeric, else ASCII; shorter list < longer when equal prefix."""
    if not a and not b:
        return 0
    if not a:
        return 1
    if not b:
        return -1
    for xa, xb in zip(a, b):
        na, nb = _NUM.match(xa), _NUM.match(xb)
        if na and nb:
            ia, ib = int(xa), int(xb)
            if ia != ib:
                return -1 if ia < ib else 1
        elif na:
            return -1  # numeric < alphanumeric
        elif nb:
            return 1
        elif xa != xb:
            return -1 if xa < xb else 1
    if len(a) != len(b):
        return -1 if len(a) < len(b) else 1
    return 0


def compare(a: str, b: str) -> int:
    na, pa = parse(a)
    nb, pb = parse(b)
    # compare numeric components pairwise, padding with zeros
    ln = max(len(na), len(nb))
    xa = na + (0,) * (ln - len(na))
    xb = nb + (0,) * (ln - len(nb))
    if xa != xb:
        return -1 if xa < xb else 1
    return _cmp_pre(pa, pb)
