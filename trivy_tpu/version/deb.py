"""Debian package version comparison (dpkg algorithm).

Semantics per deb-version(7) / dpkg's verrevcmp (the reference depends on
knqyf263/go-deb-version): ``[epoch:]upstream[-revision]``; strings compare
by alternating non-digit/digit parts; in non-digit parts letters sort before
non-letters and ``~`` sorts before everything including end-of-string.
"""

from __future__ import annotations

import re
from functools import lru_cache

_VALID = re.compile(r"^(?:\d+:)?[0-9][A-Za-z0-9.+:~-]*$|^(?:\d+:)?[0-9]$|^[0-9]+$")


@lru_cache(maxsize=65536)
def parse(v: str) -> tuple[int, str, str]:
    """-> (epoch, upstream, revision)."""
    v = v.strip()
    epoch = 0
    if ":" in v:
        head, _, rest = v.partition(":")
        if head.isdigit():
            epoch = int(head)
            v = rest
    upstream, _, revision = v.rpartition("-")
    if not upstream:
        upstream, revision = revision, ""
    return epoch, upstream, revision


def _char_order(c: str) -> int:
    """verrevcmp character order: ~ < end(0) < digits(as part break) <
    letters < other symbols."""
    if c == "~":
        return -1
    if c.isalpha():
        return ord(c)
    return ord(c) + 256


def _verrevcmp(a: str, b: str) -> int:
    ia = ib = 0
    while ia < len(a) or ib < len(b):
        # non-digit run
        while (ia < len(a) and not a[ia].isdigit()) or (
            ib < len(b) and not b[ib].isdigit()
        ):
            ca = _char_order(a[ia]) if ia < len(a) and not a[ia].isdigit() else 0
            cb = _char_order(b[ib]) if ib < len(b) and not b[ib].isdigit() else 0
            if ca != cb:
                return -1 if ca < cb else 1
            if ia < len(a) and not a[ia].isdigit():
                ia += 1
            if ib < len(b) and not b[ib].isdigit():
                ib += 1
        # digit run
        na = nb = 0
        while ia < len(a) and a[ia].isdigit():
            na = na * 10 + int(a[ia])
            ia += 1
        while ib < len(b) and b[ib].isdigit():
            nb = nb * 10 + int(b[ib])
            ib += 1
        if na != nb:
            return -1 if na < nb else 1
    return 0


def compare(a: str, b: str) -> int:
    ea, ua, ra = parse(a)
    eb, ub, rb = parse(b)
    if ea != eb:
        return -1 if ea < eb else 1
    c = _verrevcmp(ua, ub)
    if c:
        return c
    return _verrevcmp(ra, rb)
