"""Scheme dispatch and constraint evaluation.

Advisory version ranges follow trivy-db conventions (ref:
pkg/detector/library/driver.go:115-142 + compare/): an expression is an
OR (``||``) of AND-groups (comma-separated) of ``<op><version>`` terms;
bare versions mean equality; ``^``/``~``/``~>`` expand per npm/gem rules.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from trivy_tpu.version import apk, deb, maven, pep440, rpm, rubygems, semver

_COMPARERS = {
    "deb": deb.compare,
    "rpm": rpm.compare,
    "apk": apk.compare,
    "semver": semver.compare,
    "npm": semver.compare,
    "pep440": pep440.compare,
    "maven": maven.compare,
    "gem": rubygems.compare,
    "rubygems": rubygems.compare,
}


def compare(scheme: str, a: str, b: str) -> int:
    return _COMPARERS.get(scheme, semver.compare)(a, b)


@dataclass(frozen=True)
class Constraint:
    op: str  # one of < <= > >= = !=
    version: str

    def check(self, scheme: str, version: str) -> bool:
        c = compare(scheme, version, self.version)
        return {
            "<": c < 0,
            "<=": c <= 0,
            ">": c > 0,
            ">=": c >= 0,
            "=": c == 0,
            "!=": c != 0,
        }[self.op]


_TERM = re.compile(r"^\s*(>=|<=|==|!=|>|<|=|\^|~>|~)?\s*v?([^\s,]+)\s*$")


def _expand_term(op: str, ver: str) -> list[Constraint]:
    """^/~/~> expand to >=/< pairs (npm caret/tilde, gem pessimistic)."""
    if op in ("", None, "=", "=="):
        return [Constraint("=", ver)]
    if op in (">", ">=", "<", "<=", "!="):
        return [Constraint(op, ver)]
    nums, _pre = semver.parse(ver)
    if op == "^":
        # bump the leftmost nonzero component
        upper = list(nums[:3])
        for i, n in enumerate(upper):
            if n != 0 or i == 2:
                upper[i] += 1
                upper[i + 1 :] = [0] * (len(upper) - i - 1)
                break
        return [Constraint(">=", ver), Constraint("<", ".".join(map(str, upper)))]
    if op in ("~", "~>"):
        parts = ver.split("-")[0].split(".")
        if op == "~>" and len(parts) >= 2:
            upper = parts[:-1]
            upper[-1] = str(int(re.sub(r"\D.*$", "", upper[-1]) or 0) + 1)
        elif len(parts) >= 2:
            upper = parts[:2]
            upper[-1] = str(int(re.sub(r"\D.*$", "", upper[-1]) or 0) + 1)
        else:
            upper = [str(int(re.sub(r"\D.*$", "", parts[0]) or 0) + 1)]
        return [Constraint(">=", ver), Constraint("<", ".".join(upper))]
    return [Constraint("=", ver)]


def parse_constraints(expr: str) -> list[list[Constraint]]:
    """expr -> OR-list of AND-groups. Empty/'*' matches anything."""
    groups = []
    for or_part in expr.split("||"):
        terms: list[Constraint] = []
        ok = True
        for raw in or_part.split(","):
            raw = raw.strip()
            if not raw or raw in ("*", "ANY"):
                continue
            m = _TERM.match(raw)
            if not m:
                ok = False
                break
            terms.extend(_expand_term(m.group(1) or "", m.group(2)))
        if ok:
            groups.append(terms)
    return groups


def satisfies(scheme: str, version: str, expr: str) -> bool:
    """Does ``version`` fall inside ``expr``? Unparseable groups are
    skipped (advisory-side data errors must not crash a scan)."""
    groups = parse_constraints(expr)
    if not groups:
        return False
    for group in groups:
        if all(c.check(scheme, version) for c in group):
            return True
    return False
