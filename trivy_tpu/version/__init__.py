"""Version parsing and comparison per packaging ecosystem.

Mirrors the reference's per-ecosystem comparers (ref:
pkg/detector/library/compare/{maven,npm,pep440,rubygems}/,
pkg/detector/ospkg/version/ — deb/rpm/apk version algebra). Each scheme
exposes ``compare(a, b) -> -1|0|1`` and ``Constraint`` evaluation used by
advisory matching; schemes also *encode* versions into flat int token
sequences whose plain lexicographic order equals the scheme's order, which
is what lets the CVE-match kernel run batched compares on device
(trivy_tpu/ops/verscmp.py) with all scheme quirks folded in at encode time.
"""

from trivy_tpu.version.compare import (  # noqa: F401
    Constraint,
    compare,
    parse_constraints,
    satisfies,
)
