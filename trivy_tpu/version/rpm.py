"""RPM version comparison (rpmvercmp algorithm).

Semantics per rpm's rpmvercmp (the reference depends on knqyf263/go-rpm-version):
``[epoch:]version-release``; segments of digits or letters compared in
order; digits beat letters; ``~`` sorts before everything; ``^`` sorts
after the base version but before a longer normal suffix.
"""

from __future__ import annotations

import re
from functools import lru_cache

_SEG = re.compile(r"([0-9]+|[a-zA-Z]+|~|\^)")


@lru_cache(maxsize=65536)
def parse(v: str) -> tuple[int, str, str]:
    v = v.strip()
    epoch = 0
    if ":" in v:
        head, _, rest = v.partition(":")
        if head.isdigit():
            epoch = int(head)
            v = rest
    version, _, release = v.partition("-")
    return epoch, version, release


def _rpmvercmp(a: str, b: str) -> int:
    if a == b:
        return 0
    sa = _SEG.findall(a)
    sb = _SEG.findall(b)
    ia = ib = 0
    while ia < len(sa) or ib < len(sb):
        ca = sa[ia] if ia < len(sa) else None
        cb = sb[ib] if ib < len(sb) else None
        # tilde: sorts before everything, including end of string
        if ca == "~" or cb == "~":
            if ca != "~":
                return 1
            if cb != "~":
                return -1
            ia += 1
            ib += 1
            continue
        # caret: newer than base, older than any further normal segment
        if ca == "^" or cb == "^":
            if ca is None:
                return -1  # b has ^ where a ended: a < b
            if cb is None:
                return 1
            if ca != "^":
                return 1  # a has a normal segment vs b's ^: a > b
            if cb != "^":
                return -1
            ia += 1
            ib += 1
            continue
        if ca is None:
            return -1
        if cb is None:
            return 1
        a_num = ca[0].isdigit()
        b_num = cb[0].isdigit()
        if a_num and b_num:
            na, nb = int(ca), int(cb)
            if na != nb:
                return -1 if na < nb else 1
        elif a_num != b_num:
            return 1 if a_num else -1  # numeric segments beat alpha
        else:
            if ca != cb:
                return -1 if ca < cb else 1
        ia += 1
        ib += 1
    return 0


def compare(a: str, b: str) -> int:
    ea, va, ra = parse(a)
    eb, vb, rb = parse(b)
    if ea != eb:
        return -1 if ea < eb else 1
    c = _rpmvercmp(va, vb)
    if c:
        return c
    # releases always compare through rpmvercmp: "" vs "1" -> -1 via the
    # missing-segment rule, and "" vs "~x" -> +1 (tilde sorts before end)
    return _rpmvercmp(ra, rb)
