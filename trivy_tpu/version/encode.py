"""Encode versions as int sequences with scheme-faithful lexicographic order.

The device CVE-match path (trivy_tpu/ops/verscmp.py) compares versions as
flat int32 vectors: ``lexcmp(encode(a), encode(b)) == compare(scheme, a, b)``
for the schemes encoded here (deb, rpm, apk, semver/npm). All ordering
quirks — dpkg's ``~`` sorting before end-of-string, rpm's numeric-beats-
alpha segments and ``^``, apk's pre/post suffixes, semver's prerelease
rules — are folded into token values at encode time, leaving the device a
pure elementwise compare. Schemes not encoded (maven, pep440, gem) fall
back to host comparison in the detector.

Verified against the exact Python comparers by property tests
(tests/test_verscmp.py).
"""

from __future__ import annotations

from trivy_tpu.version import apk as apk_mod, deb as deb_mod, rpm as rpm_mod, semver as semver_mod

# shared numeric-run encoding: [NUM_BASE + ndigits, *digit chars]
NUM_BASE = 2000
MAX_EPOCH = 1 << 20

ENCODABLE = {"deb", "rpm", "apk", "semver", "npm"}


def _digits(run: str) -> list[int]:
    run = run.lstrip("0")
    return [NUM_BASE + len(run)] + [ord(c) for c in run]


# --- deb -------------------------------------------------------------------
# token order within a non-digit run: ~(1) < PAD(2) < END(3) < letters < others
_DEB_PAD = 2
_DEB_END = 3


def _deb_char(c: str) -> int:
    if c == "~":
        return 1
    if c.isalpha():
        return ord(c) + 4
    return ord(c) + 260


def _deb_part(s: str) -> list[int]:
    out: list[int] = []
    i = 0
    while i < len(s) or i == 0:
        # non-digit run (possibly empty), terminated with END
        while i < len(s) and not s[i].isdigit():
            out.append(_deb_char(s[i]))
            i += 1
        out.append(_DEB_END)
        if i >= len(s):
            break
        j = i
        while j < len(s) and s[j].isdigit():
            j += 1
        out.extend(_digits(s[i:j]))
        i = j
        if i >= len(s):
            break
    return out


def encode_deb(v: str) -> list[int]:
    epoch, upstream, revision = deb_mod.parse(v)
    return (
        [min(epoch, MAX_EPOCH)]
        + _deb_part(upstream)
        + _deb_part(revision or "0")
    )


# --- rpm -------------------------------------------------------------------
# segment markers: ~(1) < PAD(2) < ^(3) < ALPHA(4) < NUM(5); alpha chars
# ord+7 with SEG_END(6)
_RPM_PAD = 2


def _rpm_part(s: str) -> list[int]:
    out: list[int] = []
    for seg in rpm_mod._SEG.findall(s):
        if seg == "~":
            out.append(1)
        elif seg == "^":
            out.append(3)
        elif seg[0].isdigit():
            out.append(5)
            out.extend(_digits(seg))
        else:
            out.append(4)
            out.extend(ord(c) + 7 for c in seg)
            out.append(6)
    return out


def encode_rpm(v: str) -> list[int]:
    epoch, version, release = rpm_mod.parse(v)
    out = [min(epoch, MAX_EPOCH)] + _rpm_part(version)
    out.append(_RPM_PAD)  # explicit end of version part
    out.extend(_rpm_part(release))
    return out


# --- apk -------------------------------------------------------------------
# in-band markers: LETTER('' = 1, else ord+2); suffix ranks shifted +10 with
# REV marker = 10 (the bare-version rank)
_APK_REV = 10


def encode_apk(v: str) -> list[int] | None:
    parsed = apk_mod.parse(v)
    if parsed is None:
        # invalid versions use a host-side string-compare fallback whose
        # order a flat encoding cannot reproduce; force the host path
        return None
    nums, letter, suffixes, rev = parsed
    out: list[int] = [1]
    for n in nums:
        out.extend(_digits(str(n)))
    out.append(1 + (ord(letter) - ord("a") + 1 if letter else 0))
    for rank, num in suffixes:
        out.append(rank + _APK_REV)
        out.append(num)
    out.append(_APK_REV)
    out.extend(_digits(str(rev)))
    return out


# --- semver ----------------------------------------------------------------
# core nums as digit runs with trailing zeros stripped (semver zero-pads, so
# "1.2" == "1.2.0"), then NUMS_END(1); NOPRE(3)/PRE(2); ids: numeric
# [1, digits...], alpha [2, ord+4..., CHAR_END(3)]; LIST_END(0)
def encode_semver(v: str) -> list[int]:
    nums, pre = semver_mod.parse(v)
    nums = list(nums)
    while nums and nums[-1] == 0:
        nums.pop()
    out: list[int] = []
    for n in nums:
        out.extend(_digits(str(n)))
    out.append(1)  # NUMS_END: sorts below any NUM_BASE length token
    if not pre:
        out.append(3)
        return out
    out.append(2)
    for pid in pre:
        if pid.isdigit():
            out.append(1)
            out.extend(_digits(pid))
        else:
            out.append(2)
            out.extend(ord(c) + 4 for c in pid)
            out.append(3)
    out.append(0)
    return out


_ENCODERS = {
    "deb": encode_deb,
    "rpm": encode_rpm,
    "apk": encode_apk,
    "semver": encode_semver,
    "npm": encode_semver,
}

_PADS = {"deb": _DEB_PAD, "rpm": _RPM_PAD, "apk": 0, "semver": 0, "npm": 0}


def encode(scheme: str, version: str) -> list[int] | None:
    enc = _ENCODERS.get(scheme)
    if enc is None:
        return None
    try:
        return enc(version)
    except Exception:
        return None


def pad_value(scheme: str) -> int:
    return _PADS.get(scheme, 0)


def encode_batch(scheme: str, versions: list[str], length: int | None = None):
    """-> int32 array [N, L] zero... pad-filled, or None if un-encodable."""
    import numpy as np

    rows = []
    for v in versions:
        r = encode(scheme, v)
        if r is None:
            return None
        rows.append(r)
    L = length or max((len(r) for r in rows), default=1)
    out = np.full((len(rows), L), pad_value(scheme), dtype=np.int32)
    for i, r in enumerate(rows):
        if len(r) > L:
            return None  # caller must re-pad with a larger length
        out[i, : len(r)] = r
    return out
