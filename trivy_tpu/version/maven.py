"""Maven version comparison (ref: pkg/detector/library/compare/maven,
masahiro331/go-mvn-version — org.apache.maven ComparableVersion).

Tokens split on '.', '-', and digit/letter transitions; known qualifiers
order: alpha < beta < milestone < rc=cr < snapshot < '' (release) < sp <
other qualifiers (case-insensitive, alphabetical); the single-letter
aliases a/b/m mean alpha/beta/milestone only when immediately followed by
a digit ("1-a1" == "1-alpha-1" but "1-a" uses plain qualifier "a");
null-padding semantics per ComparableVersion ("1" == "1.0" == "1.0.0").
"""

from __future__ import annotations

import re

_QUALIFIERS = ["alpha", "beta", "milestone", "rc", "snapshot", "", "sp"]
_ALIASES = {"cr": "rc", "ga": "", "final": "", "release": ""}
_SPLIT = re.compile(r"([0-9]+|[a-zA-Z]+)")


def _tokenize(v: str):
    """-> list of ('int', n) / ('str', normalized_qualifier) tokens."""
    v = v.strip().lower()
    tokens = []
    i = 0
    while i < len(v):
        c = v[i]
        if c in ".-":
            i += 1
            continue
        m = _SPLIT.match(v, i)
        if not m:
            i += 1
            continue
        run = m.group(0)
        i = m.end()
        if run.isdigit():
            tokens.append(("int", int(run)))
        else:
            q = _ALIASES.get(run, run)
            # a/b/m alias only when the letter run is immediately followed
            # by a digit (no separator in between)
            if run in ("a", "b", "m") and i < len(v) and v[i].isdigit():
                q = {"a": "alpha", "b": "beta", "m": "milestone"}[run]
            tokens.append(("str", q))
    return tokens


def _qualifier_rank(q: str) -> tuple:
    if q in _QUALIFIERS:
        return (0, _QUALIFIERS.index(q), "")
    return (1, len(_QUALIFIERS), q)  # unknown qualifiers after 'sp', alphabetical


def _normalize(tokens):
    """Strip trailing null values (0 and release-equivalent qualifiers)."""
    out = list(tokens)
    while out:
        kind, val = out[-1]
        if (kind == "int" and val == 0) or (kind == "str" and val == ""):
            out.pop()
        else:
            break
    return out


def compare(a: str, b: str) -> int:
    ta = _normalize(_tokenize(a))
    tb = _normalize(_tokenize(b))
    for i in range(max(len(ta), len(tb))):
        xa = ta[i] if i < len(ta) else None
        xb = tb[i] if i < len(tb) else None
        if xa is None or xb is None:
            kind, val = xa if xb is None else xb
            if kind == "int":
                c = 1 if val > 0 else 0
            else:
                rank = _qualifier_rank(val)
                base = _qualifier_rank("")
                c = -1 if rank < base else (1 if rank > base else 0)
            if c:
                return c if xb is None else -c
            continue
        ka, va_ = xa
        kb, vb_ = xb
        if ka == "int" and kb == "int":
            if va_ != vb_:
                return -1 if va_ < vb_ else 1
        elif ka == "int":
            return 1  # numbers beat qualifiers
        elif kb == "int":
            return -1
        else:
            ra, rb = _qualifier_rank(va_), _qualifier_rank(vb_)
            if ra != rb:
                return -1 if ra < rb else 1
    return 0
