"""RubyGems version comparison (Gem::Version semantics, ref:
pkg/detector/library/compare/rubygems).

Segments split on '.' with letter/digit transitions; numeric segments
compare numerically, string segments (prerelease markers) sort before
numeric ones and make the version a prerelease of its release.
"""

from __future__ import annotations

import re

_SEG = re.compile(r"[0-9]+|[a-z]+", re.IGNORECASE)


def _segments(v: str):
    v = v.strip()
    segs = []
    for s in _SEG.findall(v.replace("-", ".pre.")):
        segs.append(int(s) if s.isdigit() else s.lower())
    return segs


def compare(a: str, b: str) -> int:
    sa, sb = _segments(a), _segments(b)
    # trim trailing zeros
    while sa and sa[-1] == 0:
        sa.pop()
    while sb and sb[-1] == 0:
        sb.pop()
    for i in range(max(len(sa), len(sb))):
        xa = sa[i] if i < len(sa) else 0
        xb = sb[i] if i < len(sb) else 0
        a_str, b_str = isinstance(xa, str), isinstance(xb, str)
        if a_str and b_str:
            if xa != xb:
                return -1 if xa < xb else 1
        elif a_str != b_str:
            return -1 if a_str else 1  # strings sort before numbers
        else:
            if xa != xb:
                return -1 if xa < xb else 1
    return 0
