"""Local scan driver (ref: pkg/scanner/local/scan.go).

Consumes cache keys only: applies layers, then assembles per-class results
— vulnerabilities via the detectors, plus misconfig/secret/license sections
(ref: scan.go:63-151, 229-318).
"""

from __future__ import annotations

from trivy_tpu import log, obs
from trivy_tpu.fanal.applier import apply_layers
from trivy_tpu.scanner import ScanOptions
from trivy_tpu.types import (
    BlobInfo,
    DetectedLicense,
    OS,
    Result,
    ResultClass,
    Secret,
)

logger = log.logger("scanner:local")


class LocalDriver:
    def __init__(self, cache, vuln_client=None):
        self.cache = cache
        self.vuln_client = vuln_client

    def scan(
        self, target: str, artifact_id: str, blob_ids: list[str], options: ScanOptions
    ) -> tuple[list[Result], OS | None]:
        ctx = obs.current()
        # server-side live progress: when nothing upstream tracked progress
        # (a remote client's analysis walk ran in another process), the
        # blob set is this scan's work-list — count it so
        # GET /scan/<trace_id>/progress moves while detection runs. A local
        # CLI scan's progress is owned by the artifact walk; don't muddy it.
        prog = ctx.progress()
        track_blobs = prog.files_walked == 0
        if track_blobs:
            # finish_walk() waits until results are assembled: the ratio
            # caps at 0.999 regardless (only finish() reports 100%), but
            # "work-list final" should not be claimed while detection can
            # still be running
            prog.note_walked(0, files=len(blob_ids))
        with ctx.span("driver.apply_layers"):
            blobs = []
            for bid in blob_ids:
                d = self.cache.get_blob(bid)
                if d is None:
                    raise KeyError(f"blob missing from cache: {bid}")
                blobs.append(BlobInfo.from_dict(d))
                if track_blobs:
                    prog.note_scanned(0)
            detail = apply_layers(blobs)
        results: list[Result] = []

        if "vuln" in options.scanners and self.vuln_client is not None:
            with ctx.span("driver.detect_vulns"):
                results.extend(
                    self._scan_vulnerabilities(target, detail, options)
                )
        elif options.list_all_pkgs:
            # package inventory without detection (SBOM output paths)
            results.extend(self._package_results(target, detail))
        if "misconfig" in options.scanners:
            results.extend(self._misconfig_results(target, detail))
        if "secret" in options.scanners:
            results.extend(self._secret_results(detail))
        if "license" in options.scanners:
            results.extend(self._license_results(target, detail, options))
        # post-scan hooks may rewrite the result list (ref: local/scan.go:145)
        from trivy_tpu.scanner.post import post_scan

        results = post_scan(results)
        if track_blobs:
            prog.finish_walk()
        return results, detail.os

    # -- per-class assembly (ref: scan.go:153-318) --------------------------

    def _scan_vulnerabilities(self, target, detail, options):
        results: list[Result] = []
        if self.vuln_client is None:
            return results
        from trivy_tpu.detector import detect_all

        return detect_all(self.vuln_client, target, detail, options)

    def _package_results(self, target, detail) -> list[Result]:
        results: list[Result] = []
        if detail.packages:
            name = target
            if detail.os:
                name = f"{target} ({detail.os.family} {detail.os.name})"
            results.append(
                Result(
                    target=name,
                    cls=ResultClass.OS_PKGS.value,
                    type=detail.os.family if detail.os else "",
                    packages=detail.packages,
                )
            )
        for app in sorted(detail.applications, key=lambda a: (a.file_path, a.type)):
            results.append(
                Result(
                    target=app.file_path or app.type,
                    cls=ResultClass.LANG_PKGS.value,
                    type=app.type,
                    packages=app.packages,
                )
            )
        return results

    def _secret_results(self, detail) -> list[Result]:
        out = []
        for secret in detail.secrets:
            assert isinstance(secret, Secret)
            out.append(
                Result(
                    target=secret.file_path,
                    cls=ResultClass.SECRET.value,
                    secrets=secret.findings,
                )
            )
        return out

    def _misconfig_results(self, target, detail) -> list[Result]:
        out = []
        for mc in detail.misconfigurations:
            out.append(
                Result(
                    target=mc.file_path,
                    cls=ResultClass.CONFIG.value,
                    type=mc.file_type,
                    misconfigurations=mc.successes + mc.failures,
                )
            )
        return out

    def _license_results(self, target, detail, options) -> list[Result]:
        from trivy_tpu.licensing.scanner import LicenseCategorizer

        cat = LicenseCategorizer(options.license_categories)
        os_lics: list[DetectedLicense] = []
        file_lics: list[DetectedLicense] = []
        for pkg in detail.packages:
            for name in pkg.licenses:
                os_lics.append(cat.detect(name, pkg_name=pkg.name))
        for lf in detail.licenses:
            for f in lf.findings:
                d = cat.detect(f.name, file_path=lf.file_path)
                d.confidence = f.confidence
                d.link = f.link
                file_lics.append(d)
        results = []
        if os_lics:
            results.append(
                Result(
                    target="OS Packages",
                    cls=ResultClass.LICENSE.value,
                    licenses=os_lics,
                )
            )
        app_lics: list[DetectedLicense] = []
        for app in detail.applications:
            for pkg in app.packages:
                for name in pkg.licenses:
                    app_lics.append(cat.detect(name, pkg_name=pkg.name))
        if app_lics:
            results.append(
                Result(
                    target="Language Packages",
                    cls=ResultClass.LICENSE.value,
                    licenses=app_lics,
                )
            )
        if file_lics:
            results.append(
                Result(
                    target="Loose File License(s)",
                    cls=ResultClass.LICENSE_FILE.value,
                    licenses=file_lics,
                )
            )
        return results
