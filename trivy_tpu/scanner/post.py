"""Post-scan hook registry (ref: pkg/scanner/post/post_scan.go).

Post scanners run after result assembly and may rewrite the result list —
the extension seam WASM modules and plugins use in the reference
(ref: pkg/module/module.go:417). Versions feed cache keys like analyzer
versions do.
"""

from __future__ import annotations

from trivy_tpu import log

logger = log.logger("scanner:post")

_post_scanners: dict[str, object] = {}


class PostScanner:
    """Interface: subclass with name/version attrs and post_scan()."""

    name: str = ""
    version: int = 1

    def post_scan(self, results: list) -> list:  # pragma: no cover - iface
        return results


def register_post_scanner(scanner: PostScanner) -> None:
    _post_scanners[scanner.name] = scanner


def deregister_post_scanner(name: str) -> None:
    _post_scanners.pop(name, None)


def scanner_versions() -> dict[str, int]:
    return {name: s.version for name, s in sorted(_post_scanners.items())}


def post_scan(results: list) -> list:
    """Run every registered post scanner in name order (deterministic —
    the reference iterates a map; sorted order is strictly better)."""
    for name in sorted(_post_scanners):
        try:
            out = _post_scanners[name].post_scan(results)
        except Exception as e:
            # hooks must not kill a scan (analyzer-error policy applies)
            logger.warning("post scanner %s failed: %s", name, e)
            continue
        if isinstance(out, list):
            results = out
        else:
            logger.warning(
                "post scanner %s returned %s, not a result list; ignored",
                name, type(out).__name__,
            )
    return results
