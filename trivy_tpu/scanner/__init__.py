"""Scanner facade binding an Artifact to a scan Driver
(ref: pkg/scanner/scan.go:134-204)."""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field

from trivy_tpu.types import Report, Result


@dataclass
class ScanOptions:
    """What to scan and how (ref: pkg/types ScanOptions)."""

    scanners: list[str] = field(default_factory=lambda: ["vuln", "secret"])
    license_categories: dict[str, list[str]] = field(default_factory=dict)
    license_full: bool = False
    include_dev_deps: bool = False
    pkg_types: list[str] = field(default_factory=lambda: ["os", "library"])
    detection_priority: str = "precise"
    list_all_pkgs: bool = False


class Scanner:
    """Artifact + Driver (local or remote client), ref: scan.go:134-152."""

    def __init__(self, artifact, driver):
        self.artifact = artifact
        self.driver = driver

    def scan_artifact(self, options: ScanOptions) -> Report:
        from trivy_tpu import obs

        # scan-health events (degradations, skipped files) accumulate on
        # the active trace context even with tracing off; the before/after
        # delta is exactly this scan's share, so back-to-back library scans
        # sharing the process-default context stay disjoint
        health0 = obs.current().health_snapshot()
        ref = self.artifact.inspect()
        results, os_info = self.driver.scan(ref.name, ref.id, ref.blob_ids, options)
        health = obs.current().health_snapshot()
        delta = {k: v - health0.get(k, 0) for k, v in health.items()}
        metadata = {
            "ImageID": ref.image_metadata.get("id", ""),
            "DiffIDs": ref.image_metadata.get("diff_ids", []),
        }
        if os_info is not None:
            metadata["OS"] = os_info.to_dict()
        if ref.image_metadata.get("config"):
            metadata["ImageConfig"] = ref.image_metadata["config"]
        skipped = delta.get("walk.skipped", 0)
        if skipped > 0:
            metadata["SkippedFiles"] = skipped
        if delta.get("cache.degraded", 0) > 0:
            metadata["CacheDegraded"] = True
        return Report(
            created_at=datetime.datetime.now(datetime.timezone.utc).isoformat(),
            artifact_name=ref.name,
            artifact_type=ref.type,
            metadata=metadata,
            results=[r for r in results if not r.is_empty],
            degraded=delta.get("scan.degraded", 0) > 0,
        )


__all__ = ["Scanner", "ScanOptions", "Result"]
