"""Scan manifest: the record that turns a repeat scan into a stat-walk.

One manifest per (root, analysis fingerprint), persisted through the scan
cache's artifact table (any backend — fs, redis, memory). It records, for
every walked file, the stat signature ``(size, mtime_ns)`` and the content
key the last scan computed, plus the git commit the tree was at (when the
root is a git worktree) and the unit → blob-id map.

``--since-last`` reuses a recorded content key when the stat signature
matches (no read, no hash); ``--diff-base <commit>`` reuses recorded keys
for files the git tree diff says are unchanged since the manifest's
commit, which survives fresh checkouts where every mtime is new.

The manifest is invalidated as a whole by the analysis fingerprint in its
storage key — a rule-file edit, analyzer-version bump, or skip-list change
makes the old manifest unreachable by construction (the loud-miss
discipline the persistent dedup store shares).
"""

from __future__ import annotations

import hashlib
import subprocess
import time

from trivy_tpu import log

logger = log.logger("incremental:manifest")

MANIFEST_VERSION = 1


def manifest_key(root: str, fingerprint: str) -> str:
    digest = hashlib.sha256(f"{root}|{fingerprint}".encode()).hexdigest()
    return f"incr-manifest:{digest}"


def load_manifest(cache, root: str, fingerprint: str) -> dict | None:
    try:
        doc = cache.get_artifact(manifest_key(root, fingerprint))
    except Exception as e:
        logger.warning("manifest load failed (%s); scanning without it", e)
        return None
    if not isinstance(doc, dict) or doc.get("Version") != MANIFEST_VERSION:
        if doc is not None:
            logger.warning(
                "manifest for %s has version %r (want %d); ignoring it",
                root, (doc or {}).get("Version"), MANIFEST_VERSION,
            )
        return None
    return doc


def save_manifest(
    cache, root: str, fingerprint: str,
    files: dict[str, list], units: dict[str, str],
    commit: str = "",
) -> dict:
    doc = {
        "Version": MANIFEST_VERSION,
        "Root": root,
        "Fingerprint": fingerprint,
        "Commit": commit,
        "Files": files,   # rel -> [size, mtime_ns, content_key]
        "Units": units,   # unit path -> blob id
        "CreatedWall": time.time(),
    }
    try:
        cache.put_artifact(manifest_key(root, fingerprint), doc)
    except Exception as e:
        logger.warning("manifest save failed (%s); next scan runs cold", e)
    return doc


# -- git helpers (diff-base) --------------------------------------------------


class GitDiffError(RuntimeError):
    pass


def _git(root: str, args: list[str]) -> str:
    try:
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=120,
        )
    except FileNotFoundError as e:
        raise GitDiffError("git is not installed") from e
    except subprocess.TimeoutExpired as e:
        raise GitDiffError(f"git {args[0]} timed out") from e
    if proc.returncode != 0:
        raise GitDiffError(
            f"git {' '.join(args[:2])} failed: {proc.stderr.strip()[:300]}"
        )
    return proc.stdout


def git_head(root: str) -> str:
    """HEAD commit id, or "" when the root is not a git worktree."""
    try:
        return _git(root, ["rev-parse", "HEAD"]).strip()
    except GitDiffError:
        return ""


def git_clean_head(root: str) -> str:
    """HEAD commit id IF the worktree is clean (no staged/unstaged/
    untracked changes), else "". The manifest records only clean-worktree
    commits: content keys hashed over dirty files must never be reachable
    through a later ``--diff-base`` tree diff (a revert would mark them
    unchanged while the recorded keys cover the dirty bytes)."""
    head = git_head(root)
    if not head:
        return ""
    try:
        dirty = _git(root, ["status", "--porcelain", "--no-renames"]).strip()
    except GitDiffError:
        return ""
    return "" if dirty else head


def git_resolve(root: str, ref: str) -> str:
    """Resolve a commit-ish to a full id (raises GitDiffError loudly —
    a typoed ``--diff-base`` must not silently full-scan)."""
    return _git(root, ["rev-parse", "--verify", f"{ref}^{{commit}}"]).strip()


def git_changed_paths(root: str, base: str) -> set[str]:
    """Paths changed between ``base`` and the CURRENT worktree: committed
    changes (tree diff base..HEAD), staged/unstaged edits, and untracked
    files. Renames are reported as delete+add (--no-renames) so both sides
    re-key. Paths are repo-root-relative posix, matching the walker."""
    changed: set[str] = set()
    out = _git(
        root,
        ["diff", "--name-only", "--no-renames", "-z", base, "HEAD"],
    )
    changed.update(p for p in out.split("\0") if p)
    # worktree state on top of HEAD: modified, staged, and untracked files
    out = _git(root, ["status", "--porcelain", "--no-renames", "-z"])
    for entry in out.split("\0"):
        if len(entry) > 3:
            changed.add(entry[3:])
    return changed
