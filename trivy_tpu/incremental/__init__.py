"""Incremental scanning (ROADMAP item 2): make re-scans near-no-ops.

Production traffic at fleet scale is mostly re-scans of the same base
images, vendored trees, and registries. This package turns cross-scan
reuse into a first-class scenario on top of the content-addressed cache:

- :mod:`~trivy_tpu.incremental.fs` — the unit-level incremental fs
  artifact: the tree partitions into the SAME directory-atomic units the
  fleet shard planner uses, each unit's blob is cached under a key derived
  from its files' content hashes plus the full analysis fingerprint, and a
  re-scan analyzes only units whose key is missing (everything else merges
  out of the cache through the untouched applier path — findings
  byte-identical to a full scan by the same construction the fleet merger
  relies on);
- :mod:`~trivy_tpu.incremental.manifest` — the scan manifest: per-file
  content keys + stat signatures + the git commit, persisted through the
  scan cache, so ``--since-last`` turns a repeat scan into a stat-walk
  (no reads for unchanged files) and ``--diff-base <commit>`` trusts the
  git tree diff instead of mtimes (CI checkouts have fresh mtimes);
- diff-scan for images rides the existing ``MissingBlobs``/``layer_plan``
  machinery: ``--diff-base <image-ref>`` pre-seeds the cache with the base
  image's layers under the derived image's planned keys
  (:func:`trivy_tpu.artifact.image.preseed_from_base`), so the scan
  analyzes only layers absent from the base.

Failure semantics: the cache is an accelerator, never a correctness
dependency — a missing/unreadable manifest or a cold cache only means
files get re-hashed / units get re-analyzed. Analysis boundaries are the
fleet's (directory-atomic, Helm subtrees whole), so cross-directory
post-analysis links (e.g. a Maven parent POM outside its module tree)
share the fleet mode's documented caveat.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class IncrementalOptions:
    """Resolved incremental-scan knobs (``--incremental`` /
    ``--diff-base`` / ``--since-last``; watch mode forces since_last)."""

    enabled: bool = False
    diff_base: str = ""      # git commit-ish (fs/repo) — implies enabled
    since_last: bool = False  # stat-manifest reuse — implies enabled

    @classmethod
    def from_opts(cls, opts: dict) -> "IncrementalOptions":
        diff_base = str(opts.get("diff_base") or "")
        since_last = bool(opts.get("since_last"))
        enabled = bool(opts.get("incremental")) or bool(diff_base) or since_last
        return cls(
            enabled=enabled, diff_base=diff_base, since_last=since_last
        )
