"""Unit-level incremental filesystem artifact.

The single-blob fs artifact (``artifact/local_fs.py``) re-analyzes the
whole tree every scan. This artifact partitions the tree into the SAME
directory-atomic units the fleet shard planner produces
(:func:`trivy_tpu.fleet.plan.group_units` — Helm chart subtrees whole,
sibling manifest/lockfile pairs together), gives every unit its own blob
keyed by **input content** (the unit's files' content hashes + the full
analysis fingerprint), and analyzes only units whose key is missing from
the cache. The ordinary applier merges the per-unit blobs, so findings
are byte-identical to a full scan by the same construction the fleet
merger relies on (path-disjoint blobs, deterministic sorted union).

Re-scan ladder, cheapest first:

1. ``--since-last``: a stat-walk — files whose ``(size, mtime_ns)``
   matches the manifest reuse their recorded content key, NO read;
2. ``--diff-base <commit>``: the git tree diff — files unchanged since
   the manifest's commit reuse recorded keys even when every mtime is
   fresh (CI checkouts);
3. plain ``--incremental``: every file is re-hashed (one streaming read),
   but unchanged units still skip analysis entirely — no chunking, no
   device feed, no confirms.

An unchanged tree therefore costs a walk plus (at most) hashing; the
device pipeline never starts. That is the ≥10× warm re-scan win the
``warm_rescan`` bench rep measures end to end.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from trivy_tpu import log, obs
from trivy_tpu.artifact.local_fs import DEFAULT_PARALLEL, ArtifactOption
from trivy_tpu.cache.key import calc_key
from trivy_tpu.fanal.analyzer import (
    AnalyzerGroup,
    AnalyzerOptions,
    AnalysisResult,
    note_file_skipped,
)
from trivy_tpu.fanal.handler import HandlerManager
from trivy_tpu.fanal.walker import FSWalker, WalkOption
from trivy_tpu.incremental import IncrementalOptions, manifest as manifest_mod
from trivy_tpu.types import ArtifactReference

logger = log.logger("incremental:fs")


def _content_key(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _hash_file(path: str) -> str | None:
    """Streaming content hash (bounded memory on huge files)."""
    h = hashlib.blake2b(digest_size=16)
    try:
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
    except OSError:
        return None
    return h.hexdigest()


class IncrementalFSArtifact:
    """Filesystem artifact with per-unit content-addressed blobs."""

    type = "filesystem"

    def __init__(self, root: str, cache, option: ArtifactOption | None = None,
                 incremental: IncrementalOptions | None = None):
        self.root = root
        self.cache = cache
        self.option = option or ArtifactOption()
        self.incremental = incremental or IncrementalOptions(enabled=True)
        self.group = AnalyzerGroup(
            AnalyzerOptions(
                disabled=self.option.disabled_analyzers,
                secret_config_path=self.option.secret_config_path,
                backend=self.option.backend,
                root=root,
                extra=self.option.analyzer_extra,
            )
        )
        self.handlers = HandlerManager()
        self.walker = FSWalker(
            WalkOption(
                skip_files=self.option.skip_files,
                skip_dirs=self.option.skip_dirs,
            )
        )
        # reuse accounting for tests, bench, and the watch-mode change
        # detector: {units_total, units_analyzed, units_reused,
        # files_stat_reused, files_git_reused, files_hashed, bytes_reused}
        self.last_stats: dict = {}

    # -- fingerprint ---------------------------------------------------------

    def fingerprint(self) -> str:
        """Digest of the FULL effective analysis config: analyzer + hook
        versions, skip lists, the ``--secret-config`` file CONTENT, and
        the misconfig knobs. Anything that can change findings must flip
        this — a stale manifest/unit blob must be unreachable, never
        served (the loud-miss discipline of the persistent dedup store)."""
        secret_cfg_digest = ""
        path = self.option.secret_config_path
        if path and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    secret_cfg_digest = hashlib.sha256(f.read()).hexdigest()
            except OSError:
                secret_cfg_digest = "unreadable"
        extra = self.option.analyzer_extra or {}
        doc = {
            "v": manifest_mod.MANIFEST_VERSION,
            "analyzers": self.group.versions(),
            "hooks": self.handlers.versions(),
            "skip_files": sorted(self.option.skip_files),
            "skip_dirs": sorted(self.option.skip_dirs),
            "secret_config": secret_cfg_digest,
            "check_paths": sorted(extra.get("check_paths") or []),
            "misconfig_scanners": sorted(extra.get("misconfig_scanners") or []),
        }
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        ).hexdigest()

    def _unit_blob_key(self, unit: str, files: dict[str, str],
                       fingerprint: str) -> str:
        base = json.dumps(
            {"incr_unit": unit, "files": files, "fp": fingerprint},
            sort_keys=True, separators=(",", ":"),
        )
        return calc_key(
            base,
            analyzer_versions=self.group.versions(),
            hook_versions=self.handlers.versions(),
            skip_files=self.option.skip_files,
            skip_dirs=self.option.skip_dirs,
        )

    # -- inspect -------------------------------------------------------------

    def inspect(self) -> ArtifactReference:
        ctx = obs.current()
        progress = ctx.progress()
        fingerprint = self.fingerprint()
        root_abs = os.path.abspath(self.root)
        incr = self.incremental

        # 1. walk: collect (rel, info, mtime_ns, full) — stat only
        entries: list[tuple] = []
        for rel, info, _opener in self.walker.walk(self.root):
            full = os.path.join(root_abs, *rel.split("/"))
            try:
                mtime_ns = os.lstat(full).st_mtime_ns
            except OSError:
                mtime_ns = -1
            entries.append((rel, info, mtime_ns, full))
            progress.note_walked(info.size)
        progress.finish_walk()

        # 2. prior manifest + git state for key reuse
        manifest = manifest_mod.load_manifest(self.cache, root_abs, fingerprint)
        man_files = (manifest or {}).get("Files") or {}
        git_clean: set[str] | None = None  # rels unchanged vs the manifest
        # the commit recorded in the manifest is CLEAN-only (see
        # save_manifest): a manifest whose keys include uncommitted edits
        # must never be git-reused — a later revert would make those paths
        # "unchanged vs base" while their recorded keys hash the DIRTY
        # content, serving stale findings
        commit = manifest_mod.git_clean_head(root_abs)
        if incr.diff_base:
            base = manifest_mod.git_resolve(root_abs, incr.diff_base)
            if manifest and manifest.get("Commit") == base:
                changed = manifest_mod.git_changed_paths(root_abs, base)
                git_clean = {e[0] for e in entries if e[0] not in changed}
            else:
                logger.warning(
                    "--diff-base %s: no clean-worktree manifest recorded at "
                    "that commit (have %s); falling back to content hashing",
                    incr.diff_base,
                    ((manifest or {}).get("Commit") or "none")[:12],
                )

        # 3. per-file content keys, cheapest source first
        stat_reused = git_reused = hashed = 0
        file_keys: dict[str, str] = {}
        for rel, info, mtime_ns, full in entries:
            rec = man_files.get(rel)
            if (
                incr.since_last and rec is not None
                and rec[0] == info.size and rec[1] == mtime_ns
                and mtime_ns >= 0
            ):
                file_keys[rel] = rec[2]
                stat_reused += 1
                continue
            if git_clean is not None and rel in git_clean and rec is not None:
                file_keys[rel] = rec[2]
                git_reused += 1
                continue
            key = _hash_file(full)
            if key is None:
                # vanished between walk and hash (TOCTOU): drop the entry,
                # count the skip once — same discipline as the single-host
                # walk's read failures
                note_file_skipped(rel, OSError("unreadable during hashing"))
                file_keys[rel] = ""
                continue
            file_keys[rel] = key
            hashed += 1
        entries = [e for e in entries if file_keys.get(e[0])]

        # 4. directory-atomic units + content-addressed unit keys
        from trivy_tpu.fleet.plan import group_units

        units = group_units([(rel, info.size) for rel, info, _, _ in entries])
        by_rel = {rel: (info, mtime_ns, full)
                  for rel, info, mtime_ns, full in entries}
        unit_keys: dict[str, str] = {}
        for unit, files, _nbytes in units:
            unit_keys[unit] = self._unit_blob_key(
                unit, {rel: file_keys[rel] for rel, _ in files}, fingerprint
            )
        blob_ids = [unit_keys[u] for u, _, _ in units]
        artifact_id = calc_key(
            json.dumps({"incr_root": root_abs, "units": blob_ids},
                       sort_keys=True, separators=(",", ":")),
        )

        # 5. cache diff → dirty units only
        if blob_ids:
            _, missing = self.cache.missing_blobs(artifact_id, blob_ids)
        else:
            missing = []
        missing_set = set(missing)
        dirty = [(u, files, nbytes) for u, files, nbytes in units
                 if unit_keys[u] in missing_set]
        reused_bytes = sum(nbytes for u, _, nbytes in units
                           if unit_keys[u] not in missing_set)
        ctx.count("incr.units_reused", len(units) - len(dirty))
        ctx.count("incr.bytes_reused", reused_bytes)
        progress.note_scanned(reused_bytes)

        if dirty:
            self._analyze_units(dirty, by_rel, unit_keys, progress)

        # 6. record the manifest for the next scan's stat-walk
        manifest_mod.save_manifest(
            self.cache, root_abs, fingerprint,
            files={
                rel: [info.size, mtime_ns, file_keys[rel]]
                for rel, info, mtime_ns, _ in entries
            },
            units={u: unit_keys[u] for u, _, _ in units},
            commit=commit,
        )
        self.last_stats = {
            "unit_keys": tuple(blob_ids),
            "units_total": len(units),
            "units_analyzed": len(dirty),
            "units_reused": len(units) - len(dirty),
            "files_stat_reused": stat_reused,
            "files_git_reused": git_reused,
            "files_hashed": hashed,
            "bytes_reused": reused_bytes,
        }
        logger.info(
            "incremental scan of %s: %d/%d unit(s) reused "
            "(%d stat-reused, %d git-reused, %d hashed file(s))",
            self.root, len(units) - len(dirty), len(units),
            stat_reused, git_reused, hashed,
        )

        name = self.root
        if name != os.path.sep:
            name = name.rstrip(os.path.sep)
        return ArtifactReference(
            name=name, type=self.type, id=artifact_id, blob_ids=blob_ids
        )

    # -- dirty-unit analysis -------------------------------------------------

    def _analyze_units(self, dirty, by_rel, unit_keys, progress) -> None:
        """One analyzer-group pass over every dirty unit's files, split
        into per-unit blobs. Per-file analyzer output lands directly in
        its unit's result (exact attribution, including OS identity from
        os-release-style files); batch/post analyzer output is split by
        file path — every batched item type is path-attributed."""
        unit_of: dict[str, str] = {}
        for unit, files, _ in dirty:
            for rel, _size in files:
                unit_of[rel] = unit
        unit_results: dict[str, AnalysisResult] = {
            unit: AnalysisResult() for unit, _, _ in dirty
        }
        post_files: dict = {}
        tuning = (self.option.analyzer_extra or {}).get("tuning")
        tuned_parallel = getattr(tuning, "parallel", 0) if tuning else 0
        workers = self.option.parallel or tuned_parallel or DEFAULT_PARALLEL

        def analyze(rel, fut):
            # the walk's real FileInfo (size AND mode): executable-bit
            # analyzers must see exactly what a full scan's walk passes
            info, _mtime, _full = by_rel[rel]
            try:
                wanted = self.group.analyze_file(
                    unit_results[unit_of[rel]], self.root, rel, info,
                    fut.result,
                )
            except OSError as e:
                note_file_skipped(rel, e)
                progress.note_scanned(info.size)
                return
            for t, content in wanted.items():
                post_files.setdefault(t, {})[rel] = content
            progress.note_scanned(info.size)

        try:
            # bounded read-ahead window, same shape as the single-host walk
            window: deque = deque()
            buffered = 0
            with ThreadPoolExecutor(max_workers=workers) as pool:
                for unit, files, _nbytes in dirty:
                    for rel, size in files:
                        full = by_rel[rel][2]

                        def opener(path=full) -> bytes:
                            with open(path, "rb") as f:
                                return f.read()

                        window.append((rel, pool.submit(opener)))
                        buffered += size
                        while buffered > (64 << 20) or len(window) > 128:
                            r, fut = window.popleft()
                            buffered -= by_rel[r][0].size
                            analyze(r, fut)
                while window:
                    r, fut = window.popleft()
                    analyze(r, fut)
            # batch (device) + post analyzers finalize ONCE for the whole
            # dirty set — their items split by path below
            batch_result = AnalysisResult()
            self.group.finalize(batch_result, post_files)
        except BaseException:
            self.group.abort()
            raise
        self._split_batch_result(batch_result, unit_of, unit_results, dirty)

        for unit, _files, _nbytes in dirty:
            result = unit_results[unit]
            blob = result.to_blob_info()
            self.handlers.post_handle(result, blob)
            self.cache.put_blob(unit_keys[unit], blob.to_dict())

    def _split_batch_result(self, batch: AnalysisResult, unit_of: dict,
                            unit_results: dict, dirty) -> None:
        first_unit = min(u for u, _, _ in dirty)

        def target(path: str) -> AnalysisResult | None:
            return unit_results.get(unit_of.get(path, ""))

        for item_list, attr in (
            (batch.package_infos, "package_infos"),
            (batch.applications, "applications"),
            (batch.misconfigurations, "misconfigurations"),
            (batch.secrets, "secrets"),
            (batch.licenses, "licenses"),
            (batch.custom_resources, "custom_resources"),
        ):
            for item in item_list:
                r = target(item.file_path)
                if r is None:
                    # a batched item for a path outside the dirty set
                    # cannot happen by construction; keep it loudly rather
                    # than dropping a finding
                    logger.warning(
                        "batched %s finding for unplanned path %s kept in "
                        "unit %r", attr, item.file_path, first_unit,
                    )
                    r = unit_results[first_unit]
                getattr(r, attr).append(item)
        for path, digest in (batch.digests or {}).items():
            r = target(path) or unit_results[first_unit]
            r.digests[path] = digest
        for path in batch.system_files:
            r = target(path) or unit_results[first_unit]
            r.system_files.append(path)
        # non-path-attributed fields are only ever produced by PER-FILE
        # analyzers (os-release, apk-repo, buildinfo), which landed in
        # their unit's result directly; a batched one would be a new
        # analyzer contract violation — keep it deterministic and loud
        if batch.os or batch.repository or batch.build_info:
            logger.warning(
                "batched analyzer produced non-path-attributed state; "
                "folding into unit %r (incremental split cannot attribute "
                "it)", first_unit,
            )
            unit_results[first_unit].merge(
                AnalysisResult(
                    os=batch.os, repository=batch.repository,
                    build_info=batch.build_info,
                )
            )
