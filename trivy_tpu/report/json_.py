"""JSON report writer (ref: pkg/report/writer.go JSON branch).

Field names and nesting match the reference's JSON schema (SchemaVersion,
ArtifactName, Results[].Target/Class/Secrets/Vulnerabilities/...), so tools
consuming trivy JSON can consume this output unchanged.
"""

from __future__ import annotations

import json

from trivy_tpu.types import Report


def write_json(report: Report, out, **_kw) -> None:
    json.dump(report.to_dict(), out, indent=2, ensure_ascii=False)
    out.write("\n")
