"""Template writer (ref: pkg/report/template.go).

Renders a Go-template-subset over the report's JSON form (the same
PascalCase document ``--format json`` emits), so the common community
templates keep working:

- ``{{ .Field.Sub }}`` — lookup (``.`` is the report at top level,
  rebound inside range)
- ``{{ range .X }}...{{ end }}`` — iteration
- ``{{ if .X }}...{{ else }}...{{ end }}`` — truthiness conditional
- ``{{ len .X }}``, ``{{ . | toLower }}`` / ``toUpper`` / ``json`` /
  ``escapeXML`` pipes

``@path`` template arguments load the template from a file, as the
reference does. Sprig's full function set is intentionally not replicated.
"""

from __future__ import annotations

import json as json_mod
import re
from html import escape

from trivy_tpu.types import Report

_TOKEN = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.S)

_FUNCS = {
    "toLower": lambda v: str(v).lower(),
    "toUpper": lambda v: str(v).upper(),
    "json": lambda v: json_mod.dumps(v),
    "escapeXML": lambda v: escape(str(v), quote=True),
}


class TemplateError(ValueError):
    pass


def _lookup(expr: str, dot):
    if expr == ".":
        return dot
    cur = dot
    for part in expr.lstrip(".").split("."):
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _eval(expr: str, dot):
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    if head.startswith("len "):
        v = _lookup(head[4:].strip(), dot)
        val = len(v) if v is not None else 0
    elif head.startswith('"') and head.endswith('"'):
        val = head[1:-1]
    else:
        val = _lookup(head, dot)
    for fn in parts[1:]:
        f = _FUNCS.get(fn)
        if f is None:
            raise TemplateError(f"unsupported template function: {fn}")
        val = f(val)
    return val


def _parse(tokens: list, i: int, stop: tuple) -> tuple[list, int]:
    """-> (nodes, next_index); nodes are ('text', s) | ('expr', e) |
    ('range', e, body) | ('if', e, body, else_body)."""
    nodes: list = []
    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "text":
            nodes.append(("text", val))
            i += 1
            continue
        action = val
        if action in stop:
            return nodes, i
        if action.startswith("range "):
            body, j = _parse(tokens, i + 1, ("end",))
            nodes.append(("range", action[6:].strip(), body))
            i = j + 1
        elif action.startswith("if "):
            body, j = _parse(tokens, i + 1, ("else", "end"))
            else_body: list = []
            if tokens[j][1] == "else":
                else_body, j = _parse(tokens, j + 1, ("end",))
            nodes.append(("if", action[3:].strip(), body, else_body))
            i = j + 1
        else:
            nodes.append(("expr", action))
            i += 1
    return nodes, i


def _render(nodes: list, dot, out: list) -> None:
    for node in nodes:
        if node[0] == "text":
            out.append(node[1])
        elif node[0] == "expr":
            v = _eval(node[1], dot)
            out.append("" if v is None else str(v))
        elif node[0] == "range":
            seq = _eval(node[1], dot) or []
            for item in seq:
                _render(node[2], item, out)
        elif node[0] == "if":
            v = _eval(node[1], dot)
            _render(node[2] if v else node[3], dot, out)


def render(template: str, context) -> str:
    tokens: list = []
    pos = 0
    for m in _TOKEN.finditer(template):
        if m.start() > pos:
            tokens.append(("text", template[pos : m.start()]))
        tokens.append(("action", m.group(1)))
        pos = m.end()
    if pos < len(template):
        tokens.append(("text", template[pos:]))
    nodes, _ = _parse(tokens, 0, ())
    out: list = []
    _render(nodes, context, out)
    return "".join(out)


def write_template(report: Report, out, template: str = "", **kw) -> None:
    if not template:
        raise TemplateError("--format template requires --template")
    if template.startswith("@"):
        with open(template[1:], "r", encoding="utf-8") as f:
            template = f.read()
    out.write(render(template, report.to_dict()))
