"""SARIF 2.1.0 writer (ref: pkg/report/sarif.go).

One run with one rule per distinct finding ID (vulnerability, secret rule,
misconfiguration check); results reference rules by index and carry physical
locations with line regions, matching the reference's shape so SARIF
consumers (e.g. code-scanning UIs) ingest both identically.
"""

from __future__ import annotations

import json

from trivy_tpu.types import Report

SARIF_VERSION = "2.1.0"
SCHEMA = "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json"

# severity -> SARIF level (ref: sarif.go toSarifErrorLevel)
_LEVELS = {
    "CRITICAL": "error",
    "HIGH": "error",
    "MEDIUM": "warning",
    "LOW": "note",
    "UNKNOWN": "note",
}
# severity -> security-severity property (ref: sarif.go toSarifRuleName scores)
_SCORES = {
    "CRITICAL": "9.5",
    "HIGH": "8.0",
    "MEDIUM": "5.5",
    "LOW": "2.0",
    "UNKNOWN": "0.0",
}


def _region(start: int, end: int) -> dict:
    start = max(1, start or 1)
    return {
        "startLine": start,
        "startColumn": 1,
        "endLine": max(start, end or start),
        "endColumn": 1,
    }


def write_sarif(report: Report, out, **kw) -> None:
    rules: list[dict] = []
    rule_index: dict[str, int] = {}
    results: list[dict] = []

    def rule_for(rid: str, name: str, severity: str, help_text: str,
                 help_uri: str = "") -> int:
        if rid in rule_index:
            return rule_index[rid]
        rule = {
            "id": rid,
            "name": name,
            "shortDescription": {"text": rid},
            "fullDescription": {"text": help_text or rid},
            "defaultConfiguration": {
                "level": _LEVELS.get(severity, "note"),
            },
            "properties": {
                "tags": ["security", severity],
                "precision": "very-high",
                "security-severity": _SCORES.get(severity, "0.0"),
            },
        }
        if help_uri:
            rule["helpUri"] = help_uri
        rule_index[rid] = len(rules)
        rules.append(rule)
        return rule_index[rid]

    def add_result(rid: str, idx: int, message: str, uri: str,
                   start: int = 1, end: int = 1) -> None:
        results.append(
            {
                "ruleId": rid,
                "ruleIndex": idx,
                "level": rules[idx]["defaultConfiguration"]["level"],
                "message": {"text": message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": uri,
                                "uriBaseId": "ROOTPATH",
                            },
                            "region": _region(start, end),
                        }
                    }
                ],
            }
        )

    for result in report.results:
        uri = result.target.lstrip("/") or result.target
        for v in result.vulnerabilities:
            idx = rule_for(
                v.vulnerability_id,
                f"{v.pkg_name}: {v.title}" if v.title else v.vulnerability_id,
                v.severity,
                v.description,
                v.primary_url,
            )
            msg = (
                f"Package: {v.pkg_name}\nInstalled Version: {v.installed_version}\n"
                f"Vulnerability {v.vulnerability_id}\nSeverity: {v.severity}\n"
                f"Fixed Version: {v.fixed_version or ''}"
            )
            add_result(v.vulnerability_id, idx, msg, uri)
        for s in result.secrets:
            idx = rule_for(s.rule_id, s.title, s.severity, s.title)
            add_result(
                s.rule_id, idx,
                f"Artifact: {result.target}\nType: secret\nSecret {s.title}\n"
                f"Severity: {s.severity}\nMatch: {s.match}",
                uri, s.start_line, s.end_line,
            )
        for m in result.misconfigurations:
            if m.status != "FAIL":
                continue
            idx = rule_for(m.id, m.title, m.severity, m.description, m.primary_url)
            add_result(
                m.id, idx,
                f"Artifact: {result.target}\nType: {result.type}\n"
                f"Vulnerability {m.id}\nSeverity: {m.severity}\n"
                f"Message: {m.message}",
                uri, m.start_line, m.end_line,
            )

    doc = {
        "$schema": SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "trivy-tpu",
                        "informationUri": "https://github.com/aquasecurity/trivy",
                        "fullName": "trivy-tpu security scanner",
                        "rules": rules,
                    }
                },
                "results": results,
                "columnKind": "utf16CodeUnits",
                "originalUriBaseIds": {
                    "ROOTPATH": {"uri": "file:///"},
                },
            }
        ],
    }
    json.dump(doc, out, indent=2)
    out.write("\n")
