"""GitHub dependency snapshot writer (ref: pkg/report/github/github.go).

Emits the dependency-submission API shape: one manifest per result with
resolved packages keyed by purl.
"""

from __future__ import annotations

import json

from trivy_tpu import purl as purl_mod
from trivy_tpu.types import OS, Report


def write_github(report: Report, out, **kw) -> None:
    os_d = report.metadata.get("OS")
    os_info = OS.from_dict(os_d) if os_d else None
    manifests = {}
    for result in report.results:
        if not result.packages:
            continue
        resolved = {}
        for pkg in result.packages:
            p = purl_mod.from_package(
                pkg, result.type or "", os_info if result.cls == "os-pkgs" else None
            )
            if p is None:
                continue
            resolved[pkg.name] = {
                "package_url": p.to_string(),
                "relationship": "direct" if pkg.relationship in ("direct", "root")
                else "indirect",
                "scope": "runtime",
                "dependencies": [],
            }
        if not resolved:
            continue
        manifests[result.target] = {
            "name": result.target,
            "file": {"source_location": result.target},
            "resolved": resolved,
        }
    doc = {
        "version": 0,
        "detector": {
            "name": "trivy-tpu",
            "version": "0.1.0",
            "url": "https://github.com/aquasecurity/trivy",
        },
        "scanned": report.created_at,
        "manifests": manifests,
    }
    json.dump(doc, out, indent=2)
    out.write("\n")
