"""Cosign vulnerability-attestation predicate writer
(ref: pkg/report/predicate/vuln.go CosignVulnPredicate — the Cosign
Vulnerability Scan Record shape: invocation, scanner{uri,version,db,
result}, metadata{scanStartedOn,scanFinishedOn}).
"""

from __future__ import annotations

import json
from datetime import datetime, timezone

from trivy_tpu.types import Report


def _version() -> str:
    from trivy_tpu.cli import VERSION

    return VERSION


def write_cosign_vuln(report: Report, out, **_kw) -> None:
    now = datetime.now(timezone.utc).isoformat().replace("+00:00", "Z")
    version = _version()
    predicate = {
        "invocation": {
            "parameters": None,
            "uri": "",
            "event_id": "",
            "builder.id": "",
        },
        "scanner": {
            "uri": f"pkg:github/trivy-tpu/trivy-tpu@{version}",
            "version": version,
            "db": {"uri": "", "version": ""},
            "result": report.to_dict(),
        },
        "metadata": {
            "scanStartedOn": now,
            "scanFinishedOn": now,
        },
    }
    json.dump(predicate, out, indent=2)
    out.write("\n")
