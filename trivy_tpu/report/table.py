"""Table report writer (ref: pkg/report/table).

Per-class renderers: vulnerabilities/misconfigurations in a summary table,
secrets with their censored code context blocks — matching the reference's
terminal layout closely enough to be familiar.
"""

from __future__ import annotations

from collections import Counter

from trivy_tpu.types import Report, Result

SEV_ORDER = ["CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN"]


def _rule(width: int) -> str:
    return "─" * width


def _severity_summary(counter: Counter) -> str:
    parts = [f"{s}: {counter.get(s, 0)}" for s in SEV_ORDER if counter.get(s, 0)]
    return ", ".join(parts) if parts else "none"


def write_table(report: Report, out, show_suppressed: bool = False,
                dependency_tree: bool = False, **_kw) -> None:
    visible = any(not r.is_empty for r in report.results)
    n_suppressed = sum(len(r.modified_findings) for r in report.results)
    if not visible:
        out.write(f"\n{report.artifact_name} ({report.artifact_type})\n")
        out.write("No issues detected.\n")
        if n_suppressed and not show_suppressed:
            out.write(
                f"({n_suppressed} suppressed finding"
                f"{'s' if n_suppressed != 1 else ''}; --show-suppressed lists them)\n"
            )
    for result in report.results:
        _write_result(result, out)
        if dependency_tree and result.vulnerabilities and result.packages:
            _write_dependency_tree(result, out)
        if show_suppressed and result.modified_findings:
            _write_suppressed(result, out)


def _write_dependency_tree(result: Result, out) -> None:
    """Reversed dependency-origin tree for vulnerable packages (ref: the
    table writer's --dependency-tree rendering over
    pkg/dependency/relationship.go graphs): each vulnerable package is a
    root; its children are the packages that depend on it, walking up to
    the direct dependencies a user can actually bump."""
    by_id = {p.id or f"{p.name}@{p.version}": p for p in result.packages}
    reverse: dict[str, list[str]] = {}
    for p in result.packages:
        pid = p.id or f"{p.name}@{p.version}"
        for dep in p.depends_on:
            reverse.setdefault(dep, []).append(pid)
    if not reverse:
        return
    from collections import Counter as _Counter

    vuln_counts: dict[str, _Counter] = {}
    for v in result.vulnerabilities:
        pid = v.pkg_id or f"{v.pkg_name}@{v.installed_version}"
        vuln_counts.setdefault(pid, _Counter())[v.severity] += 1
    out.write("\nDependency Origin Tree (Reversed)\n")
    out.write(_rule(40) + "\n")
    out.write(f"{result.target}\n")
    roots = sorted(vuln_counts)
    for ri, pid in enumerate(roots):
        last_root = ri == len(roots) - 1
        counts = vuln_counts[pid]
        summary = ", ".join(f"{s}: {c}" for s, c in sorted(counts.items()))
        out.write(f"{'└── ' if last_root else '├── '}{pid}, ({summary})\n")
        prefix = "    " if last_root else "│   "
        # BFS up the reverse edges (cycle-guarded) to show who pulls it in
        seen = {pid}
        level = [pid]
        depth = 0
        while level and depth < 8:
            parents = sorted({
                par for node in level for par in reverse.get(node, [])
                if par not in seen
            })
            if not parents:
                break
            seen.update(parents)
            for pi, par in enumerate(parents):
                last = pi == len(parents) - 1
                rel = ""
                pk = by_id.get(par)
                if pk is not None and pk.relationship in ("direct", "root", "workspace"):
                    rel = f" ({pk.relationship})"
                out.write(
                    prefix + "    " * depth
                    + ("└── " if last else "├── ") + par + rel + "\n"
                )
            level = parents
            depth += 1


def _write_suppressed(result: Result, out) -> None:
    """Suppressed-findings table (ref: pkg/report/table --show-suppressed)."""
    _header(out, f"{result.target} (suppressed)",
            f"— {len(result.modified_findings)} findings")
    cols = ["ID", "Type", "Status", "Statement", "Source"]
    rows = []
    for m in result.modified_findings:
        fid = (
            m.finding.get("VulnerabilityID")
            or m.finding.get("ID")
            or m.finding.get("RuleID")
            or m.finding.get("Name", "")
        )
        rows.append([fid, m.type, m.status, (m.statement or "")[:50], m.source])
    _grid(out, cols, rows)


def _header(out, title: str, extra: str = "") -> None:
    out.write(f"\n{title}{(' ' + extra) if extra else ''}\n")
    out.write(_rule(max(20, len(title) + len(extra) + 1)) + "\n")


def _write_result(result: Result, out) -> None:
    if result.vulnerabilities:
        counter = Counter(v.severity for v in result.vulnerabilities)
        _header(
            out,
            f"{result.target} ({result.type})",
            f"— {len(result.vulnerabilities)} vulnerabilities ({_severity_summary(counter)})",
        )
        cols = ["Library", "Vulnerability", "Severity", "Installed", "Fixed", "Title"]
        rows = [
            [
                v.pkg_name,
                v.vulnerability_id,
                v.severity,
                v.installed_version,
                v.fixed_version or "—",
                (v.title or "")[:60],
            ]
            for v in result.vulnerabilities
        ]
        _grid(out, cols, rows)
    if result.secrets:
        counter = Counter(s.severity for s in result.secrets)
        _header(
            out,
            result.target,
            f"— {len(result.secrets)} secrets ({_severity_summary(counter)})",
        )
        for s in result.secrets:
            out.write(f"\n{s.severity}: {s.title} ({s.rule_id})\n")
            loc = (
                f"line {s.start_line}"
                if s.start_line == s.end_line
                else f"lines {s.start_line}-{s.end_line}"
            )
            out.write(f"{_rule(40)}\n{result.target}:{loc}\n")
            for line in s.code.lines:
                marker = ">" if line.is_cause else " "
                out.write(f"{line.number:>4} {marker} {line.content}\n")
            out.write(_rule(40) + "\n")
    if result.misconfigurations:
        fails = [m for m in result.misconfigurations if m.status == "FAIL"]
        counter = Counter(m.severity for m in fails)
        _header(
            out,
            f"{result.target} ({result.type})",
            f"— {len(fails)} failures ({_severity_summary(counter)})",
        )
        for m in fails:
            out.write(f"\n{m.severity}: {m.id} — {m.title}\n")
            if m.message:
                out.write(f"  {m.message}\n")
            if m.start_line:
                out.write(f"  at {result.target}:{m.start_line}\n")
    if result.licenses:
        counter = Counter(l.severity for l in result.licenses)
        _header(
            out,
            f"{result.target} (license)",
            f"— {len(result.licenses)} findings ({_severity_summary(counter)})",
        )
        cols = ["Package/File", "License", "Category", "Severity"]
        rows = [
            [l.pkg_name or l.file_path, l.name, l.category, l.severity]
            for l in result.licenses
        ]
        _grid(out, cols, rows)
    if result.packages and not (
        result.vulnerabilities or result.secrets or result.licenses
    ):
        _header(out, f"{result.target} ({result.type})", f"— {len(result.packages)} packages")
        cols = ["Package", "Version"]
        rows = [[p.name, p.version] for p in result.packages]
        _grid(out, cols, rows)


def _grid(out, cols: list[str], rows: list[list[str]]) -> None:
    widths = [len(c) for c in cols]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    line = "┼".join(_rule(w + 2) for w in widths)

    def fmt(cells):
        return "│".join(f" {str(c):<{widths[i]}} " for i, c in enumerate(cells))

    out.write(fmt(cols) + "\n")
    out.write(line + "\n")
    for row in rows:
        out.write(fmt(row) + "\n")
