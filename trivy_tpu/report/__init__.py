"""Report writers (ref: pkg/report/writer.go:45-99 format switch)."""

from __future__ import annotations

import sys

from trivy_tpu.types import Report


def write(report: Report, fmt: str = "table", output=None, **kw) -> None:
    out = output or sys.stdout
    if fmt == "json":
        from trivy_tpu.report.json_ import write_json

        write_json(report, out, **kw)
    elif fmt == "table":
        from trivy_tpu.report.table import write_table

        write_table(report, out, **kw)
    elif fmt == "sarif":
        from trivy_tpu.report.sarif import write_sarif

        write_sarif(report, out, **kw)
    elif fmt in ("cyclonedx", "spdx", "spdx-json"):
        from trivy_tpu.sbom.io import encode_report

        encode_report(report, fmt, out, **kw)
    elif fmt == "github":
        from trivy_tpu.report.github import write_github

        write_github(report, out, **kw)
    elif fmt == "template":
        from trivy_tpu.report.template import write_template

        write_template(report, out, **kw)
    elif fmt == "cosign-vuln":
        from trivy_tpu.report.predicate import write_cosign_vuln

        write_cosign_vuln(report, out, **kw)
    else:
        raise ValueError(f"unknown format: {fmt}")
