"""Structured logging facade.

Mirrors the reference's slog wrapper (ref: pkg/log/logger.go:20-28): a thin
layer over :mod:`logging` with per-subsystem prefixes, ``--debug``/``--quiet``
switches, and deferred configuration so library code can log before the CLI
has parsed flags. ``--log-format json`` swaps the formatter for one JSON
object per line (ts/level/subsystem/msg) so server-mode logs are
machine-parseable; plain stays the default.
"""

from __future__ import annotations

import json
import logging
import sys
import time

_ROOT_NAME = "trivy_tpu"
_configured = False


def logger(prefix: str | None = None) -> logging.Logger:
    """Return the framework logger, optionally namespaced by subsystem."""
    name = _ROOT_NAME if not prefix else f"{_ROOT_NAME}.{prefix}"
    return logging.getLogger(name)


class _JSONFormatter(logging.Formatter):
    """One JSON object per line:
    {"ts", "level", "subsystem", "msg", "trace_id"}."""

    def format(self, record: logging.LogRecord) -> str:
        subsystem = record.name
        if subsystem.startswith(_ROOT_NAME):
            subsystem = subsystem[len(_ROOT_NAME):].lstrip(".") or "root"
        doc = {
            # UTC with an explicit Z: collectors correlating logs across
            # hosts must not have to guess the zone
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int((record.created % 1) * 1000):03d}Z",
            "level": record.levelname,
            "subsystem": subsystem,
            "msg": record.getMessage(),
        }
        # active scan trace id (the same id a client's traceparent carried,
        # since server handlers join the incoming trace): lets collectors
        # correlate server log lines with client traces. Lazy import — log
        # must stay importable before/without the obs subsystem.
        try:
            from trivy_tpu import obs

            doc["trace_id"] = obs.current().trace_id
        except Exception:
            pass
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def init(
    debug: bool = False,
    quiet: bool = False,
    stream=None,
    fmt: str = "plain",
) -> None:
    """Configure the root framework logger once (idempotent re-config allowed)."""
    global _configured
    root = logging.getLogger(_ROOT_NAME)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    if fmt == "json":
        handler.setFormatter(_JSONFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s [%(name)s] %(message)s", "%H:%M:%S"
            )
        )
    root.addHandler(handler)
    if quiet:
        root.setLevel(logging.ERROR)
    elif debug:
        root.setLevel(logging.DEBUG)
    else:
        root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def is_configured() -> bool:
    return _configured
