"""Structured logging facade.

Mirrors the reference's slog wrapper (ref: pkg/log/logger.go:20-28): a thin
layer over :mod:`logging` with per-subsystem prefixes, ``--debug``/``--quiet``
switches, and deferred configuration so library code can log before the CLI
has parsed flags.
"""

from __future__ import annotations

import logging
import sys

_ROOT_NAME = "trivy_tpu"
_configured = False


def logger(prefix: str | None = None) -> logging.Logger:
    """Return the framework logger, optionally namespaced by subsystem."""
    name = _ROOT_NAME if not prefix else f"{_ROOT_NAME}.{prefix}"
    return logging.getLogger(name)


def init(debug: bool = False, quiet: bool = False, stream=None) -> None:
    """Configure the root framework logger once (idempotent re-config allowed)."""
    global _configured
    root = logging.getLogger(_ROOT_NAME)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s [%(name)s] %(message)s", "%H:%M:%S")
    )
    root.addHandler(handler)
    if quiet:
        root.setLevel(logging.ERROR)
    elif debug:
        root.setLevel(logging.DEBUG)
    else:
        root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def is_configured() -> bool:
    return _configured
