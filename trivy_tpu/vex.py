"""VEX (Vulnerability Exploitability eXchange) filtering.

Suppresses detected vulnerabilities whose VEX status is ``not_affected`` or
``fixed`` (ref: pkg/vex/vex.go:65-200 Filter/NotAffected). Three document
formats are auto-detected, matching the reference's format sniffing
(ref: pkg/vex/document.go):

- OpenVEX (``@context`` openvex.dev): statements with vulnerability name,
  product identifiers (purl), status, justification
  (ref: pkg/vex/openvex.go).
- CycloneDX VEX: a BOM whose ``vulnerabilities[].analysis.state`` carries
  the status and ``affects[].ref`` points at bom-refs / purls
  (ref: pkg/vex/cyclonedx.go).
- CSAF VEX: ``product_tree`` branches with purl helpers +
  ``vulnerabilities[].product_status`` (ref: pkg/vex/csaf.go — the subset
  driven by known_not_affected/fixed).

Product matching is purl-based: a VEX purl matches a detected package when
type/namespace/name agree, the VEX version (if given) equals the package
version, and VEX qualifiers (if given) are a subset of the package's —
the openvex matching semantics. The reference additionally walks the SBOM
component graph for subcomponent statements; this build's reports are
flat, so products match the affected package directly.

Suppressed findings are recorded in ``Result.modified_findings`` and
surface as ``ExperimentalModifiedFindings`` in JSON output, like the
reference's ``--show-suppressed`` data.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from trivy_tpu import log
from trivy_tpu.types import ModifiedFinding, Report

logger = log.logger("vex")

_SUPPRESS_STATUSES = ("not_affected", "fixed")

# status vocabulary normalization per format
_CDX_STATES = {
    "not_affected": "not_affected",
    "resolved": "fixed",
    "resolved_with_pedigree": "fixed",
    "exploitable": "affected",
    "in_triage": "under_investigation",
    "false_positive": "not_affected",
}


@dataclass
class Statement:
    vuln_id: str
    purls: list[str]
    status: str  # not_affected | fixed | affected | under_investigation
    justification: str = ""
    source: str = ""
    # True only when the document genuinely declared no products (OpenVEX
    # product-less statements apply globally). Statements whose declared
    # products failed to resolve to purls must NOT match everything.
    match_all: bool = False


# ---------------------------------------------------------------------------
# purl matching
# ---------------------------------------------------------------------------


def _parse_purl(purl: str):
    """Split ``pkg:type/ns/name@version?q=v`` → (type, namespace, name,
    version, qualifiers) — enough structure for matching."""
    if not purl.startswith("pkg:"):
        return None
    body = purl[4:]
    qualifiers: dict[str, str] = {}
    if "?" in body:
        body, q = body.split("?", 1)
        for pair in q.split("&"):
            if "=" in pair:
                k, v = pair.split("=", 1)
                qualifiers[k] = v
    version = ""
    if "@" in body:
        body, version = body.rsplit("@", 1)
    parts = [p for p in body.split("/") if p]
    if not parts:
        return None
    ptype = parts[0]
    name = parts[-1] if len(parts) > 1 else ""
    namespace = "/".join(parts[1:-1])
    return (ptype.lower(), namespace, name, version, qualifiers)


def purl_matches(vex_purl: str, pkg_purl: str) -> bool:
    """openvex-style matching: the VEX purl's specified fields must agree."""
    a = _parse_purl(vex_purl)
    b = _parse_purl(pkg_purl)
    if a is None or b is None:
        return False
    at, ans, an, av, aq = a
    bt, bns, bn, bv, bq = b
    if at != bt or an != bn:
        return False
    if ans and ans != bns:
        return False
    if av and av != bv:
        return False
    for k, v in aq.items():
        if bq.get(k) != v:
            return False
    return True


# ---------------------------------------------------------------------------
# document loading
# ---------------------------------------------------------------------------


class VexDocument:
    def __init__(self, statements: list[Statement], source: str):
        self.statements = statements
        self.source = source

    def not_affected(self, vuln_id: str, purl: str) -> ModifiedFinding | None:
        """Last matching statement wins (OpenVEX override semantics,
        ref: pkg/vex/openvex.go NotAffected)."""
        matched = [
            s
            for s in self.statements
            if s.vuln_id == vuln_id
            and (s.match_all or any(purl_matches(p, purl) for p in s.purls))
        ]
        if not matched:
            return None
        stmt = matched[-1]
        if stmt.status in _SUPPRESS_STATUSES:
            return ModifiedFinding(
                type="vulnerability",
                status=stmt.status,
                statement=stmt.justification,
                source=self.source,
            )
        return None


def load(path: str) -> VexDocument:
    """Load a VEX file, sniffing its format."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    source = os.path.basename(path)
    if "@context" in doc and "openvex" in str(doc.get("@context", "")):
        return VexDocument(_load_openvex(doc), source)
    if doc.get("bomFormat") == "CycloneDX" or "vulnerabilities" in doc and "components" in doc:
        return VexDocument(_load_cyclonedx(doc), source)
    if "document" in doc and "product_tree" in doc:
        return VexDocument(_load_csaf(doc), source)
    raise ValueError(f"unrecognized VEX format in {path}")


def _load_openvex(doc: dict) -> list[Statement]:
    out = []
    for stmt in doc.get("statements", []) or []:
        vuln = stmt.get("vulnerability") or {}
        vuln_id = vuln.get("name", "") if isinstance(vuln, dict) else str(vuln)
        purls = []
        products = stmt.get("products", []) or []
        for product in products:
            if isinstance(product, dict):
                pid = product.get("@id", "")
                if pid.startswith("pkg:"):
                    purls.append(pid)
                for ident in (product.get("identifiers") or {}).values():
                    if str(ident).startswith("pkg:"):
                        purls.append(str(ident))
            elif str(product).startswith("pkg:"):
                purls.append(str(product))
        out.append(
            Statement(
                vuln_id=vuln_id,
                purls=purls,
                status=stmt.get("status", ""),
                justification=stmt.get("justification", "")
                or stmt.get("impact_statement", ""),
                source="OpenVEX",
                match_all=not products,
            )
        )
    return out


def _load_cyclonedx(doc: dict) -> list[Statement]:
    # bom-ref → purl for affects[].ref resolution
    ref_purl: dict[str, str] = {}
    meta_comp = (doc.get("metadata") or {}).get("component") or {}
    for comp in list(doc.get("components", []) or []) + [meta_comp]:
        if comp.get("bom-ref") and comp.get("purl"):
            ref_purl[comp["bom-ref"]] = comp["purl"]
    out = []
    for vuln in doc.get("vulnerabilities", []) or []:
        analysis = vuln.get("analysis") or {}
        status = _CDX_STATES.get(analysis.get("state", ""), "")
        purls = []
        affects = vuln.get("affects", []) or []
        for affect in affects:
            ref = affect.get("ref", "")
            purl = ref_purl.get(ref, ref if ref.startswith("pkg:") else "")
            if purl:
                purls.append(purl)
        if not purls:
            # affects were declared but none resolved to a purl (or none were
            # declared at all) — suppressing everything would silently hide
            # real vulnerabilities; CDX VEX matching is product-based only.
            continue
        out.append(
            Statement(
                vuln_id=vuln.get("id", ""),
                purls=purls,
                status=status,
                justification=analysis.get("detail", "")
                or analysis.get("justification", ""),
                source="CycloneDX VEX",
            )
        )
    return out


def _csaf_purls(branches: list, out: dict) -> None:
    """product id → purl from the (recursive) CSAF product tree."""
    for br in branches or []:
        prod = br.get("product") or {}
        pid = prod.get("product_id", "")
        helper = (prod.get("product_identification_helper") or {}).get("purl", "")
        if pid and helper:
            out[pid] = helper
        _csaf_purls(br.get("branches"), out)


def _load_csaf(doc: dict) -> list[Statement]:
    purls: dict[str, str] = {}
    _csaf_purls((doc.get("product_tree") or {}).get("branches"), purls)
    # relationships: composed products inherit the component purl
    for rel in (doc.get("product_tree") or {}).get("relationships", []) or []:
        child = (rel.get("full_product_name") or {}).get("product_id", "")
        parent = rel.get("product_reference", "")
        if child and parent in purls:
            purls[child] = purls[parent]
    out = []
    for vuln in doc.get("vulnerabilities", []) or []:
        status_map = vuln.get("product_status") or {}
        for key, status in (
            ("known_not_affected", "not_affected"),
            ("fixed", "fixed"),
        ):
            ids = status_map.get(key) or []
            stmt_purls = [purls[i] for i in ids if i in purls]
            if not stmt_purls:
                # no product ids, or ids that resolved to no purls — do
                # not let this statement match every package
                continue
            out.append(
                Statement(
                    vuln_id=vuln.get("cve", "") or (vuln.get("ids") or [{}])[0].get("text", ""),
                    purls=stmt_purls,
                    status=status,
                    justification=(vuln.get("threats") or [{}])[0].get("details", ""),
                    source="CSAF VEX",
                )
            )
    return out


# ---------------------------------------------------------------------------
# report filtering
# ---------------------------------------------------------------------------


class RepositorySet:
    """VEX repositories (ref: pkg/vex/repo/: manifest ``vex-repository.json``,
    index at ``<repo>/0.1/index.json``, documents resolved relative to the
    index). The ``--vex repo`` source reads the repository config
    (``repository.yaml``, a ``repositories: [{name, url, enabled}]`` list),
    then looks up each vulnerability's package by its version-less purl in
    every enabled repository's index, in config order — first repository
    holding the package wins (ref: pkg/vex/repo.go:90-113).

    Zero-egress build: repositories must already be present in the cache
    (``<cache>/vex/repositories/<name>/``); downloading is the env-blocked
    seam, resolution/matching is complete.
    """

    SCHEMA_VERSION = "0.1"

    def __init__(self, cache_dir: str, config_path: str = ""):
        import yaml

        self.indexes: list[tuple[str, str, dict, str]] = []
        self._doc_cache: dict[str, VexDocument | None] = {}
        config_path = config_path or os.path.join(
            cache_dir, "vex", "repository.yaml"
        )
        if not os.path.exists(config_path):
            alt = os.path.expanduser("~/.trivy/vex/repository.yaml")
            config_path = alt if os.path.exists(alt) else config_path
        try:
            with open(config_path, encoding="utf-8") as f:
                conf = yaml.safe_load(f) or {}
        except (OSError, yaml.YAMLError) as e:
            logger.warning(
                "no usable VEX repository config at %s (%s); `--vex repo` "
                "has nothing to consult", config_path, e,
            )
            return
        for r in conf.get("repositories") or []:
            if not (r or {}).get("enabled", True):
                continue
            name = str(r.get("name", ""))
            repo_dir = os.path.join(cache_dir, "vex", "repositories", name)
            index_path = os.path.join(
                repo_dir, self.SCHEMA_VERSION, "index.json"
            )
            if not os.path.exists(index_path):
                logger.warning(
                    "VEX repository %s not found locally (%s), skipping",
                    name, index_path,
                )
                continue
            try:
                with open(index_path, encoding="utf-8") as f:
                    raw = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                logger.warning("VEX repository %s: bad index: %s", name, e)
                continue
            pkgs = {}
            for entry in raw.get("packages") or raw.get("Packages") or []:
                pid = entry.get("id") or entry.get("ID") or ""
                if pid:
                    pkgs[pid] = {
                        "location": entry.get("location")
                        or entry.get("Location") or "",
                        "format": entry.get("format")
                        or entry.get("Format") or "",
                    }
            self.indexes.append(
                (name, str(r.get("url", "")), pkgs,
                 os.path.dirname(index_path))
            )

    @staticmethod
    def package_id(purl: str) -> str:
        """Version/qualifier/subpath-less purl — the index key (vex-repo
        spec §3.2; OCI keeps its repository_url qualifier)."""
        from trivy_tpu.purl import PackageURL

        try:
            p = PackageURL.parse(purl)
        except ValueError:
            return ""
        keep_q = {}
        if p.type == "oci" and "repository_url" in p.qualifiers:
            keep_q = {"repository_url": p.qualifiers["repository_url"]}
        p.version = ""
        p.qualifiers = keep_q
        p.subpath = ""
        return p.to_string()

    def not_affected(self, vuln_id: str, purl: str) -> ModifiedFinding | None:
        pkg_id = self.package_id(purl)
        if not pkg_id:
            return None
        for name, url, pkgs, base_dir in self.indexes:
            entry = pkgs.get(pkg_id)
            if entry is None:
                continue
            loc = os.path.join(base_dir, entry["location"])
            if loc not in self._doc_cache:
                try:
                    self._doc_cache[loc] = load(loc)
                except (OSError, ValueError, json.JSONDecodeError) as e:
                    logger.warning(
                        "VEX repository %s: cannot load %s: %s", name, loc, e
                    )
                    self._doc_cache[loc] = None
            doc = self._doc_cache[loc]
            if doc is not None:
                m = doc.not_affected(vuln_id, purl)
                if m is not None:
                    m.source = f"VEX Repository: {name} ({url})"
                    return m
            # higher-precedence repository holds the package: stop here
            return None
        return None


def filter_report(
    report: Report, sources: list[str], cache_dir: str = ""
) -> None:
    """Drop vulnerabilities a VEX document marks not_affected/fixed;
    record them as modified findings (ref: vex.go filterVulnerabilities).
    A source of ``repo`` consults the local VEX repositories."""
    docs = []
    for src in sources:
        if src == "repo":
            if not cache_dir:
                from trivy_tpu.cache.fs import default_cache_dir

                cache_dir = default_cache_dir()
            docs.append(RepositorySet(cache_dir))
            continue
        try:
            docs.append(load(src))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            logger.warning("cannot load VEX source %s: %s", src, e)
    if not docs:
        return
    for result in report.results:
        if not result.vulnerabilities:
            continue
        kept = []
        for vuln in result.vulnerabilities:
            purl = vuln.pkg_identifier.purl
            modified = None
            for doc in docs:
                modified = doc.not_affected(vuln.vulnerability_id, purl)
                if modified is not None:
                    break
            if modified is None:
                kept.append(vuln)
            else:
                modified.finding = vuln.to_dict()
                result.modified_findings.append(modified)
        result.vulnerabilities = kept
