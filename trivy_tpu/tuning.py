"""Telemetry-driven tuning: one typed config for every perf knob, plus the
offline/online machinery that closes the observability loop (ROADMAP item 4).

Three pieces:

- :class:`TuningConfig` — the knob sprawl (transfer streams, per-stream
  in-flight window, arena slab count, dispatch bucket ladder, host read
  ``--parallel``) consolidated into one typed config, resolved with strict
  precedence **CLI > env > autotune record > topology default** and carrying
  per-knob provenance (``source``) so every surface can say *why* a knob has
  its value. The secret feed, the mesh dispatch, the artifact read-ahead,
  the offline tuner, and the online controller all read the same object.

- **Offline autotune records** (:func:`load_autotune` /
  :func:`save_autotune`) — ``bench --autotune`` sweeps the knob space and
  records the optimum plus the measured surface into a versioned
  ``AUTOTUNE.json`` keyed by *topology fingerprint* (device kind, device
  count, link class). A later run on the same topology resolves unset knobs
  from the record; a mismatched fingerprint falls back to topology defaults
  LOUDLY (a record tuned for an 8-chip tunnel host must not silently steer
  a single-chip PCIe box).

- :class:`TuningController` — the online half: a per-scan control loop
  riding the live-telemetry cadence that adapts stream count, in-flight
  windows, and arena sizing mid-scan from gauge feedback (grow streams
  while work is queued and the device is unsaturated, shrink when
  device-bound, back off the in-flight window on OOM-split signals), with
  hysteresis and bounded ±1 steps so it cannot oscillate. The controller is
  itself first-class telemetry: every decision appends to a bounded
  decision log (input gauge snapshot, rule fired, knob delta) exported as
  Perfetto instant events + counter tracks in ``--trace-out``, a ``tuning``
  block in ``--metrics-out``/``--timeseries-out``, ``trivy_tpu_tuning_*``
  gauges on ``GET /metrics``, and a decisions column in the ``--live``
  line — an operator can replay every decision it made.

Zero-cost-when-off: with the controller off nothing here allocates — no
thread, no decision buffers, no gauges (the same bar as the telemetry
sampler; ``bench --smoke`` asserts it).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from trivy_tpu import log

logger = log.logger("tuning")

AUTOTUNE_VERSION = 1
AUTOTUNE_DEFAULT_PATH = "AUTOTUNE.json"
ENV_TUNING_FILE = "TRIVY_TPU_TUNING_FILE"

# online-controller cadence: one decision window per tick. Defaults to 2x
# the telemetry sampler's 250 ms so each tick sees at least one fresh
# sample of every gauge (--tuning-interval / TRIVY_TPU_TUNING_INTERVAL)
DEFAULT_TUNING_INTERVAL = 0.5

# fleet replica-poller cadence (--fleet-telemetry-interval /
# TRIVY_TPU_FLEET_TELEMETRY_INTERVAL, 0 = off): one /metrics scrape per
# replica per tick. Coarser than the in-process sampler's 250 ms — each
# tick is N HTTP round trips, and replica gauges only refresh at the
# replica's own sampler cadence anyway
DEFAULT_FLEET_TELEMETRY_INTERVAL = 1.0

# mid-scan shard re-planning (--fleet-split-threshold /
# TRIVY_TPU_FLEET_SPLIT_THRESHOLD, 0 = off): an in-flight fs shard whose
# wall exceeds this x the median shard estimate while its replica shows
# no headroom is split at a directory boundary and the remainder
# re-scattered to survivors. Above the speculate multiplier (2.0) by
# design: a full-copy twin is cheaper than a re-plan, so it gets first go
DEFAULT_FLEET_SPLIT_THRESHOLD = 3.0

# knobs TuningConfig owns; order is the canonical display/serialize order
KNOBS = (
    "feed_streams", "inflight", "arena_slabs", "bucket_rungs", "parallel",
    "fleet_inflight", "dedup_store_mb", "license_gate_block_min",
    "license_row_width",
)

# env spellings per knob (the feed-path pair predates this module and is
# documented in BASELINE.md; the rest follow the TRIVY_TPU_ prefix rule)
_ENV_NAMES = {
    "feed_streams": "TRIVY_TPU_FEED_STREAMS",
    "inflight": "TRIVY_TPU_FEED_INFLIGHT",
    "arena_slabs": "TRIVY_TPU_ARENA_SLABS",
    "bucket_rungs": "TRIVY_TPU_BUCKET_RUNGS",
    "parallel": "TRIVY_TPU_PARALLEL",
    "fleet_inflight": "TRIVY_TPU_FLEET_INFLIGHT",
    "dedup_store_mb": "TRIVY_TPU_DEDUP_STORE_MB",
    "license_gate_block_min": "TRIVY_TPU_LICENSE_GATE_BLOCK_MIN",
    "license_row_width": "TRIVY_TPU_LICENSE_ROW_WIDTH",
}


def validate_interval(value, name: str) -> float:
    """A sampling/tuning interval from flag/env input: a finite float
    >= 0 (0 = disabled). Negative, NaN, infinite, or garbage values are
    rejected LOUDLY at resolution time — a degenerate cadence would
    otherwise spawn a busy-spinning (or never-firing) background thread
    the user only notices from the symptoms."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        raise ValueError(f"{name}: not a number: {value!r}") from None
    if math.isnan(v) or math.isinf(v):
        raise ValueError(f"{name}: must be a finite number, got {value!r}")
    if v < 0:
        raise ValueError(f"{name}: must be >= 0 (0 disables), got {value!r}")
    return v


def validate_ratio(value, name: str) -> float:
    """A (0, 1] fraction from flag/env input, rejected loudly otherwise —
    a wire budget of 0 would force every batch raw silently, and > 1
    would 'compress' batches into more bytes than raw."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        raise ValueError(f"{name}: not a number: {value!r}") from None
    if math.isnan(v) or not 0.0 < v <= 1.0:
        raise ValueError(f"{name}: must be in (0, 1], got {value!r}")
    return v


def topology_fingerprint(devices=None, link: str | None = None) -> str:
    """``<device kind>:<device count>:<link class>`` — the key autotune
    records live under. Device kind/count come from the jax device set;
    the link class from :func:`trivy_tpu.parallel.mesh.link_class` (env
    override ``TRIVY_TPU_LINK_CLASS``)."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    platform = devices[0].platform if devices else "cpu"
    if link is None:
        from trivy_tpu.parallel.mesh import link_class

        link = link_class(platform)
    return f"{platform}:{len(devices)}:{link}"


@dataclass
class TuningConfig:
    """Every feed/dispatch perf knob, post-resolution. 0 means "derive the
    topology default at the point of use" (the secret scanner's stream
    heuristic, the artifact layer's DEFAULT_PARALLEL) — resolved values are
    always explicit in ``source`` so surfaces can tell tuned from auto."""

    feed_streams: int = 0   # transfer-stream worker threads (0 = auto)
    inflight: int = 0       # in-flight batches per stream (0 = auto: 2)
    arena_slabs: int = 0    # chunk-arena slab count (0 = derived bound)
    bucket_rungs: int = 0   # dispatch bucket-ladder depth (0 = default: 3)
    parallel: int = 0       # host read/analyze workers (0 = DEFAULT_PARALLEL)
    fleet_inflight: int = 0  # shard jobs in flight per fleet replica (0 = 2)
    dedup_store_mb: int = 0  # dedup hit-store LRU byte budget (0 = 32 MB)
    license_gate_block_min: int = 0  # shingle-gate density floor (0 = 16)
    license_row_width: int = 0  # license row-width ladder cap (0 = full)
    # compressed slab wire format (secret/compress.py). Modes, not int
    # optima — like controller/tuning_interval they resolve CLI > env >
    # default with provenance, but never from an autotune record
    compress: str = ""          # 'auto' | 'on' | 'off' ('' = auto at use)
    compress_min_ratio: float = 0.0  # per-batch wire budget fraction
    # (0 = codec default 0.875, the 7-bit-packing line)
    controller: bool = False          # online mid-scan adaptation
    tuning_interval: float = DEFAULT_TUNING_INTERVAL
    # fleet replica-poller cadence (0 = off: no poller thread, no parser
    # import, no fleet gauges); only consulted in --fleet mode
    fleet_telemetry_interval: float = DEFAULT_FLEET_TELEMETRY_INTERVAL
    # straggler split multiplier over the median shard estimate (0 = no
    # mid-scan re-planning); only consulted in --fleet mode
    fleet_split_threshold: float = DEFAULT_FLEET_SPLIT_THRESHOLD
    topology: str = ""                # fingerprint this config resolved for
    autotune_path: str | None = None  # record file consulted (if any)
    # per-knob provenance: cli | env | autotune | default
    source: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "feed_streams": self.feed_streams,
            "inflight": self.inflight,
            "arena_slabs": self.arena_slabs,
            "bucket_rungs": self.bucket_rungs,
            "parallel": self.parallel,
            "fleet_inflight": self.fleet_inflight,
            "dedup_store_mb": self.dedup_store_mb,
            "license_gate_block_min": self.license_gate_block_min,
            "license_row_width": self.license_row_width,
            "compress": self.compress,
            "compress_min_ratio": self.compress_min_ratio,
            "controller": self.controller,
            "tuning_interval": self.tuning_interval,
            "fleet_telemetry_interval": self.fleet_telemetry_interval,
            "fleet_split_threshold": self.fleet_split_threshold,
            "topology": self.topology,
            "source": dict(self.source),
        }


def _env_int(env: dict, knob: str) -> int | None:
    raw = env.get(_ENV_NAMES[knob], "")
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"{_ENV_NAMES[knob]}: not an integer: {raw!r}"
        ) from None
    return v if v > 0 else None


def load_autotune(path: str, topology: str) -> dict | None:
    """The autotune record for ``topology`` from a versioned AUTOTUNE.json,
    or None. Every fallback is loud: a missing/corrupt file, an alien
    version, and — most importantly — a topology-fingerprint miss each log
    a warning naming what was expected, so "silently running hand-me-down
    knobs from different hardware" cannot happen."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning(
            "autotune record %s unreadable (%s); using topology defaults",
            path, e,
        )
        return None
    if not isinstance(doc, dict) or doc.get("version") != AUTOTUNE_VERSION:
        logger.warning(
            "autotune record %s has version %r (want %d); using topology "
            "defaults", path, doc.get("version") if isinstance(doc, dict)
            else None, AUTOTUNE_VERSION,
        )
        return None
    records = doc.get("records") or {}
    rec = records.get(topology)
    if rec is None:
        logger.warning(
            "autotune record %s has no entry for topology %r (recorded: %s)"
            "; using topology defaults — run `bench.py --autotune` on this "
            "hardware to close the gap",
            path, topology, sorted(records) or "none",
        )
        return None
    best = rec.get("best")
    if not isinstance(best, dict):
        logger.warning(
            "autotune record %s[%s] carries no 'best' knobs; using "
            "topology defaults", path, topology,
        )
        return None
    return rec


def save_autotune(path: str, topology: str, best: dict, surface: list,
                  meta: dict | None = None) -> dict:
    """Merge one topology's sweep result into AUTOTUNE.json (other
    topologies' records are preserved) and return the full document."""
    doc: dict = {"version": AUTOTUNE_VERSION, "records": {}}
    try:
        with open(path) as f:
            prev = json.load(f)
    except FileNotFoundError:
        prev = None
    except (OSError, ValueError) as e:
        # rewriting over an unreadable file drops every OTHER topology's
        # swept optimum — that must be as loud as load_autotune's fallback
        logger.warning(
            "existing autotune record %s unreadable (%s); rewriting it "
            "fresh — prior topologies' records are lost", path, e,
        )
        prev = None
    if isinstance(prev, dict) and prev.get("version") == AUTOTUNE_VERSION:
        doc = prev
    elif prev is not None:
        logger.warning(
            "existing autotune record %s has version %r (want %d); "
            "rewriting it fresh — records for %s are lost",
            path, prev.get("version") if isinstance(prev, dict) else None,
            AUTOTUNE_VERSION,
            sorted((prev.get("records") or {}))
            if isinstance(prev, dict) else "unknown topologies",
        )
    doc.setdefault("records", {})[topology] = {
        "created_wall": time.time(),
        "best": {k: int(v) for k, v in best.items() if k in KNOBS},
        "surface": list(surface),
        **(meta or {}),
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    return doc


def resolve_tuning(opts: dict | None = None, env: dict | None = None,
                   autotune_path: str | None = None,
                   topology: str | None = None) -> TuningConfig:
    """Resolve the knob set with strict precedence per knob:
    **CLI (``opts``) > env > autotune record > topology default (0)**.

    ``opts`` carries the flag layer's already-resolved values (which fold
    config files in); 0/None there means "unset". ``autotune_path`` — an
    explicit path, else ``TRIVY_TPU_TUNING_FILE``, else ``AUTOTUNE.json``
    in the working directory when present — supplies swept optima for the
    current topology fingerprint; everything still unset stays 0 and the
    point of use derives its topology default (exactly today's heuristics,
    so an untuned run behaves identically to one before this module)."""
    opts = opts or {}
    env = os.environ if env is None else env
    # CLI option spellings per knob (the flag layer's dest names)
    cli_names = {
        "feed_streams": "secret_streams",
        "inflight": "secret_inflight",
        "arena_slabs": "secret_arena_slabs",
        "bucket_rungs": "secret_bucket_rungs",
        "parallel": "parallel",
        "fleet_inflight": "fleet_inflight",
        "dedup_store_mb": "secret_dedup_mb",
        "license_gate_block_min": "license_gate_block_min",
        "license_row_width": "license_row_width",
    }
    if autotune_path is None:
        autotune_path = opts.get("tuning_file") or env.get(ENV_TUNING_FILE)
    if autotune_path is None and os.path.exists(AUTOTUNE_DEFAULT_PATH):
        autotune_path = AUTOTUNE_DEFAULT_PATH
    # the topology fingerprint probes jax.local_devices(), which can
    # INITIALIZE an accelerator backend (libtpu acquires the chips).
    # Device-free scan paths (misconfig/vuln-only, cpu backend) resolve
    # tuning too — so fingerprint only when something will actually key
    # off it: an autotune record to look up, or a caller-supplied value
    if topology is None and autotune_path:
        topology = topology_fingerprint()
    record = (
        load_autotune(autotune_path, topology)
        if autotune_path and topology else None
    )
    rec_best = (record or {}).get("best") or {}
    topology = topology or ""

    cfg = TuningConfig(topology=topology, autotune_path=autotune_path)
    for knob in KNOBS:
        cli_v = opts.get(cli_names[knob])
        env_v = _env_int(env, knob)
        rec_v = rec_best.get(knob)
        if isinstance(cli_v, (int, float)) and int(cli_v) > 0:
            value, source = int(cli_v), "cli"
        elif env_v is not None:
            value, source = env_v, "env"
        elif isinstance(rec_v, (int, float)) and int(rec_v) > 0:
            value, source = int(rec_v), "autotune"
        else:
            value, source = 0, "default"
        setattr(cfg, knob, value)
        cfg.source[knob] = source
    # compressed-feed mode + wire budget (CLI > env > default, with
    # provenance; no autotune layer — the codec is a mode, not an optimum)
    raw_cmp = opts.get("secret_compress")
    if raw_cmp is None or raw_cmp == "":
        env_cmp = str(env.get("TRIVY_TPU_SECRET_COMPRESS", "")).lower()
        if env_cmp:
            if env_cmp in ("1", "true", "yes", "on"):
                env_cmp = "on"
            elif env_cmp in ("0", "false", "no", "off"):
                env_cmp = "off"
            if env_cmp not in ("auto", "on", "off"):
                raise ValueError(
                    f"TRIVY_TPU_SECRET_COMPRESS: use auto/on/off, got "
                    f"{env_cmp!r}"
                )
            cfg.compress, cfg.source["compress"] = env_cmp, "env"
        else:
            cfg.source["compress"] = "default"
    else:
        v = str(raw_cmp).lower()
        if v not in ("auto", "on", "off"):
            raise ValueError(
                f"--secret-compress: use auto/on/off, got {raw_cmp!r}"
            )
        cfg.compress, cfg.source["compress"] = v, "cli"
    raw_mr = opts.get("secret_compress_min_ratio")
    if raw_mr is None or raw_mr == 0:
        env_mr = env.get("TRIVY_TPU_SECRET_COMPRESS_MIN_RATIO") or None
        if env_mr is not None:
            cfg.compress_min_ratio = validate_ratio(
                env_mr, "TRIVY_TPU_SECRET_COMPRESS_MIN_RATIO"
            )
            cfg.source["compress_min_ratio"] = "env"
        else:
            cfg.source["compress_min_ratio"] = "default"
    else:
        cfg.compress_min_ratio = validate_ratio(
            raw_mr, "--secret-compress-min-ratio"
        )
        cfg.source["compress_min_ratio"] = "cli"
    # controller + cadence (no autotune layer: they are modes, not optima)
    raw_ctl = opts.get("tuning_controller")
    if raw_ctl is None:
        raw_ctl = env.get("TRIVY_TPU_TUNING_CONTROLLER", "")
        raw_ctl = str(raw_ctl).lower() in ("1", "true", "yes", "on")
    cfg.controller = bool(raw_ctl)
    raw_iv = opts.get("tuning_interval")
    if raw_iv is None:
        raw_iv = env.get("TRIVY_TPU_TUNING_INTERVAL") or None
    if raw_iv is not None:
        cfg.tuning_interval = validate_interval(
            raw_iv, "--tuning-interval/TRIVY_TPU_TUNING_INTERVAL"
        )
    # fleet telemetry cadence: same CLI > env > default ladder, explicit 0
    # (a mode, not an unset value) disables the poller entirely
    raw_fiv = opts.get("fleet_telemetry_interval")
    if raw_fiv is None:
        raw_fiv = env.get("TRIVY_TPU_FLEET_TELEMETRY_INTERVAL") or None
    if raw_fiv is not None:
        cfg.fleet_telemetry_interval = validate_interval(
            raw_fiv,
            "--fleet-telemetry-interval/TRIVY_TPU_FLEET_TELEMETRY_INTERVAL",
        )
    # straggler split multiplier: same ladder, explicit 0 turns mid-scan
    # re-planning off (validate_interval's >= 0 contract fits exactly)
    raw_fst = opts.get("fleet_split_threshold")
    if raw_fst is None:
        raw_fst = env.get("TRIVY_TPU_FLEET_SPLIT_THRESHOLD") or None
    if raw_fst is not None:
        cfg.fleet_split_threshold = validate_interval(
            raw_fst,
            "--fleet-split-threshold/TRIVY_TPU_FLEET_SPLIT_THRESHOLD",
        )
    if record is not None and any(
        s == "autotune" for s in cfg.source.values()
    ):
        logger.info(
            "tuning knobs loaded from %s for topology %s: %s",
            autotune_path, topology,
            {k: getattr(cfg, k) for k, s in cfg.source.items()
             if s == "autotune"},
        )
    return cfg


# -- admission budgets (ROADMAP item 1: multi-tenant serving) ----------------

# HBM proxy for one scan's device-side footprint: the chunk arena is the
# feed's residency ceiling (slabs x slab bytes — PR 6's RSS bound), so
# "how many scans fit" is budget / arena footprint. Slab bytes use the
# pallas-backend batch geometry (1024 rows x 8 KiB chunks); the CPU/XLA
# fallback slabs are smaller, which only makes this proxy conservative.
SLAB_PROXY_BYTES = 8 << 20
# feed.py arena derivation constants, mirrored here so budget resolution
# never imports the scanner (which initializes jax — a vuln-only server
# must not touch the accelerator to size its queue)
_FEED_QUEUE_DEPTH = 2
_ARENA_MARGIN = 2
_DEFAULT_STREAMS = 4
_DEFAULT_INFLIGHT = 2

HBM_BUDGET_ENV = "TRIVY_TPU_HBM_BUDGET_MB"
DEFAULT_HBM_BUDGET_MB = 1024
MAX_DERIVED_CONCURRENT = 32


def admission_budgets(cfg: TuningConfig | None = None,
                      env: dict | None = None) -> dict:
    """Concurrent-scan and queued-bytes budgets for the admission
    controller, resolved through :class:`TuningConfig` from the topology.

    ``per_scan_bytes`` is the arena footprint one scan pins host+device
    side (arena slabs x slab bytes — the HBM proxy); the concurrent-scan
    budget is how many such footprints fit ``TRIVY_TPU_HBM_BUDGET_MB``
    (default 1024 MB), and the queued-bytes budget caps the host-side
    queue at one full budget's worth of pending work — queueing more
    than the device can absorb in one wave only converts overload into
    memory growth.

    The budget multiplies by device count only when the caller supplies a
    ``cfg`` with a resolved topology fingerprint: the env-only resolution
    path (a detection-only scan server) deliberately never probes jax —
    acquiring accelerators to size a queue would be backwards — so it
    budgets for one device and the operator raises
    ``TRIVY_TPU_HBM_BUDGET_MB`` on bigger hosts.
    """
    env = os.environ if env is None else env
    if cfg is None:
        # autotune_path="" skips record discovery AND the jax topology
        # probe (resolve_tuning only fingerprints when a record is
        # consulted) — budget resolution stays accelerator-free
        cfg = resolve_tuning(autotune_path="", env=env)
    streams = cfg.feed_streams or _DEFAULT_STREAMS
    inflight = cfg.inflight or _DEFAULT_INFLIGHT
    slabs = cfg.arena_slabs or (
        _FEED_QUEUE_DEPTH + streams * inflight + _ARENA_MARGIN
    )
    slabs = max(2, slabs)
    per_scan_bytes = slabs * SLAB_PROXY_BYTES
    raw = env.get(HBM_BUDGET_ENV, "")
    if raw:
        try:
            budget_mb = int(raw)
        except ValueError:
            raise ValueError(
                f"{HBM_BUDGET_ENV}: not an integer: {raw!r}") from None
        if budget_mb <= 0:
            raise ValueError(f"{HBM_BUDGET_ENV}: must be > 0, got {raw!r}")
    else:
        budget_mb = DEFAULT_HBM_BUDGET_MB
    devices = 1
    if cfg.topology:
        try:  # "<kind>:<count>:<link>"
            devices = max(1, int(cfg.topology.split(":")[1]))
        except (IndexError, ValueError):
            devices = 1
    budget_bytes = budget_mb * (1 << 20) * devices
    max_concurrent = max(
        1, min(MAX_DERIVED_CONCURRENT, budget_bytes // per_scan_bytes)
    )
    return {
        "max_concurrent": int(max_concurrent),
        "queued_bytes": int(budget_bytes),
        "per_scan_bytes": int(per_scan_bytes),
        "hbm_budget_mb": budget_mb,
        "devices": devices,
    }


def stream_limit(initial: int) -> int:
    """Online-controller headroom above the configured stream count: the
    controller may grow streams up to 2x the starting point (capped at 16
    — axon-tunnel saturation measurements flatten well before that). The
    extra worker threads are allocated parked, controller-on only."""
    return max(initial, min(16, initial * 2))


def inflight_limit(initial: int) -> int:
    """Controller headroom for the per-stream in-flight window (2x,
    capped at 8: deeper windows only add host-memory residency once the
    link is saturated)."""
    return max(initial, min(8, initial * 2))


# -- online controller -------------------------------------------------------

# decision-rate bound: the log is replay evidence, not a firehose — at the
# default cadence 256 entries cover >2 minutes of *continuous* decisions,
# far beyond what hysteresis+cooldown allow; older entries drop counted
MAX_DECISIONS = 256
# hysteresis: a candidate rule must hold for this many CONSECUTIVE ticks
# before it fires (one noisy gauge sample cannot move a knob) ...
HYSTERESIS_TICKS = 2
# ... and after a knob moves, this many ticks pass before the next decision
# (the outcome window: the new setting must show up in the gauges first)
COOLDOWN_TICKS = 3
# OOM backoff holds longer: re-growing into a fresh OOM would thrash
OOM_COOLDOWN_TICKS = 8
# dead band: grow only while device busy <= GROW, shrink only past SHRINK —
# the gap between them is the no-decision zone that kills oscillation
GROW_BUSY_MAX = 0.80
SHRINK_BUSY_MIN = 0.95

# the gauge snapshot every decision must carry (the decision-log schema
# bench --smoke asserts): enough to replay why the rule fired
DECISION_GAUGES = (
    "queue_depth", "busy_ratio", "link_mbs", "arena_free", "oom_splits",
)
DECISION_FIELDS = ("t", "rule", "knob", "from", "to", "gauges")


class TuningController:
    """Per-scan online knob controller.

    ``adapter`` is the running pipeline's control surface (the secret
    scanner's ``_ScanRun`` in production; a stub in tests):

    - ``knobs() -> {"feed_streams", "inflight", "arena_slabs"}`` (current)
    - ``limits() -> {"max_streams", "max_inflight", "max_arena_slabs"}``
    - ``raw_gauges() -> dict`` — instantaneous gauges plus cumulative
      ``*_total`` counters the controller differentiates per tick
    - ``set_streams(n)`` / ``set_inflight(n)`` / ``grow_arena(k) -> int``

    Control law (one bounded ±1 step per decision, hysteresis + cooldown
    between them, dead band ``GROW_BUSY_MAX``..``SHRINK_BUSY_MIN``):

    - ``oom-backoff``: OOM-shaped batch splits observed → shrink the
      in-flight window (immediate — an OOM is a discrete loud event, not
      gauge noise — then the long cooldown holds the backoff)
    - ``shrink-streams``: device busy past the dead band → one less stream
    - ``grow-streams``: work queued AND device under the dead band (the
      link, not the device, is the binding constraint) → one more stream,
      arena grown to match so backpressure doesn't choke the new stream
    - ``grow-inflight``: same signal with streams maxed → deepen windows

    :meth:`step` is pure decision logic over an already-derived gauge dict
    — the hysteresis/convergence tests drive it with synthetic feeds, no
    threads or scans involved.
    """

    def __init__(self, adapter, ctx=None, interval: float | None = None,
                 clock=time.perf_counter):
        self.adapter = adapter
        self.ctx = ctx
        self.interval = (
            DEFAULT_TUNING_INTERVAL if interval is None else interval
        )
        self.clock = clock
        self.ticks = 0
        self.cooldown = 0
        self._pending: str | None = None
        self._streak = 0
        self._last_raw: dict | None = None
        self._last_t = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._gauges_set = False
        self._lock = threading.Lock()
        self.decisions: deque = deque(maxlen=MAX_DECISIONS)
        self.dropped = 0
        initial = dict(adapter.knobs())
        # the live document surfaces read (ctx.tuning["controller"]):
        # mutated in place under _lock, snapshotted by doc()
        self._doc = {
            "enabled": True,
            "interval": self.interval,
            "initial": initial,
            "current": dict(initial),
            "ticks": 0,
            "decisions": 0,
        }
        if ctx is not None:
            # surfaces (export, --live, heartbeat) snapshot the decision
            # log through ctx.tuning_doc() -> doc()
            ctx.tuning_controller = self

    # -- decision core ------------------------------------------------------

    def _candidate(self, g: dict) -> str | None:
        k = self.adapter.knobs()
        lim = self.adapter.limits()
        if g.get("oom_splits", 0) > 0 and k["inflight"] > 1:
            return "oom-backoff"
        busy = g.get("busy_ratio", 0.0)
        if busy >= SHRINK_BUSY_MIN and k["feed_streams"] > 1:
            return "shrink-streams"
        if g.get("queue_depth", 0.0) >= 1 and busy <= GROW_BUSY_MAX:
            if k["feed_streams"] < lim["max_streams"]:
                return "grow-streams"
            if k["inflight"] < lim["max_inflight"]:
                return "grow-inflight"
        return None

    def _record(self, t: float, rule: str, knob: str, old: int, new: int,
                g: dict) -> dict:
        d = {
            "t": round(t, 3),
            "rule": rule,
            "knob": knob,
            "from": int(old),
            "to": int(new),
            "gauges": {
                name: round(float(g.get(name, 0.0)), 4)
                for name in DECISION_GAUGES
            },
        }
        with self._lock:
            if len(self.decisions) == self.decisions.maxlen:
                self.dropped += 1
            self.decisions.append(d)
            self._doc["current"][knob] = int(new)
            self._doc["decisions"] = len(self.decisions) + self.dropped
            if self.dropped:
                self._doc["dropped"] = self.dropped
        from trivy_tpu.obs import recorder as flight

        flight.record(
            "tuning", f"{rule}: {knob} {int(old)}->{int(new)}", ctx=self.ctx,
        )
        return d

    def _apply(self, rule: str, g: dict, t: float) -> list[dict]:
        a = self.adapter
        k = a.knobs()
        out = []
        if rule == "oom-backoff":
            new = max(1, k["inflight"] - 1)
            if new != k["inflight"]:
                a.set_inflight(new)
                out.append(self._record(
                    t, rule, "inflight", k["inflight"], new, g))
        elif rule == "shrink-streams":
            new = max(1, k["feed_streams"] - 1)
            if new != k["feed_streams"]:
                a.set_streams(new)
                out.append(self._record(
                    t, rule, "feed_streams", k["feed_streams"], new, g))
        elif rule == "grow-streams":
            new = min(a.limits()["max_streams"], k["feed_streams"] + 1)
            if new != k["feed_streams"]:
                a.set_streams(new)
                out.append(self._record(
                    t, rule, "feed_streams", k["feed_streams"], new, g))
                # match the arena to the new stream's window so slab
                # backpressure doesn't immediately starve it
                grown = a.grow_arena(max(1, k["inflight"]))
                if grown != k["arena_slabs"]:
                    out.append(self._record(
                        t, rule, "arena_slabs", k["arena_slabs"], grown, g))
        elif rule == "grow-inflight":
            new = min(a.limits()["max_inflight"], k["inflight"] + 1)
            if new != k["inflight"]:
                a.set_inflight(new)
                out.append(self._record(
                    t, rule, "inflight", k["inflight"], new, g))
                grown = a.grow_arena(k["feed_streams"])
                if grown != k["arena_slabs"]:
                    out.append(self._record(
                        t, rule, "arena_slabs", k["arena_slabs"], grown, g))
        return out

    def step(self, g: dict, t: float | None = None) -> list[dict]:
        """One control tick over a derived gauge dict (keys:
        :data:`DECISION_GAUGES`); returns the decisions fired (usually
        none). OOM backoff fires immediately; every other rule must
        survive :data:`HYSTERESIS_TICKS` consecutive ticks, and any firing
        opens a cooldown window."""
        self.ticks += 1
        with self._lock:
            self._doc["ticks"] = self.ticks
        if t is None:
            t = self.ticks * self.interval
        if self.cooldown > 0:
            self.cooldown -= 1
            self._pending, self._streak = None, 0
            return []
        cand = self._candidate(g)
        if cand is None:
            self._pending, self._streak = None, 0
            return []
        if cand == "oom-backoff":
            self._pending, self._streak = None, 0
            self.cooldown = OOM_COOLDOWN_TICKS
            return self._apply(cand, g, t)
        if cand != self._pending:
            self._pending, self._streak = cand, 1
            return []
        self._streak += 1
        if self._streak < HYSTERESIS_TICKS:
            return []
        self._pending, self._streak = None, 0
        self.cooldown = COOLDOWN_TICKS
        return self._apply(cand, g, t)

    # -- gauge derivation ---------------------------------------------------

    def derive(self, raw: dict, now: float) -> dict:
        """Instantaneous decision gauges from a raw probe snapshot:
        cumulative ``*_total`` counters differentiate against the previous
        tick; everything else passes through."""
        g = {
            "queue_depth": float(raw.get("queue_depth", 0.0)),
            "arena_free": float(raw.get("arena_free", 0.0)),
            "busy_ratio": 0.0,
            "link_mbs": 0.0,
            "oom_splits": 0.0,
        }
        prev, prev_t = self._last_raw, self._last_t
        self._last_raw, self._last_t = dict(raw), now
        if prev is None:
            return g
        dt = now - prev_t
        if dt <= 0:
            return g
        g["busy_ratio"] = min(1.0, max(0.0, (
            raw.get("busy_seconds_total", 0.0)
            - prev.get("busy_seconds_total", 0.0)
        ) / dt))
        g["link_mbs"] = max(0.0, (
            raw.get("bytes_uploaded_total", 0.0)
            - prev.get("bytes_uploaded_total", 0.0)
        ) / dt / (1 << 20))
        g["oom_splits"] = max(0.0, (
            raw.get("batch_splits_total", 0.0)
            - prev.get("batch_splits_total", 0.0)
        ))
        return g

    def tick(self) -> list[dict]:
        """One live tick: read the adapter's raw gauges, derive, decide,
        and mirror knob values to the scan timeseries (counter tracks in
        --trace-out) and the process ``trivy_tpu_tuning_*`` gauges."""
        now = self.clock()
        try:
            raw = self.adapter.raw_gauges()
        except Exception as e:  # a dying pipeline must not kill the loop
            logger.debug("tuning gauge probe failed: %s", e)
            return []
        g = self.derive(raw, now)
        t = now - (self.ctx.created if self.ctx is not None else 0.0)
        fired = self.step(g, t)
        self._export_state(t)
        return fired

    def _export_state(self, t: float) -> None:
        k = self.adapter.knobs()
        ctx = self.ctx
        if ctx is not None:
            ts = getattr(ctx, "timeseries", None)
            if ts is None:
                # controller-on without a telemetry sampler: the knob
                # tracks still deserve a home in --trace-out
                from trivy_tpu.obs.timeseries import Timeseries

                ts = ctx.timeseries = Timeseries()
            for name, v in k.items():
                ts.record(f"tuning.{name}", t, float(v))
        from trivy_tpu.obs import metrics as obs_metrics

        # per-scan trace label: concurrent controller-on scans must not
        # clobber each other's knob gauges, and one scan's stop() must not
        # retire another's state — same cardinality discipline as
        # trivy_tpu_scan_progress_ratio{trace=} (label retired at stop)
        trace = self.ctx.trace_id if self.ctx is not None else "anon"
        reg = obs_metrics.REGISTRY
        for name, v in k.items():
            reg.gauge(
                f"trivy_tpu_tuning_{name}",
                f"Current value of the {name} tuning knob (online "
                f"controller attached)",
                labelnames=("trace",),
            ).set(float(v), trace=trace)
        reg.counter(
            "trivy_tpu_tuning_decisions_total",
            "Online tuning-controller decisions fired",
        )  # registered so a scrape sees 0 before the first decision
        self._gauges_set = True

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "TuningController":
        if self.interval <= 0:
            return self
        trace8 = (self.ctx.trace_id[:8] if self.ctx is not None else "anon")
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"tuning-controller-{trace8}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from trivy_tpu import obs

        ctx = self.ctx
        cm = obs.activate(ctx) if ctx is not None else None
        if cm is not None:
            cm.__enter__()
        try:
            while not self._stop.wait(self.interval):
                try:
                    fired = self.tick()
                except Exception as e:
                    logger.debug("tuning tick failed: %s", e)
                    continue
                for d in fired:
                    from trivy_tpu.obs import metrics as obs_metrics

                    obs_metrics.REGISTRY.counter(
                        "trivy_tpu_tuning_decisions_total",
                        "Online tuning-controller decisions fired",
                    ).inc()
                    logger.info(
                        "tuning: %s %s %d -> %d (busy %.2f, queue %.1f, "
                        "link %.1f MB/s)",
                        d["rule"], d["knob"], d["from"], d["to"],
                        d["gauges"]["busy_ratio"], d["gauges"]["queue_depth"],
                        d["gauges"]["link_mbs"],
                    )
        finally:
            if cm is not None:
                cm.__exit__(None, None, None)

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the loop (idempotent), freeze the final knob set into the
        document, and retire the process gauges so an idle fleet scrapes
        0-cardinality tuning state, not the last scan's knobs forever."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            self._doc["final"] = dict(self.adapter.knobs())
            self._doc["ticks"] = self.ticks
        if self._gauges_set:
            from trivy_tpu.obs import metrics as obs_metrics

            trace = self.ctx.trace_id if self.ctx is not None else "anon"
            reg = obs_metrics.REGISTRY
            for name in self._doc["final"]:
                reg.gauge(
                    f"trivy_tpu_tuning_{name}",
                    f"Current value of the {name} tuning knob (online "
                    f"controller attached)",
                    labelnames=("trace",),
                ).remove(trace=trace)
            self._gauges_set = False

    def doc(self) -> dict:
        """Snapshot of the decision log + knob state (the ``tuning``
        block's ``controller`` entry): initial/current/final knob dicts,
        tick count, and the bounded decision list — deltas sum exactly to
        ``final - initial`` per knob, the replay invariant tests assert."""
        with self._lock:
            out = dict(self._doc)
            out["current"] = dict(self._doc["current"])
            out["decision_log"] = [dict(d) for d in self.decisions]
            if "final" in out:
                out["final"] = dict(out["final"])
        return out
