"""Fleet coordinator: scatter shards to replicas, gather blobs back.

Dispatch model: every replica gets ``inflight`` dedicated worker threads
(the bounded per-replica in-flight window — each submit also rides the RPC
client's admission-aware retry ladder, so a replica shedding with
``Retry-After`` throttles its own window without stalling the others) and
an affinity queue of shards kept largest-first. The failure ladder reuses
the mesh semantics end to end:

- **work-stealing**: a worker whose own queue drained takes the largest
  shard still queued on the most-loaded peer — skewed shards re-balance
  without a central scheduler tick;
- **speculative re-dispatch**: an in-flight shard running past
  ``speculate ×`` the median completed-shard wall time (floor
  ``speculate_floor_s``) is handed to an otherwise-idle replica too; the
  first result wins, the losing attempt is cancelled (its poll abandons);
- **replica failure**: failures feed a per-replica
  :class:`~trivy_tpu.parallel.mesh.CircuitBreaker` (same
  threshold/half-open-probe/backoff ladder as device dispatch) and the
  shard re-dispatches to a survivor;
- **all replicas dead**: remaining shards degrade to a local
  :func:`~trivy_tpu.fleet.plan.execute_shard` run (the parity oracle —
  findings stay byte-identical, the report flips ``Degraded``) unless
  ``--no-host-fallback`` keeps the failure loud.

Observability folds into the coordinator's scan context: per-shard server
``Trace`` blocks join via ``ctx.ingest_remote`` (one Perfetto timeline,
replicas as distinct pids), per-shard progress polls aggregate into the
scan's :class:`~trivy_tpu.obs.timeseries.ScanProgress`, and
``fleet.dispatch`` / ``fleet.steal`` / ``fleet.result`` fault sites let
the chaos harness prove every rung.
"""

from __future__ import annotations

import statistics
import threading
import time
from dataclasses import dataclass, field

from trivy_tpu import faults, log, obs
from trivy_tpu.fleet import FleetError, parse_fleet
from trivy_tpu.obs import recorder as flight
from trivy_tpu.fleet.plan import DEFAULT_SHARDS_PER_REPLICA, split_fs_shard
from trivy_tpu.tuning import (
    DEFAULT_FLEET_SPLIT_THRESHOLD,
    DEFAULT_FLEET_TELEMETRY_INTERVAL,
)

logger = log.logger("fleet:coordinator")

DEFAULT_INFLIGHT = 2  # async shard jobs in flight per replica
DEFAULT_SPECULATE = 2.0  # straggler multiplier over the median shard time
DEFAULT_SPECULATE_FLOOR_S = 10.0  # no speculation before this wall time
DEFAULT_JOB_TIMEOUT = 600.0  # per-shard attempt wall cap
DEFAULT_RUN_TIMEOUT = 3600.0  # whole-fan-out wall cap
RESULT_POLL_S = 0.1
PROGRESS_EVERY_POLLS = 5  # fold replica progress every Nth result poll
# a straggler split must leave a replica no headroom to hide behind: only
# shards whose owning replica scores at or below this (the far side of the
# tuning dead band — busy >= SHRINK_BUSY_MIN with an empty queue) are
# split; an unknown headroom (telemetry off) counts as none
SPLIT_HEADROOM_MAX = 0.05


class ReplicaDraining(Exception):
    """A replica rejected a queued shard because it is draining (SIGTERM
    → ``"draining"`` on ``/healthz``): hand the shard back for re-dispatch
    WITHOUT a breaker penalty — the replica is shutting down cleanly, not
    failing."""


@dataclass
class FleetConfig:
    """Resolved coordinator knobs (see BASELINE.md "Distributed scanning").
    ``inflight`` resolves through :class:`~trivy_tpu.tuning.TuningConfig`
    (CLI ``--fleet-inflight`` > ``TRIVY_TPU_FLEET_INFLIGHT`` > autotune
    record > default 2) like every other perf knob."""

    hosts: list = field(default_factory=list)
    token: str = ""
    inflight: int = DEFAULT_INFLIGHT
    shards_per_replica: int = DEFAULT_SHARDS_PER_REPLICA
    speculate: float = DEFAULT_SPECULATE  # 0 disables speculation
    speculate_floor_s: float = DEFAULT_SPECULATE_FLOOR_S
    host_fallback: bool = True
    job_timeout: float = DEFAULT_JOB_TIMEOUT
    run_timeout: float = DEFAULT_RUN_TIMEOUT
    # cross-replica dedup warming: warm hit-store entries
    # ([[persist_key, doc], ...]) shipped once per replica on its first
    # shard so a fresh replica joins a re-scan warm (PR 11's named
    # headroom; entries are namespace-keyed, replicas drop mismatches)
    warm_seed: list = field(default_factory=list)
    rpc_retries: int = 1  # replica-death detection must be fast — the
    rpc_deadline: float = 10.0  # coordinator's ladder is the real retry
    poll_s: float = RESULT_POLL_S
    # replica health-poll cadence (fleet telemetry plane); 0 disables the
    # poller entirely — no thread, no telemetry import, no fleet gauges
    telemetry_interval: float = DEFAULT_FLEET_TELEMETRY_INTERVAL
    # mid-scan re-planning: an in-flight fs shard running past
    # ``split_threshold ×`` the median shard wall (floor
    # ``speculate_floor_s``) while its owner has no headroom is split at
    # a directory boundary and the remainder re-scattered; 0 disables
    split_threshold: float = DEFAULT_FLEET_SPLIT_THRESHOLD
    # bearer token a POST /fleet/register must present on the live-join
    # seam; empty falls back to the scan token (same _token_ok path)
    register_token: str = ""

    @classmethod
    def from_opts(cls, opts: dict, tuning=None) -> "FleetConfig":
        hosts = parse_fleet(opts.get("fleet"))
        if not hosts:
            raise ValueError("--fleet: at least one replica address required")
        inflight = int(
            opts.get("fleet_inflight")
            or getattr(tuning, "fleet_inflight", 0)
            or DEFAULT_INFLIGHT
        )
        speculate = opts.get("fleet_speculate")
        cfg = cls(
            hosts=hosts,
            token=opts.get("token") or "",
            inflight=max(1, inflight),
            shards_per_replica=max(
                1, int(opts.get("fleet_shards_per_replica")
                       or DEFAULT_SHARDS_PER_REPLICA)
            ),
            host_fallback=not opts.get("no_host_fallback"),
        )
        if speculate is not None:
            cfg.speculate = max(0.0, float(speculate))
        # explicit CLI 0 must win over the tuning layer (0.0 is falsy, so
        # no `or`-chain here): "telemetry off" is a decision, not absence
        tiv = opts.get("fleet_telemetry_interval")
        if tiv is None:
            tiv = getattr(
                tuning, "fleet_telemetry_interval",
                DEFAULT_FLEET_TELEMETRY_INTERVAL,
            )
        cfg.telemetry_interval = max(0.0, float(tiv))
        # same explicit-0-wins shape for the split threshold ("elastic
        # re-planning off" is a decision, not absence)
        fst = opts.get("fleet_split_threshold")
        if fst is None:
            fst = getattr(
                tuning, "fleet_split_threshold",
                DEFAULT_FLEET_SPLIT_THRESHOLD,
            )
        cfg.split_threshold = max(0.0, float(fst))
        cfg.register_token = opts.get("fleet_register_token") or ""
        return cfg

    def target_shards(self) -> int:
        return max(1, len(self.hosts) * self.shards_per_replica)


def _normalize_100(buckets: dict[str, float]) -> dict[str, float]:
    """Round efficiency buckets to one decimal so they sum to exactly
    100.0 — rounding drift lands on the largest bucket, where a ±0.1
    cannot mislead anyone."""
    rounded = {k: round(max(0.0, v), 1) for k, v in buckets.items()}
    drift = round(100.0 - sum(rounded.values()), 1)
    if drift:
        largest = max(rounded, key=lambda k: rounded[k])
        rounded[largest] = round(rounded[largest] + drift, 1)
    return rounded


class _ShardState:
    """Coordinator-side bookkeeping for one shard across its attempts."""

    __slots__ = (
        "spec", "state", "running", "failed_on", "attempts", "started",
        "speculated", "stolen", "done", "blobs", "counted",
        "split", "parent", "children", "resolved_by",
    )

    def __init__(self, spec):
        self.spec = spec
        self.state = "queued"  # queued | inflight | done | dead
        self.running: set[int] = set()  # replica indexes mid-attempt
        self.failed_on: set[int] = set()
        self.attempts = 0
        self.started = 0.0  # first-attempt start (speculation clock)
        self.speculated = False
        self.stolen = False
        self.done = False
        self.blobs: list | None = None
        self.counted = 0  # replica-reported bytes already folded into progress
        # mid-scan re-planning: a straggler split spawns fragment states
        # whose union of paths is exactly the parent's — the parent's
        # whole-shard attempt keeps racing the fragment group, and the
        # first side to complete wins ("self" via its own attempt,
        # "children" when every fragment lands first, "parent" stamped on
        # fragments a parent win superseded)
        self.split = False  # a split was attempted (never re-split)
        self.parent: "_ShardState | None" = None
        self.children: "list[_ShardState] | None" = None
        self.resolved_by = "self"


class FleetCoordinator:
    """One fan-out: ``run(shards)`` scatters, gathers, and returns
    ``{shard index: [{"BlobID", "BlobInfo"}, ...]}``."""

    def __init__(self, cfg: FleetConfig, scan_options, local_cache=None):
        from trivy_tpu.parallel.mesh import CircuitBreaker
        from trivy_tpu.rpc.client import RemoteDriver

        self.cfg = cfg
        self.scan_options = scan_options
        self.local_cache = local_cache
        self.drivers = [
            RemoteDriver(
                h, token=cfg.token, retries=cfg.rpc_retries,
                deadline=cfg.rpc_deadline,
            )
            for h in cfg.hosts
        ]
        self.breaker = CircuitBreaker(
            len(cfg.hosts), labels=[f"fleet:{h}" for h in cfg.hosts]
        )
        self._sync_only = [False] * len(cfg.hosts)  # 404 on submit → sync scan
        self.stats = {
            "replicas": len(cfg.hosts),
            "shards": 0,
            "dispatches": 0,
            "steals": 0,
            "speculative": 0,
            "redispatches": 0,
            "cancelled": 0,
            "local_fallback": 0,
            "warm_seeded": 0,  # replicas sent a warm dedup payload
            "splits": 0,  # stragglers split at a directory boundary
            "joins": 0,  # replicas that registered mid-sweep
            "drains": 0,  # replicas that handed queued work back
            "placement_decisions": 0,  # controller re-weights applied
            "replica_shards": {h: 0 for h in cfg.hosts},
        }
        self._warm_sent: set[int] = set()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: list[list[_ShardState]] = []
        self._shards: list[_ShardState] = []
        self._durations: list[float] = []
        self._stop = False
        # fleet telemetry inputs: jobs currently polling per replica (the
        # poller scrapes their live progress) and per-replica attempt wall
        # accounting for the efficiency verdict
        self._active_jobs: dict[str, set[str]] = {h: set() for h in cfg.hosts}
        self._host_busy: dict[str, float] = {h: 0.0 for h in cfg.hosts}
        self._host_last_done: dict[str, float] = {}
        self._run_started = 0.0
        self.verdict: dict[str, dict] = {}  # set at fan-out end
        # elastic control plane (all grown in lockstep by register_replica):
        # draining replicas take no new work, dead-marked replicas abandon
        # their in-flight polls NOW, weights bias requeue/steal placement
        self._draining = [False] * len(cfg.hosts)
        self._dead_marks = [False] * len(cfg.hosts)
        self._weights: dict[str, float] = {h: 1.0 for h in cfg.hosts}
        self._workers: list[threading.Thread] = []
        self._running = False
        self._ctx = None
        self.controller = None  # FleetController when telemetry is on
        self._poller = None  # ReplicaPoller when telemetry is on

    def active_jobs(self, host: str) -> list[str]:
        """Snapshot of the job ids currently polling on ``host`` — the
        telemetry poller's progress-scrape targets."""
        with self._lock:
            return list(self._active_jobs.get(host, ()))

    # -- elastic control plane ----------------------------------------------

    def register_replica(self, host: str) -> dict:
        """Live join: a replica appearing mid-sweep is validated, probed,
        and then every per-replica structure grows in lockstep under the
        lock — breaker slot, affinity queue, workers — so it starts
        stealing work immediately. Idempotent on duplicates (the joiner's
        retry ladder may re-POST); a joiner that fails its health probe
        (or arrives already draining) is refused loudly and the running
        fan-out is untouched."""
        hosts = parse_fleet(host)
        if len(hosts) != 1:
            raise FleetError(
                f"register: exactly one replica address required, "
                f"got {host!r}"
            )
        host = hosts[0]
        faults.check("fleet.register", key=host)
        with self._lock:
            if host in self.cfg.hosts:
                return {"Host": host, "Known": True,
                        "Replicas": len(self.cfg.hosts)}
        from trivy_tpu.rpc.client import RemoteDriver, get_healthz

        # probe OUTSIDE the lock — a dead joiner must not stall dispatch
        try:
            hz = get_healthz(host, deadline=self.cfg.rpc_deadline)
        except Exception as e:
            raise FleetError(
                f"register: health probe of {host} failed: {e}"
            ) from e
        if (hz or {}).get("Status") == "draining":
            raise FleetError(
                f"register: {host} is draining; refusing the join"
            )
        driver = RemoteDriver(
            host, token=self.cfg.token, retries=self.cfg.rpc_retries,
            deadline=self.cfg.rpc_deadline,
        )
        with self._cond:
            if host in self.cfg.hosts:  # lost a duplicate-register race
                return {"Host": host, "Known": True,
                        "Replicas": len(self.cfg.hosts)}
            i = len(self.cfg.hosts)
            self.cfg.hosts.append(host)
            self.drivers.append(driver)
            self.breaker.grow(f"fleet:{host}")
            self._sync_only.append(False)
            self._draining.append(False)
            self._dead_marks.append(False)
            self._queues.append([])
            self._active_jobs[host] = set()
            self._host_busy[host] = 0.0
            self._weights[host] = 1.0
            self.stats["replicas"] = len(self.cfg.hosts)
            self.stats["joins"] += 1
            self.stats["replica_shards"].setdefault(host, 0)
            if self._running and not self._stop:
                ws = [
                    threading.Thread(
                        target=self._worker, args=(i, self._ctx),
                        daemon=True, name=f"fleet-worker-r{i}-{j}",
                    )
                    for j in range(self.cfg.inflight)
                ]
                self._workers.extend(ws)
                for w in ws:
                    w.start()
            self._cond.notify_all()
        if self.controller is not None:
            self.controller.add_host(host)
        if self._ctx is not None:
            self._ctx.count("fleet.joins")
        logger.info(
            "replica %s joined the fleet mid-sweep (now %d replica(s))",
            host, len(self.cfg.hosts),
        )
        flight.record("fleet", f"replica join {host}",
                      {"replicas": len(self.cfg.hosts)})
        return {"Host": host, "Known": False,
                "Replicas": len(self.cfg.hosts)}

    def deregister_replica(self, host: str) -> dict:
        """Explicit live leave: the inverse of :meth:`register_replica`.
        Reuses the drain hand-back path — the replica takes no new work,
        its queued shards re-scatter to survivors, and in-flight attempts
        finish (or come back via the rejected hand-back). Idempotent: an
        unknown or already-draining host is a no-op answer, never an
        error (the leaver's retry ladder may re-POST)."""
        hosts = parse_fleet(host)
        if len(hosts) != 1:
            raise FleetError(
                f"deregister: exactly one replica address required, "
                f"got {host!r}"
            )
        host = hosts[0]
        with self._cond:
            try:
                i = self.cfg.hosts.index(host)
            except ValueError:
                return {"Host": host, "Known": False,
                        "Replicas": len(self.cfg.hosts)}
            already = self._draining[i]
            if not already:
                self._note_draining_locked(i)
                self._cond.notify_all()
        if not already:
            logger.info("replica %s deregistered from the fleet", host)
        return {"Host": host, "Known": True, "Draining": True,
                "Replicas": len(self.cfg.hosts)}

    def note_replica_draining(self, i: int) -> None:
        """Telemetry verdict: replica ``i`` scraped as draining — hand its
        queued shards back and stop assigning it work."""
        with self._cond:
            self._note_draining_locked(i)
            self._cond.notify_all()

    def note_replica_dead(self, i: int, reason: str = "") -> None:
        """Telemetry verdict (2 consecutive failed scrapes): trip the
        breaker NOW and mark the replica so in-flight result polls on it
        abandon immediately instead of waiting out the job timeout — the
        fix for a replica that takes work and dies leaving its shard
        parked in ``dispatched``."""
        with self._cond:
            if i >= len(self._dead_marks) or self._dead_marks[i]:
                return
            self._dead_marks[i] = True
            self._cond.notify_all()
        self.breaker.trip(i, reason or "2 consecutive dead telemetry scrapes")
        host = self.cfg.hosts[i] if i < len(self.cfg.hosts) else f"r{i}"
        flight.record(
            "dead", f"fleet replica {host}",
            {"reason": reason or "2 consecutive dead telemetry scrapes"},
        )
        # the forensics bundle for a dead replica merges that replica's
        # own flight-recorder ring (best-effort — it may be truly dead,
        # in which case the pull error itself is part of the story)
        flight.auto_emit(
            "dead-replica", ctx=self._ctx,
            extra={"replica_bundles": self._pull_replica_bundles([host])},
        )

    def note_replica_alive(self, i: int) -> None:
        """A successful scrape (or attempt) on a dead-marked replica: the
        mark clears; the breaker's own half-open ladder decides re-entry."""
        with self._lock:
            if i < len(self._dead_marks):
                self._dead_marks[i] = False

    def _pull_replica_bundles(self, hosts: list[str]) -> dict[str, dict]:
        """Best-effort ``GET /debug/bundle`` against each named replica so
        the coordinator's merged bundle carries the replica-side rings
        too. A pull failure is recorded in place of the bundle — for a
        dead replica the error IS the evidence."""
        from trivy_tpu.rpc.client import fetch_debug_bundle

        out: dict[str, dict] = {}
        for h in hosts:
            try:
                out[h] = fetch_debug_bundle(
                    h, token=self.cfg.token, deadline=self.cfg.rpc_deadline
                )
            except Exception as e:
                out[h] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def apply_placement(self, weights: dict, fired: int = 0) -> None:
        """Controller output: swap in the placement weights consulted by
        requeue targeting and steal ordering, and account fired
        decisions."""
        with self._lock:
            self._weights = dict(weights)
            if fired:
                self.stats["placement_decisions"] += fired

    # -- queue mechanics (all under self._lock) ------------------------------

    def _insert_sorted(self, q: list[_ShardState], shard: _ShardState) -> None:
        for pos, s in enumerate(q):
            if shard.spec.nbytes > s.spec.nbytes:
                q.insert(pos, shard)
                return
        q.append(shard)

    def _pending_locked(self) -> int:
        n = 0
        for s in self._shards:
            if s.state in ("done", "dead"):
                continue
            if s.children is not None and not s.running and all(
                c.state in ("done", "dead") for c in s.children
            ):
                # a split parent with no racing attempt of its own is
                # settled by its fragments (a dead fragment completes in
                # the post-loop fallback, which resolves the parent)
                continue
            n += 1
        return n

    def _median_wall_locked(self) -> float | None:
        """Median shard wall for straggler deadlines. Before ANY shard has
        completed, seed the estimate from planner byte sizes over the
        observed progress throughput — a 2-shard plan with one stalled
        shard must still speculate/split the straggler (the completed-only
        median left it unactionable forever)."""
        if self._durations:
            return statistics.median(self._durations)
        counted = sum(s.counted for s in self._shards if s.parent is None)
        elapsed = time.monotonic() - self._run_started
        if counted <= 0 or elapsed <= 0:
            return None
        sizes = [
            s.spec.nbytes for s in self._shards
            if s.parent is None and s.spec.nbytes > 0
        ]
        if not sizes:
            return None
        return statistics.median(sizes) * elapsed / counted

    def _speculate_deadline_locked(self) -> float:
        med = self._median_wall_locked()
        if med is not None:
            return max(self.cfg.speculate_floor_s, self.cfg.speculate * med)
        return self.cfg.speculate_floor_s

    def _take_locked(self, i: int) -> tuple[_ShardState | None, str]:
        """Next shard for replica ``i``: own largest → stolen largest from
        the most-loaded peer → largest fragment of a freshly split
        straggler → speculative twin of the worst straggler."""
        q = self._queues[i]
        if q:
            return q.pop(0), "own"
        donors = [
            j for j in range(len(self._queues)) if j != i and self._queues[j]
        ]
        if donors:
            # largest stealable shard across peers (queues are sorted
            # desc, so each queue's first eligible entry is its largest);
            # shards this replica already failed on are not stealable —
            # stealing back a shard that was deliberately requeued AWAY
            # from us would burn attempts on a known-bad pairing; donors
            # rank by weighted queued bytes so a drowning replica sheds
            # first
            best = None
            best_j = -1
            for j in sorted(
                donors,
                key=lambda j: -sum(s.spec.nbytes for s in self._queues[j])
                / max(0.05, self._weights.get(self.cfg.hosts[j], 1.0)),
            ):
                for s in self._queues[j]:
                    if i in s.failed_on:
                        continue
                    if best is None or s.spec.nbytes > best.spec.nbytes:
                        best, best_j = s, j
                    break
            if best is not None:
                self._queues[best_j].remove(best)
                best.stolen = True
                self.stats["steals"] += 1
                return best, "steal"
        split = self._try_split_locked(i)
        if split is not None:
            return split, "split"
        if self.cfg.speculate > 0:
            now = time.monotonic()
            deadline = self._speculate_deadline_locked()
            cands = [
                s for s in self._shards
                if s.state == "inflight" and not s.done and not s.speculated
                and s.children is None
                and i not in s.running and i not in s.failed_on
                and now - s.started > deadline
            ]
            if cands:
                shard = min(cands, key=lambda s: s.started)  # worst straggler
                shard.speculated = True
                self.stats["speculative"] += 1
                return shard, "speculate"
        return None, ""

    def _owner_headroom_locked(self, s: _ShardState) -> float:
        """Best headroom among the replicas currently running ``s`` — a
        split only fires when even the most-relieved owner is out of
        headroom. No telemetry (poller off, host never scraped) reads as
        0.0: with no gauge arguing the owner can catch up, the straggler
        deadline alone decides."""
        p = self._poller
        if p is None or not s.running:
            return 0.0
        hs = []
        for j in s.running:
            if j < len(self.cfg.hosts):
                rh = p.health.get(self.cfg.hosts[j])
                hs.append(rh.headroom() if rh is not None else 0.0)
        return max(hs) if hs else 0.0

    def _try_split_locked(self, i: int) -> _ShardState | None:
        """Mid-scan re-planning: when an in-flight fs shard's wall exceeds
        ``split_threshold ×`` the median and its owner has no headroom,
        split it at a directory boundary (Helm subtrees stay whole),
        scatter the fragments to survivors, and hand the largest to this
        worker. The parent's attempt keeps racing the fragment group —
        first side to finish wins, so a split can never lose work."""
        if self.cfg.split_threshold <= 0:
            return None
        med = self._median_wall_locked()
        if med is None:
            return None
        now = time.monotonic()
        deadline = max(
            self.cfg.speculate_floor_s, self.cfg.split_threshold * med
        )
        cands = [
            s for s in self._shards
            if s.state == "inflight" and not s.done and not s.split
            and s.children is None and s.parent is None
            and s.spec.wire.get("Kind") == "fs"
            and i not in s.running and i not in s.failed_on
            and now - s.started > deadline
            and self._owner_headroom_locked(s) <= SPLIT_HEADROOM_MAX
        ]
        if not cands:
            return None
        shard = min(cands, key=lambda s: s.started)  # worst straggler
        shard.split = True  # one split per shard, even if it fails below
        try:
            faults.check("fleet.split", key=str(shard.spec.index))
            frags = split_fs_shard(shard.spec, n=2)
        except Exception as e:
            logger.warning(
                "split of %s abandoned: %s (original attempt keeps racing)",
                shard.spec.label(), e,
            )
            return None
        if not frags:
            return None  # indivisible (single planning unit)
        children = []
        for spec in frags:
            c = _ShardState(spec)
            c.parent = shard
            children.append(c)
        shard.children = children
        self._shards.extend(children)
        self.stats["splits"] += 1
        logger.info(
            "straggler %s split into %d fragment(s) after %.1fs "
            "(median %.1fs)", shard.spec.label(), len(children),
            now - shard.started, med,
        )
        flight.record("fleet", f"shard split {shard.spec.label()}",
                      {"fragments": len(children)})
        # largest fragment goes to this (idle) worker; the rest scatter
        # to survivors, weighted, avoiding the straggler's own owners
        for c in children[1:]:
            self._place_fragment_locked(c, avoid=shard.running | {i})
        self._cond.notify_all()
        return children[0]

    def _eligible_work_locked(self, i: int) -> bool:
        """Would :meth:`_take_locked` yield anything for replica ``i``?
        Mirrors its filters without popping — the breaker's half-open
        probe slot must only be claimed when there is an attempt to spend
        it on (an empty-handed claim locks recovery out for the whole
        probe timeout). Splits are deliberately NOT mirrored: a probe
        slot is too scarce to spend on re-planning someone else's shard."""
        if self._queues[i]:
            return True
        for j, q in enumerate(self._queues):
            if j != i and any(i not in s.failed_on for s in q):
                return True
        if self.cfg.speculate > 0:
            now = time.monotonic()
            deadline = self._speculate_deadline_locked()
            return any(
                s.state == "inflight" and not s.done and not s.speculated
                and s.children is None
                and i not in s.running and i not in s.failed_on
                and now - s.started > deadline
                for s in self._shards
            )
        return False

    def _weighted_target_locked(self, cands: list[int]) -> int:
        """Least *weighted* queued bytes wins: the controller's placement
        weight divides a replica's apparent load, so a down-weighted
        (drowning) replica looks fuller than its raw bytes say."""
        return min(
            cands,
            key=lambda j: (
                sum(s.spec.nbytes for s in self._queues[j])
                / max(0.05, self._weights.get(self.cfg.hosts[j], 1.0)),
                j,
            ),
        )

    def _place_fragment_locked(self, child: _ShardState, avoid) -> None:
        n = len(self._queues)
        cands = [
            j for j in range(n)
            if j not in avoid and not self._draining[j]
        ]
        if not cands:
            cands = [j for j in range(n) if not self._draining[j]] \
                or list(range(n))
        child.state = "queued"
        self._insert_sorted(self._queues[self._weighted_target_locked(cands)],
                            child)

    def _requeue_locked(self, shard: _ShardState, avoid: int,
                        redispatch: bool = True) -> None:
        """Re-dispatch a failed shard to a survivor's queue (the replica
        with the least weighted queued bytes that hasn't already failed
        it and isn't draining; everyone-failed resets the slate so
        breaker probes can retry it until the attempt cap declares it
        dead). ``redispatch=False`` is the drain hand-back: same routing,
        but the move is clean bookkeeping, not a failure retry."""
        n = len(self._queues)
        cands = [
            j for j in range(n)
            if j != avoid and j not in shard.failed_on
            and not self._draining[j]
        ]
        if not cands:
            shard.failed_on.clear()
            cands = [
                j for j in range(n) if j != avoid and not self._draining[j]
            ] or [j for j in range(n) if j != avoid] or list(range(n))
        target = self._weighted_target_locked(cands)
        shard.state = "queued"
        shard.speculated = False
        if redispatch:
            self.stats["redispatches"] += 1
        self._insert_sorted(self._queues[target], shard)

    def _note_draining_locked(self, i: int) -> None:
        """Replica ``i`` reported draining: stop assigning it work and
        hand its queued shards back to survivors. Shards it already
        accepted either finish (drain waits for running jobs) or come
        back via the rejected→hand-back path; a replica that dies instead
        of draining cleanly is the breaker ladder's half."""
        if i >= len(self._draining) or self._draining[i]:
            return
        self._draining[i] = True
        self.stats["drains"] += 1
        # a deregister can land before any scan scattered work (no
        # per-replica queues yet): the drain mark alone is the whole story
        handed = list(self._queues[i]) if i < len(self._queues) else []
        if handed:
            self._queues[i].clear()
        for s in handed:
            self._place_fragment_locked(s, avoid={i})
        logger.info(
            "replica %s draining: %d queued shard(s) handed back",
            self.cfg.hosts[i], len(handed),
        )
        flight.record("fleet", f"replica drain {self.cfg.hosts[i]}",
                      {"handed_back": len(handed)})

    def _resolve_split_locked(self, shard: _ShardState) -> None:
        """Settle the parent/fragments race after ``shard`` completed.
        Parent finished first → the whole-shard result wins outright:
        every fragment is marked superseded and its blobs (even completed
        ones) are dropped, so no path can fold twice. Last fragment
        finished first → the parent is resolved by its children and its
        still-racing attempt cancels on the next poll."""
        if shard.children is not None:
            for c in shard.children:
                if not c.done:
                    c.done = True
                    c.state = "done"
                c.resolved_by = "parent"
                c.blobs = None
                for q in self._queues:
                    if c in q:
                        q.remove(c)
            return
        p = shard.parent
        if p is not None and not p.done and all(
            c.done and c.resolved_by == "self" for c in p.children
        ):
            p.done = True
            p.state = "done"
            p.resolved_by = "children"

    def _declare_fleet_dead_locked(self) -> None:
        """All breakers open at once: every queued shard (and every
        in-flight shard with no attempt still running) goes to the local
        fallback; attempts still racing resolve themselves (their own
        failure paths land here again). Split parents are skipped — their
        fragments cover the same paths exactly once."""
        for q in self._queues:
            q.clear()
        for s in self._shards:
            if s.state in ("queued", "inflight") and not s.done \
                    and not s.running and s.children is None:
                s.state = "dead"

    # -- the fan-out ---------------------------------------------------------

    def run(self, specs) -> dict[int, list[dict]]:
        ctx = obs.current()
        n = len(self.cfg.hosts)
        self._run_started = time.monotonic()
        self._shards = [_ShardState(s) for s in specs]
        self.stats["shards"] = len(self._shards)
        ctx.count("fleet.shards", len(self._shards))
        self._queues = [[] for _ in range(n)]
        # round-robin the largest-first plan across affinity queues: each
        # queue stays sorted desc, and loads start near-balanced
        for k, shard in enumerate(self._shards):
            self._queues[k % n].append(shard)
        # the per-shard attempt cap bounds the all-dead detection time:
        # a shard that failed this many times (across redispatches and
        # breaker probes) is declared dead and handed to the fallback
        self._attempt_cap = max(4, 2 * n)
        self._ctx = ctx
        self._workers = [
            threading.Thread(
                target=self._worker, args=(i, ctx), daemon=True,
                name=f"fleet-worker-r{i}-{j}",
            )
            for i in range(n)
            for j in range(self.cfg.inflight)
        ]
        deadline = time.monotonic() + self.cfg.run_timeout
        # the telemetry plane is strictly optional: interval 0 means the
        # module is never imported, no thread starts, no gauges exist
        # (bench --smoke asserts exactly this), and the heartbeat's fleet
        # fragment falls back to coordinator-local breaker state; the
        # placement controller rides the same gate — it is tickless and
        # only the poller's scrape loop drives it
        poller = None
        if self.cfg.telemetry_interval > 0:
            from trivy_tpu.fleet.controller import FleetController
            from trivy_tpu.fleet.telemetry import start_poller

            self.controller = FleetController(
                list(self.cfg.hosts), ctx=ctx,
                interval=self.cfg.telemetry_interval,
            )
            poller = start_poller(
                self, ctx, interval=self.cfg.telemetry_interval
            )
            if poller is not None:
                poller.controller = self.controller
        self._poller = poller
        ctx.fleet_status = lambda: self._fleet_status(poller)
        if poller is not None:
            ctx.fleet_live = poller.live_fragment
        with self._cond:
            self._running = True
            for w in self._workers:
                w.start()
        try:
            with self._cond:
                while self._pending_locked() > 0:
                    if time.monotonic() > deadline:
                        raise FleetError(
                            f"fleet scan exceeded {self.cfg.run_timeout:.0f}s"
                            f" ({self._pending_locked()} shard(s) unfinished)"
                        )
                    self._cond.wait(0.1)
        finally:
            with self._cond:
                self._stop = True
                self._running = False
                ws = list(self._workers)
                self._cond.notify_all()
            for w in ws:
                w.join(timeout=30.0)
            if poller is not None:
                poller.stop()
        dead = [s for s in self._shards if s.state == "dead"]
        if dead:
            self._fallback(dead, ctx)
        # fold the fan-out's shape into the trace counters so --trace /
        # --metrics-out carry the steal/speculation/redispatch story
        # (joins and placement decisions were counted live as they fired)
        for key in ("steals", "speculative", "redispatches", "splits",
                    "drains"):
            if self.stats[key]:
                ctx.count(f"fleet.{key}", self.stats[key])
        # the verdict is computed whether or not tracing is on (bench
        # reads it for fleet_idle_share); the profile copy feeds report()
        self.verdict = self._efficiency_verdict()
        if ctx.enabled:
            ctx.profile().note_fleet(self.verdict)
        out = {}
        for s in self._shards:
            if s.resolved_by == "parent":
                continue  # fragment superseded by its parent's win
            if s.children is not None and s.resolved_by == "children":
                continue  # split parent represented by its fragments
            if s.blobs is None:
                raise FleetError(f"{s.spec.label()} completed without blobs")
            out[s.spec.index] = s.blobs
        logger.info(
            "fleet fan-out complete: %d shard(s) over %d replica(s) "
            "(%d steal(s), %d speculative, %d redispatch(es), %d split(s), "
            "%d join(s), %d drain(s), %d local)",
            self.stats["shards"], len(self.cfg.hosts), self.stats["steals"],
            self.stats["speculative"], self.stats["redispatches"],
            self.stats["splits"], self.stats["joins"], self.stats["drains"],
            self.stats["local_fallback"],
        )
        return out

    def _fleet_status(self, poller) -> dict:
        """Heartbeat-sized fleet snapshot (shards done/total + replica
        health). With the telemetry poller off, replica health degrades
        to the coordinator's own breaker view and fleet MB/s is unknown."""
        with self._lock:
            done = sum(1 for s in self._shards if s.done)
            total = len(self._shards)
        if poller is not None:
            st = poller.status()
        else:
            n = len(self.cfg.hosts)
            open_ = sum(
                1 for j in range(n) if self.breaker.is_open(j)
            )
            st = {
                "replicas": n,
                "healthy": n - open_,
                "breaker_open": open_,
                "fleet_mbs": None,
            }
        st["shards_done"] = done
        st["shards_total"] = total
        return st

    def _efficiency_verdict(self) -> dict[str, dict]:
        """Per-replica efficiency buckets summing to exactly 100%:

        - ``busy``: attempt wall time (wins, losses, cancelled twins — the
          replica burned it either way) over worker capacity
          (run wall x inflight);
        - ``stalled_on_coordinator``: the tail between a replica's last
          completion and fan-out end — it sat drained while the
          coordinator had no work left to give it;
        - ``dead``: 100 for a replica that completed nothing and ended
          behind an open breaker;
        - ``idle``: the remainder (queue gaps, poll latency).
        """
        run_wall = max(1e-9, time.monotonic() - self._run_started)
        capacity = run_wall * max(1, self.cfg.inflight)
        with self._lock:
            busy = dict(self._host_busy)
            last_done = dict(self._host_last_done)
            shard_counts = dict(self.stats["replica_shards"])
        out = {}
        for j, host in enumerate(self.cfg.hosts):
            row = {"shards": int(shard_counts.get(host, 0)),
                   "busy_s": round(busy.get(host, 0.0), 3)}
            if not shard_counts.get(host) and self.breaker.is_open(j):
                row.update(busy=0.0, idle=0.0,
                           stalled_on_coordinator=0.0, dead=100.0)
                out[host] = row
                continue
            busy_pct = 100.0 * min(1.0, busy.get(host, 0.0) / capacity)
            ld = last_done.get(host)
            tail_s = max(0.0, run_wall - (ld - self._run_started)) \
                if ld is not None else 0.0
            stalled_pct = 100.0 * min(1.0, tail_s / run_wall)
            busy_pct = min(busy_pct, 100.0 - stalled_pct)
            buckets = {
                "busy": busy_pct,
                "idle": max(0.0, 100.0 - busy_pct - stalled_pct),
                "stalled_on_coordinator": stalled_pct,
                "dead": 0.0,
            }
            row.update(_normalize_100(buckets))
            out[host] = row
        return out

    def _worker(self, i: int, ctx) -> None:
        with obs.activate(ctx):
            while True:
                with self._cond:
                    if self._stop or self._pending_locked() == 0:
                        return
                    shard, how = (None, "")
                    if self._draining[i]:
                        # a draining replica takes no new work — its
                        # in-flight jobs finish (drain waits for running
                        # work) and its queue was already handed back
                        pass
                    elif not self.breaker.is_open(i):
                        shard, how = self._take_locked(i)
                    elif self._eligible_work_locked(i) \
                            and self.breaker.try_probe(i):
                        # an open breaker blocks dispatch until its
                        # half-open probe window arrives — the probe slot
                        # is claimed only when a take would actually yield
                        # work, and try_probe touches ONLY replica i's
                        # slot (next_device would claim a peer's as a
                        # round-robin side effect)
                        shard, how = self._take_locked(i)
                    if shard is None:
                        self._cond.wait(0.05)
                        continue
                    shard.running.add(i)
                    shard.attempts += 1
                    if shard.state == "queued":
                        shard.state = "inflight"
                        shard.started = time.monotonic()
                if how == "steal":
                    try:
                        faults.check("fleet.steal", key=self.cfg.hosts[i])
                    except Exception as e:
                        # a faulted steal must put the shard back, never
                        # lose it (the chaos harness drives this rung)
                        logger.warning("steal on %s faulted: %s",
                                       self.cfg.hosts[i], e)
                        with self._cond:
                            shard.running.discard(i)
                            if not shard.done and not shard.running:
                                shard.state = "queued"
                                self._insert_sorted(self._queues[i], shard)
                            self._cond.notify_all()
                        continue
                self._attempt(i, shard, ctx)

    # -- one attempt ---------------------------------------------------------

    def _attempt(self, i: int, shard: _ShardState, ctx) -> None:
        host = self.cfg.hosts[i]
        t0 = time.monotonic()
        try:
            faults.check("fleet.dispatch", key=host)
            with self._lock:  # stats writes stay lock-consistent
                self.stats["dispatches"] += 1
            ctx.count("fleet.dispatches")
            with ctx.span("fleet.dispatch"):
                resp = self._dispatch(i, shard)
            if resp is None:  # lost the speculation race mid-poll
                with self._cond:
                    self._host_busy[host] += time.monotonic() - t0
                    shard.running.discard(i)
                    self.stats["cancelled"] += 1
                    ctx.count("fleet.cancelled")
                    self._cond.notify_all()
                return
            faults.check("fleet.result", key=str(shard.spec.index))
            blobs = resp.get("Blobs")
            if blobs is None:
                raise FleetError(
                    f"replica {host} returned no Blobs for "
                    f"{shard.spec.label()}"
                )
        except Exception as e:
            drain = isinstance(e, ReplicaDraining)
            if drain:
                try:
                    faults.check("fleet.drain", key=host)
                except Exception as fe:
                    # a faulted hand-back falls back to the breaker
                    # ladder: the shard re-dispatches as a plain failure
                    # — never lost, never double-completed
                    logger.warning(
                        "drain hand-back on %s faulted: %s", host, fe
                    )
                    drain = False
            if drain:
                logger.info(
                    "%s handed back by draining replica %s",
                    shard.spec.label(), host,
                )
            else:
                self.breaker.record_failure(i)
                logger.warning(
                    "%s failed on replica %s (attempt %d): %s",
                    shard.spec.label(), host, shard.attempts, e,
                )
            fleet_dead = not drain and all(
                self.breaker.is_open(j) for j in range(len(self.cfg.hosts))
            )
            with self._cond:
                # a failed attempt still burned this replica's time — it
                # counts toward the verdict's busy bucket
                self._host_busy[host] += time.monotonic() - t0
                shard.running.discard(i)
                if drain:
                    # a clean drain is not a failure: no breaker penalty,
                    # no failed_on mark — the worker gate keeps replica i
                    # out of rotation and the shard re-routes
                    self._note_draining_locked(i)
                else:
                    shard.failed_on.add(i)
                if not shard.done and not shard.running:
                    if shard.children is not None:
                        # a split parent's failed attempt defers to its
                        # fragments — they cover the same paths, and
                        # re-running the whole shard would race its own
                        # children
                        pass
                    elif drain:
                        self._requeue_locked(shard, avoid=i,
                                             redispatch=False)
                    elif fleet_dead or shard.attempts >= self._attempt_cap:
                        # exhausted everywhere: hand it to the fallback
                        shard.state = "dead"
                        logger.error(
                            "%s failed %d attempt(s); no dispatchable "
                            "replica left — falling back to a local scan",
                            shard.spec.label(), shard.attempts,
                        )
                    else:
                        self._requeue_locked(shard, avoid=i)
                if fleet_dead:
                    # every replica's breaker is open at once: the fleet is
                    # down — drain the queues NOW instead of burning one
                    # backoff-throttled probe per shard per attempt-cap
                    # round (the half-open ladder would take minutes)
                    self._declare_fleet_dead_locked()
                self._cond.notify_all()
            return
        self.breaker.record_success(i)
        wall = time.monotonic() - t0
        with self._cond:
            self._host_busy[host] += wall
            self._dead_marks[i] = False  # it answered; the verdict lapses
            shard.running.discard(i)
            if shard.done:
                # a twin attempt (or the other side of a split) already
                # won; this result is the loser
                self.stats["cancelled"] += 1
                ctx.count("fleet.cancelled")
                self._cond.notify_all()
                return
            shard.done = True
            shard.state = "done"
            shard.blobs = list(blobs)
            self._resolve_split_locked(shard)
            self._durations.append(wall)
            self.stats["replica_shards"][host] += 1
            self._host_last_done[host] = time.monotonic()
            self._cond.notify_all()
        if ctx.enabled:
            ctx.profile().note_shard(
                host, shard.spec.nbytes, wall, stolen=shard.stolen,
                speculated=shard.speculated, attempts=shard.attempts,
            )
        self._fold_result(shard, resp, ctx)

    def _fold_result(self, shard: _ShardState, resp: dict, ctx) -> None:
        """Merge one shard response's observability into the coordinator
        scan: the replica's Trace block joins the timeline (a distinct pid
        in the export), its health events (skipped files, degradations)
        sum into the report metadata, and progress tops up to the shard's
        planned bytes."""
        if ctx.enabled and resp.get("Trace"):
            ctx.ingest_remote(resp["Trace"])
        for name, v in (resp.get("Health") or {}).items():
            if v:
                ctx.health_count(name, int(v))
        with self._lock:
            delta = shard.spec.nbytes - shard.counted
            shard.counted = shard.spec.nbytes
            if shard.parent is not None and delta > 0:
                # a fragment's bytes also count against its parent so a
                # later parent win folds only the remaining delta (the
                # progress bar never double-counts a split)
                p = shard.parent
                p.counted = min(p.spec.nbytes, p.counted + delta)
        if delta > 0:
            ctx.progress().note_scanned(delta, files=0)

    def _note_progress(self, shard: _ShardState, snap: dict, ctx) -> None:
        scanned = int(snap.get("BytesScanned") or 0)
        scanned = min(scanned, shard.spec.nbytes)
        with self._lock:
            delta = scanned - shard.counted
            if delta <= 0 or shard.done:
                return
            shard.counted = scanned
        ctx.progress().note_scanned(delta, files=0)

    # -- replica RPC ---------------------------------------------------------

    def _dispatch(self, i: int, shard: _ShardState):
        """One attempt on replica ``i``: async submit + cancellable result
        poll, falling back to a synchronous Scanner.Scan on replicas
        without the job API. Returns the raw shard response, or None when
        a speculation twin won while this attempt was in flight."""
        from trivy_tpu.rpc.client import RPCError

        driver = self.drivers[i]
        ctx = obs.current()
        label = shard.spec.label()
        wire = shard.spec.wire
        if self.cfg.warm_seed:
            with self._lock:
                first = i not in self._warm_sent
                self._warm_sent.add(i)
            if first:
                # first shard to each replica carries the warm dedup
                # entries; retries/steals re-send only if this attempt
                # never reached the replica (sent-set stays conservative)
                wire = dict(wire)
                wire["WarmHits"] = self.cfg.warm_seed
                self.stats["warm_seeded"] += 1
        if not self._sync_only[i]:
            try:
                sub = driver.submit(
                    label, "", [], self.scan_options, shard=wire
                )
            except RPCError as e:
                if "HTTP 404" in str(e):
                    # replica runs without admission control: no job API —
                    # remember and fall through to the sync path
                    self._sync_only[i] = True
                    logger.info(
                        "replica %s has no async job API; using "
                        "synchronous shard scans", self.cfg.hosts[i],
                    )
                else:
                    raise
            else:
                return self._poll_result(i, shard, sub["JobID"], ctx)
        resp = driver.scan_shard(label, wire, self.scan_options)
        if shard.done:
            return None
        return resp

    def _poll_result(self, i: int, shard: _ShardState, job_id: str, ctx):
        from trivy_tpu.rpc.client import RPCError

        driver = self.drivers[i]
        host = self.cfg.hosts[i]
        # the telemetry poller scrapes live progress for whatever is in
        # the active set; registration is best-effort bookkeeping only
        with self._lock:
            self._active_jobs[host].add(job_id)
        try:
            return self._poll_result_inner(
                i, shard, job_id, ctx, driver, RPCError
            )
        finally:
            with self._lock:
                self._active_jobs[host].discard(job_id)

    def _poll_result_inner(self, i, shard, job_id, ctx, driver, RPCError):
        deadline = time.monotonic() + self.cfg.job_timeout
        misses = 0
        polls = 0
        while True:
            if shard.done or self._stop:
                # the twin won, or the run was abandoned (timeout) —
                # stop polling so worker joins don't outlive the scan
                return None
            if self._dead_marks[i]:
                # the telemetry poller declared this replica dead (2
                # consecutive failed scrapes): abandon the poll NOW so
                # the shard re-dispatches instead of sitting parked in
                # "dispatched" until the job timeout
                raise RPCError(
                    f"replica {self.cfg.hosts[i]} declared dead by "
                    f"telemetry; abandoning job {job_id[:8]}"
                )
            try:
                doc = driver.fetch_result(job_id)
            except RPCError:
                misses += 1
                if misses > 3 or time.monotonic() >= deadline:
                    raise
                time.sleep(self.cfg.poll_s)
                continue
            misses = 0
            status = doc.get("Status")
            if status == "done":
                return doc.get("Result") or {}
            if status == "rejected" and \
                    "draining" in (doc.get("Error") or ""):
                # the replica's admission queue handed the job back on
                # SIGTERM — a clean drain, not a failure
                raise ReplicaDraining(
                    f"shard job {job_id[:8]} handed back: "
                    f"{doc.get('Error')}"
                )
            if status in ("failed", "expired", "rejected"):
                raise RPCError(
                    f"shard job {job_id[:8]}: {status}: "
                    f"{doc.get('Error', '')}"
                )
            if time.monotonic() >= deadline:
                raise RPCError(
                    f"shard job {job_id[:8]}: still {status} after "
                    f"{self.cfg.job_timeout:.0f}s"
                )
            polls += 1
            if status == "running" and polls % PROGRESS_EVERY_POLLS == 0:
                try:
                    self._note_progress(
                        shard, driver.progress(job_id), ctx
                    )
                except Exception:
                    pass  # progress polling is advisory, never fatal
            delay = self.cfg.poll_s
            if status == "queued" and doc.get("RetryAfterSeconds"):
                delay = min(
                    2.0, max(delay, float(doc["RetryAfterSeconds"]))
                )
            time.sleep(delay)

    # -- all-dead degradation ------------------------------------------------

    def _fallback(self, dead: list[_ShardState], ctx) -> None:
        if not self.cfg.host_fallback:
            raise FleetError(
                f"{len(dead)} shard(s) failed on every replica and "
                "--no-host-fallback is set: "
                + ", ".join(s.spec.label() for s in dead[:4])
            )
        if self.local_cache is None:
            raise FleetError(
                "no local cache available for the host-fallback scan"
            )
        from trivy_tpu.fleet import plan as fleet_plan
        from trivy_tpu.obs import export as obs_export

        logger.warning(
            "fleet degraded: scanning %d shard(s) locally (every replica "
            "is dead)", len(dead),
        )
        obs.note_scan_degraded()
        for shard in dead:
            # the local run is a pseudo-replica: it executes under a child
            # context whose trace/health fold back exactly like a remote
            # shard response, so one timeline still covers every shard
            child = obs.TraceContext(
                name=f"fleet-local:{shard.spec.label()}",
                enabled=ctx.enabled, trace_id=ctx.trace_id,
            )
            t0 = time.monotonic()
            with obs.activate(child):
                with child.span("fleet.local_shard"):
                    try:
                        blobs = fleet_plan.execute_shard(
                            shard.spec.wire, self.local_cache
                        )
                    except Exception as e:
                        # the fallback is the last rung — surface its
                        # failure as a clean FleetError (the command
                        # layer's error path), not a raw traceback
                        raise FleetError(
                            f"local fallback for {shard.spec.label()} "
                            f"failed: {e}"
                        ) from e
            resp: dict = {"Blobs": blobs, "Health": child.health_snapshot()}
            if ctx.enabled:
                resp["Trace"] = obs_export.context_doc(child)
            shard.done = True
            shard.state = "done"
            shard.blobs = list(blobs)
            with self._lock:
                # a fallback-completed fragment may be the last one its
                # split parent was waiting on
                self._resolve_split_locked(shard)
            self.stats["local_fallback"] += 1
            ctx.count("fleet.local_fallback")
            if ctx.enabled:
                # the degraded path is a pseudo-replica in the cost
                # attribution — stragglers that died everywhere show up
                # as "local" rows, not as missing bytes
                ctx.profile().note_shard(
                    "local", shard.spec.nbytes, time.monotonic() - t0,
                    attempts=shard.attempts,
                )
            self._fold_result(shard, resp, ctx)
