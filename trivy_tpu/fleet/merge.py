"""Fleet merger: shard results back into one single-host-identical report.

:class:`FleetArtifact` is the trick that keeps the merge byte-exact: it
implements the standard ``Artifact.inspect()`` surface, so the ordinary
``Scanner(artifact, LocalDriver(cache))`` pairing does the actual merging
— every shard's blobs land in the coordinator's cache under the exact
keys a single-host scan would have stored them (image layers keep their
planned per-layer keys; fs partitions get content-addressed ids in
deterministic partition order), and the untouched
:func:`~trivy_tpu.fanal.applier.apply_layers` + result-assembly path
produces the report. Dedup across overlapping layer paths, whiteout
semantics, and stable finding order are therefore *inherited*, not
re-implemented, and findings are byte-identical to a single-host scan by
construction. ``Degraded`` / ``SkippedFiles`` metadata sums the same way:
shard responses carry their health deltas and the coordinator folds them
into the scan context the report reads.
"""

from __future__ import annotations

import hashlib
import os

from trivy_tpu import log, obs
from trivy_tpu.fleet.coordinator import FleetConfig, FleetCoordinator
from trivy_tpu.types import ArtifactReference

logger = log.logger("fleet:merge")


class FleetArtifact:
    """Artifact facade that scatters analysis across the fleet and
    gathers blobs into ``cache``; detection and report assembly then run
    through the standard local driver path."""

    def __init__(self, kind: str, target: str, cache, option,
                 fleet_config: FleetConfig, scan_options):
        if kind not in ("fs", "image"):
            raise ValueError(f"fleet scans support fs/image, not {kind!r}")
        self.kind = kind
        self.type = "filesystem" if kind == "fs" else "container_image"
        self.target = target
        self.cache = cache
        self.option = option
        self.fleet_config = fleet_config
        self.scan_options = scan_options
        self.coordinator: FleetCoordinator | None = None  # set by inspect()

    def stats(self) -> dict:
        return dict(self.coordinator.stats) if self.coordinator else {}

    def telemetry(self) -> dict:
        """The fan-out's fleet telemetry doc (per-replica headroom/health
        series attached to the scan context at poller stop), or {} when
        the poller was off / no fan-out has run. Bench and report callers
        read this instead of reaching into the coordinator."""
        ctx = obs.current()
        return dict(getattr(ctx, "fleet", None) or {})

    def inspect(self) -> ArtifactReference:
        from trivy_tpu.fleet import plan as fleet_plan

        ctx = obs.current()
        self.coordinator = FleetCoordinator(
            self.fleet_config, self.scan_options, local_cache=self.cache
        )
        with ctx.span("fleet.plan"):
            if self.kind == "fs":
                return self._inspect_fs(ctx, fleet_plan)
            return self._inspect_image(ctx, fleet_plan)

    # -- fs ------------------------------------------------------------------

    def _inspect_fs(self, ctx, fleet_plan) -> ArtifactReference:
        shards, total_bytes, total_files = fleet_plan.plan_fs_shards(
            self.target, self.option, self.scan_options,
            self.fleet_config.target_shards(),
        )
        progress = ctx.progress()
        progress.note_walked(total_bytes, files=total_files)
        progress.finish_walk()
        logger.info(
            "fleet plan: %s -> %d shard(s) over %d replica(s) "
            "(%.1f MiB, %d files)",
            self.target, len(shards), len(self.fleet_config.hosts),
            total_bytes / (1 << 20), total_files,
        )
        results = self.coordinator.run(shards)
        # one blob per partition, applied in deterministic plan order —
        # partitions are path-disjoint so apply_layers yields the same
        # sorted union a single-host one-blob scan produces
        blob_ids: list[str] = []
        for idx in sorted(results):
            for b in results[idx]:
                self.cache.put_blob(b["BlobID"], b["BlobInfo"])
                blob_ids.append(b["BlobID"])
        artifact_id = "sha256:" + hashlib.sha256(
            ("fleet:" + ":".join(blob_ids)).encode()
        ).hexdigest()
        name = self.target
        if name != os.path.sep:
            name = name.rstrip(os.path.sep)
        return ArtifactReference(
            name=name, type=self.type, id=artifact_id, blob_ids=blob_ids
        )

    # -- image ---------------------------------------------------------------

    def _inspect_image(self, ctx, fleet_plan) -> ArtifactReference:
        from trivy_tpu.artifact.image import (
            DaemonImageArtifact,
            new_image_artifact,
        )
        from trivy_tpu.fleet import FleetError

        artifact = new_image_artifact(self.target, self.cache, self.option)
        if isinstance(artifact, DaemonImageArtifact):
            # the daemon export lives in a coordinator-local temp file the
            # replicas cannot open, and the shard wire would carry the
            # bare image REFERENCE — a replica would fall back to a
            # registry pull of possibly DIFFERENT content under the same
            # tag. Refuse loudly instead of scanning the wrong bytes
            raise FleetError(
                f"fleet image scans need an archive path or a registry "
                f"reference the replicas can fetch; {self.target!r} "
                "resolved to a local daemon export (save it to an archive "
                "or push it to a registry first)"
            )
        plan = fleet_plan.plan_image_shards(
            artifact, self.cache, self.scan_options
        )
        total = sum(s.nbytes for s in plan.shards)
        progress = ctx.progress()
        progress.note_walked(total, files=len(plan.shards))
        progress.finish_walk()
        logger.info(
            "fleet plan: %s -> %d missing layer shard(s) over %d "
            "replica(s) (%.1f MiB; %d layer(s) already cached)",
            plan.name, len(plan.shards), len(self.fleet_config.hosts),
            total / (1 << 20),
            len(plan.blob_ids) - 1 - len(plan.shards),
        )
        if plan.shards:
            results = self.coordinator.run(plan.shards)
            for idx in sorted(results):
                for b in results[idx]:
                    self.cache.put_blob(b["BlobID"], b["BlobInfo"])
        if plan.config_missing:
            # image-config analysis (ENV secrets, history misconfig) is one
            # tiny synthetic blob — the coordinator handles it locally
            archive = artifact._open_source()
            try:
                blob = artifact._analyze_config(archive)
            finally:
                archive.close()
            self.cache.put_blob(plan.config_key, blob.to_dict())
        return ArtifactReference(
            name=plan.name,
            type=self.type,
            id=plan.artifact_key,
            blob_ids=plan.blob_ids,
            image_metadata=plan.image_metadata,
        )
