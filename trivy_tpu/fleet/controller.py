"""Fleet placement controller: headroom-weighted dispatch (ROADMAP 4(c)).

The PR 15 telemetry plane produces a per-replica headroom score in
[0, 1]; this module closes the loop. A :class:`FleetController` maps each
replica's headroom to a quantized **placement weight** the coordinator's
affinity queues consult instead of raw byte counts alone: requeue targets
minimize *weighted* queued bytes (``queued_bytes / weight``), and steal
donors are ranked by the same weighted load, so a replica the gauges say
is drowning sheds work to one with headroom to spare.

Stability is inherited from the PR 9 tuning machinery, applied per
replica:

- **quantization** — weights move on a coarse ladder
  (:data:`WEIGHT_STEP` rungs between :data:`MIN_WEIGHT` and
  :data:`MAX_WEIGHT`), so a decision is a discrete re-weight, never a
  continuous chase of a noisy gauge;
- **dead band** — a re-weight is only *proposed* when the raw headroom
  sits more than half a rung plus :data:`DEAD_BAND` away from the current
  weight, so noise straddling a rung edge proposes nothing;
- **2-tick hysteresis** — a proposal must repeat for
  :data:`~trivy_tpu.tuning.HYSTERESIS_TICKS` consecutive ticks before it
  fires (one outlier scrape cannot move placement);
- **cooldown** — a fired re-weight opens a per-replica
  :data:`~trivy_tpu.tuning.COOLDOWN_TICKS` window during which that
  replica's weight holds still (the new placement must show up in the
  gauges before the next decision).

Together these make placement provably oscillation-free under bounded
gauge noise: any feed whose per-replica amplitude stays within the dead
band reaches a fixed point and never fires again — the scripted-gauge
tests drive :meth:`FleetController.step` directly to assert exactly that,
plus the decision-log replay invariant (per-replica weight deltas sum to
``final - initial``).

The controller is **tickless**: it owns no thread. The telemetry
poller's scrape loop calls :meth:`tick` with each fresh headroom
snapshot, so the controller's cadence IS ``--fleet-telemetry-interval``
and fleet-off / telemetry-off runs never construct one (``bench --smoke``
asserts zero cost). Decisions land in the bounded decision log
(``doc()``, attached to the scan's fleet block), the scan timeseries
(per-replica ``fleet.weight.*`` counter tracks in the merged Perfetto
timeline), and the ``trivy_tpu_fleet_weight{replica=}`` gauge the poller
exports and retires with the rest of the fleet rows.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from trivy_tpu import log
from trivy_tpu.tuning import COOLDOWN_TICKS, HYSTERESIS_TICKS, MAX_DECISIONS

logger = log.logger("fleet:controller")

# the weight ladder: coarse on purpose — placement only needs "give this
# replica roughly half / a quarter of its fair share", and coarse rungs
# are what make the dead band meaningful
WEIGHT_STEP = 0.25
MIN_WEIGHT = 0.25  # a breaker-open replica is excluded by the breaker,
MAX_WEIGHT = 1.0   # not by a zero weight — weights only bias placement
# margin past a rung's half-width before a re-weight is even proposed:
# headroom noise of amplitude < WEIGHT_STEP/2 + DEAD_BAND around a rung
# edge proposes nothing, ever
DEAD_BAND = 0.05

# decision-log schema at fleet level (mirrors tuning.DECISION_FIELDS;
# ``gauges`` carries the full per-replica headroom snapshot the decision
# was made from, so the log replays standalone)
FLEET_DECISION_FIELDS = ("t", "rule", "knob", "from", "to", "gauges")


def quantize_weight(headroom: float) -> float:
    """Nearest weight rung for a headroom score, clamped to the ladder."""
    h = min(1.0, max(0.0, headroom))
    q = round(h / WEIGHT_STEP) * WEIGHT_STEP
    return round(min(MAX_WEIGHT, max(MIN_WEIGHT, q)), 2)


class FleetController:
    """Per-fan-out headroom→placement-weight controller (tickless; the
    telemetry poller drives :meth:`tick` on its scrape cadence)."""

    def __init__(self, hosts, ctx=None, interval: float | None = None,
                 on_weights=None):
        self.ctx = ctx
        self.interval = float(interval or 0.0)
        self.on_weights = on_weights  # coordinator callback(weights dict)
        self.ticks = 0
        self._lock = threading.Lock()
        self._weights: dict[str, float] = {h: MAX_WEIGHT for h in hosts}
        self._initial: dict[str, float] = dict(self._weights)
        self._pending: dict[str, float] = {}   # host -> proposed rung
        self._streak: dict[str, int] = {}      # host -> consecutive ticks
        self._cooldown: dict[str, int] = {}    # host -> ticks remaining
        self.decisions: deque = deque(maxlen=MAX_DECISIONS)
        self.dropped = 0

    # -- surface -------------------------------------------------------------

    def weights(self) -> dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def add_host(self, host: str) -> None:
        """A replica joined mid-sweep: it enters at full weight (no gauge
        history argues otherwise) and the initial snapshot grows so the
        replay invariant stays exact."""
        with self._lock:
            if host in self._weights:
                return
            self._weights[host] = MAX_WEIGHT
            self._initial[host] = MAX_WEIGHT

    # -- decision core (pure over a headroom snapshot) -----------------------

    def step(self, headrooms: dict[str, float],
             t: float | None = None) -> list[dict]:
        """One control tick over ``{host: headroom}``. Returns the
        decisions fired (usually none). Hosts absent from the snapshot
        hold their weight — no data is not the same as headroom 0."""
        self.ticks += 1
        if t is None:
            t = self.ticks * (self.interval or 1.0)
        fired: list[dict] = []
        with self._lock:
            for host, h in headrooms.items():
                if host not in self._weights:
                    continue  # not registered (join races a scrape)
                cur = self._weights[host]
                if self._cooldown.get(host, 0) > 0:
                    self._cooldown[host] -= 1
                    self._pending.pop(host, None)
                    self._streak.pop(host, None)
                    continue
                cand = quantize_weight(h)
                # dead band: inside the current rung's half-width plus
                # the margin, nothing is even proposed
                if cand == cur or \
                        abs(h - cur) <= WEIGHT_STEP / 2 + DEAD_BAND:
                    self._pending.pop(host, None)
                    self._streak.pop(host, None)
                    continue
                if self._pending.get(host) != cand:
                    self._pending[host] = cand
                    self._streak[host] = 1
                    continue
                self._streak[host] += 1
                if self._streak[host] < HYSTERESIS_TICKS:
                    continue
                # fire: one rung assignment, then hold still
                self._pending.pop(host, None)
                self._streak.pop(host, None)
                self._cooldown[host] = COOLDOWN_TICKS
                d = {
                    "t": round(t, 3),
                    "rule": "reweight",
                    "knob": f"weight:{host}",
                    "from": cur,
                    "to": cand,
                    "gauges": {
                        hh: round(float(vv), 4)
                        for hh, vv in sorted(headrooms.items())
                    },
                }
                if len(self.decisions) == self.decisions.maxlen:
                    self.dropped += 1
                self.decisions.append(d)
                self._weights[host] = cand
                fired.append(d)
            weights = dict(self._weights) if fired else None
        if fired:
            if self.on_weights is not None:
                self.on_weights(weights)
            for d in fired:
                logger.info(
                    "fleet placement: %s %.2f -> %.2f (headroom %.3f)",
                    d["knob"], d["from"], d["to"],
                    d["gauges"].get(d["knob"].split(":", 1)[1], 0.0),
                )
        return fired

    def tick(self, headrooms: dict[str, float]) -> list[dict]:
        """One live tick from the poller: decide, then mirror weights to
        the scan timeseries so the merged Perfetto timeline carries
        per-replica ``fleet.weight.*`` counter tracks."""
        ctx = self.ctx
        t = None
        if ctx is not None:
            t = time.perf_counter() - ctx.created
        fired = self.step(headrooms, t)
        if ctx is not None and ctx.enabled:
            ts = getattr(ctx, "timeseries", None)
            if ts is not None:
                with self._lock:
                    snap = dict(self._weights)
                for host, w in snap.items():
                    ts.record(f"fleet.weight.{host}", t or 0.0, w)
            for _ in fired:
                ctx.count("fleet.placement_decisions")
        return fired

    def doc(self) -> dict:
        """Decision-log snapshot for the fleet block: per-replica weight
        deltas in ``decision_log`` sum exactly to ``final - initial`` per
        knob (the replay invariant, asserted at fleet level)."""
        with self._lock:
            out = {
                "interval": self.interval,
                "ticks": self.ticks,
                "initial": dict(self._initial),
                "final": dict(self._weights),
                "decisions": len(self.decisions) + self.dropped,
                "decision_log": [dict(d) for d in self.decisions],
            }
            if self.dropped:
                out["dropped"] = self.dropped
        return out
