"""Shard planner + replica-side shard executor.

An artifact splits at its natural boundaries into self-contained shard
specs small enough to balance and steal, big enough that per-shard RPC
overhead stays noise:

- **Image artifacts** shard by layer: the per-layer cache diff
  (``MissingBlobs``) already isolates layers, so every cached layer is
  excluded from the plan outright (never shipped, never re-analyzed) and
  each missing layer becomes one shard carrying the exact blob key the
  single-host pipeline would store it under.
- **Filesystem/repo artifacts** shard by deterministic walk partition:
  one walk (same skip rules as a single-host scan) collects per-directory
  units — directories stay atomic so sibling-file analyzers (lockfile +
  manifest pairs) and Helm chart subtrees (anything under a directory
  holding ``Chart.yaml``) never split across shards — then LPT-balances
  the units into byte-balanced partitions. The plan is a pure function of
  the tree: replanning an unchanged tree yields identical shards.

The executor half (:func:`execute_shard`) runs on a replica (inside
``ScanServer.scan`` when a request carries a ``Shard`` block) or locally
as the all-replicas-dead fallback: it turns one spec into the same
``BlobInfo`` dicts a single-host scan would produce, consulting the
executing cache first so warmed replicas skip straight to the bytes that
actually changed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from trivy_tpu import faults, log, obs

logger = log.logger("fleet:plan")

# fs trees overpartition beyond the replica count so the largest-first
# queue has grain for stealing and stragglers re-balance naturally
DEFAULT_SHARDS_PER_REPLICA = 4

# shard-executor read-ahead window (a shard is a slice of one host's walk,
# and several shard jobs run concurrently per replica — keep the per-shard
# window smaller than LocalFSArtifact's whole-scan bound)
PREFETCH_BYTES = 64 << 20
PREFETCH_FILES = 64


@dataclass
class ShardSpec:
    """One self-contained unit of fleet work. ``wire`` is the JSON body a
    replica executes; ``nbytes`` is the planner's balance/steal weight;
    ``blob_ids`` are the cache keys this shard's blobs land under (image
    shards know them up front; fs shards discover them post-analysis)."""

    index: int
    kind: str  # "fs" | "image-layer"
    nbytes: int
    wire: dict = field(default_factory=dict)
    blob_ids: list = field(default_factory=list)

    def label(self) -> str:
        return f"shard {self.index} ({self.kind}, {self.nbytes >> 10} KiB)"


def _analysis_wire(option, scan_options) -> dict:
    """The analysis-affecting knobs a shard must carry so the replica's
    analyzer group matches the coordinator's plan (cache keys and findings
    both depend on it)."""
    wire = {
        "Scanners": list(getattr(scan_options, "scanners", ["secret"])),
        "LicenseFull": bool(getattr(scan_options, "license_full", False)),
        "Backend": getattr(option, "backend", "auto"),
        "SkipFiles": list(getattr(option, "skip_files", [])),
        "SkipDirs": list(getattr(option, "skip_dirs", [])),
        "SharedArena": not (getattr(option, "analyzer_extra", None) or {}).get(
            "no_shared_arena"
        ),
        "Parallel": int(getattr(option, "parallel", 0) or 0),
    }
    # a custom secret ruleset changes findings AND cache keys: ship the
    # path (fleet fs mode already assumes a shared filesystem; a replica
    # missing the file fails the shard LOUDLY instead of silently
    # scanning with default rules — see shard_artifact_option)
    secret_cfg = getattr(option, "secret_config_path", None)
    if secret_cfg:
        wire["SecretConfig"] = secret_cfg
    # registry image sources need the coordinator's pull options on the
    # replica (same trust domain as the token-authed RPC channel; the
    # admission job table frees request docs at terminal states)
    reg = {
        "Insecure": bool(getattr(option, "insecure_registry", False)),
        "Username": getattr(option, "registry_username", "") or "",
        "Password": getattr(option, "registry_password", "") or "",
        "Platform": getattr(option, "platform", "") or "",
    }
    if any(reg.values()):
        wire["Registry"] = reg
    return wire


# -- filesystem planning -----------------------------------------------------


def group_units(files: list[tuple[str, int]]) -> list[tuple[str, list, int]]:
    """Directory-atomic unit grouping over ``[(rel, size), ...]``.

    Each unit is ``(unit_key, [(rel, size), ...], bytes)``. A directory
    containing ``Chart.yaml`` pulls its whole subtree into one unit (Helm
    chart evaluation reads the chart as a whole); every other directory is
    its own unit (sibling files — manifest + lockfile pairs — stay
    together). Shared by the fleet shard planner AND the incremental-scan
    unit planner (``trivy_tpu/incremental/fs.py``): both need an analysis
    boundary that merges back byte-identically through the applier.
    """
    by_dir: dict[str, list[tuple[str, int]]] = {}
    chart_roots: list[str] = []
    for rel, size in files:
        d = rel.rsplit("/", 1)[0] if "/" in rel else ""
        by_dir.setdefault(d, []).append((rel, size))
        if rel.rsplit("/", 1)[-1] == "Chart.yaml":
            chart_roots.append(d)
    # fold every directory under a chart root into that root's unit
    # (nearest enclosing chart wins, so nested charts stay whole too)
    chart_roots.sort(key=len, reverse=True)

    def unit_for(d: str) -> str:
        for cr in chart_roots:  # longest (nearest enclosing) chart wins
            if cr == "":
                return ""
            if d == cr or d.startswith(cr + "/"):
                return cr
        return d

    units_map: dict[str, list[tuple[str, int]]] = {}
    for d, entries in by_dir.items():
        units_map.setdefault(unit_for(d), []).extend(entries)
    units = []
    for key in sorted(units_map):
        entries = sorted(units_map[key])
        units.append((key, entries, sum(s for _, s in entries)))
    return units


def _walk_units(root: str, option) -> tuple[list[tuple[str, list, int]], int, int]:
    """One deterministic walk → directory-atomic units (see
    :func:`group_units`). Returns ``(units, total_bytes, total_files)``."""
    from trivy_tpu.fanal.walker import FSWalker, WalkOption

    walker = FSWalker(
        WalkOption(
            skip_files=list(getattr(option, "skip_files", [])),
            skip_dirs=list(getattr(option, "skip_dirs", [])),
        )
    )
    flat: list[tuple[str, int]] = []
    for rel, info, _opener in walker.walk(root):
        flat.append((rel, info.size))
    units = group_units(flat)
    return units, sum(s for _, s in flat), len(flat)


def plan_fs_shards(root: str, option, scan_options,
                   n_shards: int) -> tuple[list[ShardSpec], int, int]:
    """Deterministic byte-balanced fs partition plan. Returns
    ``(shards, total_bytes, total_files)``; shards come out largest-first
    (the dispatch order the coordinator's queues want)."""
    units, total_bytes, total_files = _walk_units(root, option)
    n_shards = max(1, min(n_shards, len(units)) if units else 1)
    # LPT: biggest unit first into the lightest bin; ties resolve by bin
    # index so the plan is a pure function of the tree
    bins: list[list] = [[] for _ in range(n_shards)]
    loads = [0] * n_shards
    for key, files, nbytes in sorted(
        units, key=lambda u: (-u[2], u[0])
    ):
        i = min(range(n_shards), key=lambda j: (loads[j], j))
        bins[i].extend(files)
        loads[i] += nbytes
    analysis = _analysis_wire(option, scan_options)
    shards = []
    order = sorted(range(n_shards), key=lambda j: (-loads[j], j))
    for idx, j in enumerate(order):
        if not bins[j]:
            continue
        paths = sorted(rel for rel, _ in bins[j])
        shards.append(
            ShardSpec(
                index=idx,
                kind="fs",
                nbytes=loads[j],
                wire={
                    "Kind": "fs",
                    "Root": os.path.abspath(root),
                    "Paths": paths,
                    "Bytes": loads[j],
                    **analysis,
                },
            )
        )
    return shards, total_bytes, total_files


def split_fs_shard(spec: ShardSpec, n: int = 2) -> list[ShardSpec] | None:
    """Mid-scan re-plan of one fs shard into ``n`` byte-balanced
    fragments at the SAME directory-atomic unit boundaries the original
    plan used (:func:`group_units` — Helm chart subtrees stay whole), so
    the fragment set is an exact partition of the shard's paths and the
    merge through the applier stays byte-identical.

    Fragment indexes interleave between the parent's and the next
    integer (``index + k/(n+1)``) so the coordinator's sorted result
    fold keeps plan order without renumbering untouched shards. Returns
    None when the shard has fewer than 2 units (nothing to split at a
    directory boundary) — image-layer shards are atomic by construction
    and must never reach here.
    """
    if spec.wire.get("Kind") != "fs" or n < 2:
        return None
    root = spec.wire["Root"]
    files = []
    for rel in spec.wire["Paths"]:
        try:
            size = os.path.getsize(os.path.join(root, rel))
        except OSError:
            # a file deleted since the plan: carry it at zero weight —
            # the replica's walker owns per-file error semantics, the
            # split must not change WHICH paths are scanned
            size = 0
        files.append((rel, size))
    units = group_units(files)
    if len(units) < 2:
        return None
    n = min(n, len(units))
    bins: list[list] = [[] for _ in range(n)]
    loads = [0] * n
    for key, unit_files, nbytes in sorted(
        units, key=lambda u: (-u[2], u[0])
    ):
        i = min(range(n), key=lambda j: (loads[j], j))
        bins[i].extend(unit_files)
        loads[i] += nbytes
    frags = []
    order = sorted(range(n), key=lambda j: (-loads[j], j))
    for k, j in enumerate(order):
        if not bins[j]:
            continue
        wire = dict(spec.wire)
        wire["Paths"] = sorted(rel for rel, _ in bins[j])
        wire["Bytes"] = loads[j]
        frags.append(
            ShardSpec(
                index=spec.index + (k + 1) / (n + 1),
                kind="fs",
                nbytes=loads[j],
                wire=wire,
            )
        )
    return frags if len(frags) >= 2 else None


# -- image planning ----------------------------------------------------------


@dataclass
class ImagePlan:
    """Everything the merger needs to reassemble a fleet image scan into
    the exact single-host reference: the full blob-id list (cached +
    planned), artifact identity, and image metadata."""

    name: str
    artifact_key: str
    blob_ids: list
    config_key: str
    config_missing: bool
    image_metadata: dict
    shards: list


def plan_image_shards(artifact, cache, scan_options) -> ImagePlan:
    """Per-layer shard plan for an image artifact: the coordinator-side
    ``MissingBlobs`` diff excludes every cached layer up front, and each
    missing layer becomes one shard carrying its planned blob key."""
    archive = artifact._open_source()
    try:
        plan = artifact.layer_plan(archive)
        blob_ids = plan["layer_keys"] + [plan["config_key"]]
        _, missing = cache.missing_blobs(plan["artifact_key"], blob_ids)
        missing_set = set(missing)
        analysis = _analysis_wire(artifact.option, scan_options)
        shards = []
        history = plan["history"]
        for i, (diff_id, lkey) in enumerate(
            zip(plan["diff_ids"], plan["layer_keys"])
        ):
            if lkey not in missing_set:
                continue
            try:  # registry sources may not expose stored layer sizes;
                nbytes = max(1, int(archive.layer_size(i)))
            except Exception:  # weight 1 keeps the plan balanced by count
                nbytes = 1
            shards.append(
                ShardSpec(
                    index=len(shards),
                    kind="image-layer",
                    nbytes=nbytes,
                    blob_ids=[lkey],
                    wire={
                        "Kind": "image-layer",
                        "Archive": artifact.path,
                        "Index": i,
                        "DiffID": diff_id,
                        "BlobID": lkey,
                        "CreatedBy": (
                            history[i].get("created_by", "")
                            if i < len(history) else ""
                        ),
                        "SkipSecret": i in plan["base_layers"],
                        "Bytes": nbytes,
                        **analysis,
                    },
                )
            )
        # largest-first dispatch order, deterministic on ties
        shards.sort(key=lambda s: (-s.nbytes, s.index))
        for idx, s in enumerate(shards):
            s.index = idx
        cfg = archive.config
        return ImagePlan(
            name=archive.name,
            artifact_key=plan["artifact_key"],
            blob_ids=blob_ids,
            config_key=plan["config_key"],
            config_missing=plan["config_key"] in missing_set,
            image_metadata={
                "id": archive.image_id,
                "diff_ids": plan["diff_ids"],
                "config": {
                    "architecture": cfg.get("architecture", ""),
                    "created": cfg.get("created", ""),
                    "os": cfg.get("os", ""),
                    "config": cfg.get("config", {}),
                },
            },
            shards=shards,
        )
    finally:
        archive.close()


# -- replica-side execution --------------------------------------------------


def shard_artifact_option(shard: dict):
    """Reconstruct the analysis-affecting :class:`ArtifactOption` a shard
    spec carries — the replica's analyzer group (and so its cache keys and
    findings) must match what the coordinator planned."""
    from trivy_tpu.artifact.local_fs import ArtifactOption
    from trivy_tpu.fanal.analyzer import AnalyzerType

    scanners = list(shard.get("Scanners") or ["secret"])
    license_full = bool(shard.get("LicenseFull"))
    backend = shard.get("Backend") or "auto"
    disabled = []
    if "secret" not in scanners:
        disabled.append(AnalyzerType.SECRET)
    if "license" not in scanners:
        disabled.append(AnalyzerType.LICENSE_FILE)
        disabled.append(AnalyzerType.LICENSE_HEADER)
    elif not license_full:
        disabled.append(AnalyzerType.LICENSE_HEADER)
    if "misconfig" not in scanners:
        disabled.append(AnalyzerType.CONFIG)
    extra: dict = {}
    if (
        "secret" in scanners
        and "license" in scanners
        and backend != "cpu"
        and shard.get("SharedArena", True)
    ):
        from trivy_tpu.licensing.fused import FusedLicenseGate

        extra["fused_license"] = FusedLicenseGate(license_full=license_full)
    secret_cfg = shard.get("SecretConfig")
    if secret_cfg and not os.path.exists(secret_cfg):
        # the coordinator scans with a custom ruleset this host cannot
        # see — silently falling back to default rules would return
        # wrong findings AND poison the planned cache keys
        raise FileNotFoundError(
            f"secret config {secret_cfg!r} does not exist on this host — "
            "fleet scans with --secret-config require replicas to share "
            "the config file"
        )
    # cross-replica dedup warming: the coordinator's warm hit-store
    # entries ride the first shard to each replica; the secret analyzer
    # seeds its scanner's store (namespace-mismatched entries drop loudly)
    warm = shard.get("WarmHits")
    if warm:
        extra["secret_hit_seed"] = warm
    reg = shard.get("Registry") or {}
    return ArtifactOption(
        skip_files=list(shard.get("SkipFiles") or []),
        skip_dirs=list(shard.get("SkipDirs") or []),
        disabled_analyzers=disabled,
        secret_config_path=secret_cfg or None,
        backend=backend,
        analyzer_extra=extra,
        parallel=int(shard.get("Parallel") or 0),
        insecure_registry=bool(reg.get("Insecure")),
        registry_username=reg.get("Username", "") or "",
        registry_password=reg.get("Password", "") or "",
        platform=reg.get("Platform", "") or "",
    )


def execute_shard(shard: dict, cache) -> list[dict]:
    """Run one shard spec to completion on the executing host (a replica's
    ``ScanServer.scan``, or the coordinator's local fallback) and return
    its ``[{"BlobID", "BlobInfo"}, ...]`` list. Progress notes land on the
    active trace context, so a replica's shard scan feeds the standard
    ``GET /scan/<job_id>/progress`` poll the coordinator aggregates."""
    kind = shard.get("Kind")
    if kind == "fs":
        return _execute_fs_shard(shard, cache)
    if kind == "image-layer":
        return _execute_image_shard(shard, cache)
    raise ValueError(f"unknown shard kind: {kind!r}")


def _execute_fs_shard(shard: dict, cache) -> list[dict]:
    from trivy_tpu.cache.key import calc_blob_key, calc_key
    from trivy_tpu.fanal.analyzer import (
        AnalyzerGroup,
        AnalyzerOptions,
        AnalysisResult,
        note_file_skipped,
    )
    from trivy_tpu.fanal.handler import HandlerManager
    from trivy_tpu.fanal.walker import FileInfo

    option = shard_artifact_option(shard)
    root = shard["Root"]
    if not os.path.isdir(root):
        # a replica that does not share the coordinator's filesystem must
        # fail the shard LOUDLY — absorbing every path as a per-file
        # TOCTOU skip would return an empty blob and a silently-wrong
        # "successful" fleet scan (the coordinator's ladder then lands on
        # a replica that does share it, or the local fallback)
        raise FileNotFoundError(
            f"fs shard root {root!r} does not exist on this host — fleet "
            "fs scans require replicas to share the scanned filesystem"
        )
    group = AnalyzerGroup(
        AnalyzerOptions(
            disabled=option.disabled_analyzers,
            secret_config_path=option.secret_config_path,
            backend=option.backend,
            root=root,
            extra=option.analyzer_extra,
        )
    )
    handlers = HandlerManager()
    result = AnalysisResult()
    post_files: dict = {}
    progress = obs.current().progress()

    def analyze(rel, info, fut):
        try:
            wanted = group.analyze_file(result, root, rel, info, fut.result)
        except OSError as e:
            # TOCTOU: the file vanished (or turned unreadable) between the
            # plan walk and this read — skip it, count it, keep scanning
            # (same discipline as the single-host walk)
            note_file_skipped(rel, e)
            progress.note_scanned(info.size)
            return
        for t, content in wanted.items():
            post_files.setdefault(t, {})[rel] = content
        progress.note_scanned(info.size)

    try:
        # reader pool prefetches contents ahead of the analyzer loop —
        # the same read/analyze overlap the single-host fs artifact gets
        # (bounded window so huge files cannot pile up in memory)
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        from trivy_tpu.artifact.local_fs import DEFAULT_PARALLEL

        window: deque = deque()  # (rel, info, future)
        buffered = 0
        workers = option.parallel or DEFAULT_PARALLEL
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for rel in shard.get("Paths") or []:
                full = os.path.join(root, *rel.split("/"))
                try:
                    st = os.lstat(full)
                except OSError as e:
                    note_file_skipped(rel, e)
                    continue
                info = FileInfo.from_stat(st)
                progress.note_walked(info.size)

                def opener(path=full, rel=rel) -> bytes:
                    faults.check("walker.read", key=rel)
                    with open(path, "rb") as f:
                        return f.read()

                window.append((rel, info, pool.submit(opener)))
                buffered += info.size
                while buffered > PREFETCH_BYTES or len(window) > PREFETCH_FILES:
                    r, i, fut = window.popleft()
                    buffered -= i.size
                    analyze(r, i, fut)
            while window:
                r, i, fut = window.popleft()
                analyze(r, i, fut)
        group.finalize(result, post_files)
    except BaseException:
        # a dying shard must not leak the analyzers' background device
        # pipelines (threads + arena slabs)
        group.abort()
        raise
    blob = result.to_blob_info()
    handlers.post_handle(result, blob)
    blob_dict = blob.to_dict()
    blob_id = calc_key(
        calc_blob_key(blob_dict),
        analyzer_versions=group.versions(),
        hook_versions=handlers.versions(),
        skip_files=option.skip_files,
        skip_dirs=option.skip_dirs,
    )
    _, missing = cache.missing_blobs(blob_id, [blob_id])
    if missing:
        cache.put_blob(blob_id, blob_dict)
    return [{"BlobID": blob_id, "BlobInfo": blob_dict}]


def _execute_image_shard(shard: dict, cache) -> list[dict]:
    option = shard_artifact_option(shard)
    blob_id = shard["BlobID"]
    # warmed replica: the layer's analyzed blob is already cached under the
    # exact key the coordinator planned — never re-walked, never re-analyzed
    _, missing = cache.missing_blobs("", [blob_id])
    if not missing:
        cached = cache.get_blob(blob_id)
        if cached is not None:
            obs.current().count("fleet.layer_cache_hits")
            return [{"BlobID": blob_id, "BlobInfo": cached}]
    artifact = _image_artifact(shard["Archive"], cache, option)
    progress = obs.current().progress()
    progress.note_walked(int(shard.get("Bytes") or 0))
    blob = artifact._analyze_layer(
        shard["Index"],
        shard.get("DiffID", ""),
        shard.get("CreatedBy", ""),
        bool(shard.get("SkipSecret")),
    )
    progress.note_scanned(int(shard.get("Bytes") or 0))
    blob_dict = blob.to_dict()
    cache.put_blob(blob_id, blob_dict)
    return [{"BlobID": blob_id, "BlobInfo": blob_dict}]


def _image_artifact(path: str, cache, option):
    """Archive path when it exists on the executing host's filesystem
    (shared storage / in-process fleets), else a registry reference — the
    replica pulls its own layers, which is exactly the production shape
    (layer bytes never cross the coordinator's link)."""
    from trivy_tpu.artifact.image import (
        ImageArchiveArtifact,
        ImageRegistryArtifact,
    )

    if os.path.exists(path):
        return ImageArchiveArtifact(path, cache, option)
    return ImageRegistryArtifact(path, cache, option)
