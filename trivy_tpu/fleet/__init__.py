"""Distributed scan fabric (ROADMAP item 5): scatter-gather scanning of
one giant artifact across N server replicas.

Items 1–2 scale *many small scans* on one host; a single multi-GB image or
monorepo stays pinned at one host's link ceiling no matter how well the
feed is tuned — the only way past it is more replicas, each with its own
accelerator and feed path. This package is the coordinator side of that:

- :mod:`trivy_tpu.fleet.plan` — the **shard planner**: split an artifact
  at natural boundaries (image layers; byte-balanced, directory-atomic
  walk partitions for fs trees) into self-contained shard specs, plus the
  replica-side executor that turns one spec into analyzed ``BlobInfo``
  dicts.
- :mod:`trivy_tpu.fleet.coordinator` — fan shards out as async jobs over
  the existing :class:`~trivy_tpu.rpc.client.RemoteDriver`
  submit/wait surface to a ``--fleet host1,host2,...`` replica set, with
  bounded per-replica in-flight, work-stealing for skewed shards,
  speculative re-dispatch of stragglers (first result wins), per-replica
  :class:`~trivy_tpu.parallel.mesh.CircuitBreaker` failure handling, and
  an all-replicas-dead degradation to a local scan (the parity oracle).
- :mod:`trivy_tpu.fleet.merge` — :class:`~trivy_tpu.fleet.merge.FleetArtifact`
  folds shard results back into the standard scan path: blobs land in the
  coordinator's cache under the exact keys a single-host scan would use,
  the normal :class:`~trivy_tpu.scanner.local_driver.LocalDriver` merges
  them through the applier (findings byte-identical to a single-host
  scan), per-shard server ``Trace`` blocks join the coordinator's context
  (one Perfetto timeline, replicas as distinct pids), and per-shard
  progress aggregates into one coordinator-level
  :class:`~trivy_tpu.obs.timeseries.ScanProgress`.

Zero-cost-when-off: nothing in this package is imported (let alone
allocated) unless ``--fleet`` is given — no coordinator threads, no pooled
connections, no gauges (``bench --smoke`` asserts it).
"""

from __future__ import annotations


class FleetError(RuntimeError):
    """Unrecoverable fleet failure (every replica dead and host fallback
    disabled, or a shard that cannot complete anywhere)."""


def parse_fleet(hosts) -> list[str]:
    """Normalize a ``--fleet`` value (list or comma-joined string) into a
    deduplicated, order-preserving replica address list."""
    if hosts is None:
        return []
    if isinstance(hosts, str):
        hosts = hosts.split(",")
    out: list[str] = []
    for h in hosts:
        h = str(h).strip()
        if h and h not in out:
            out.append(h)
    return out
