"""Fleet telemetry plane: the coordinator's sensor half of ROADMAP 4(c).

A :class:`ReplicaPoller` thread scrapes each fleet replica's existing
``GET /metrics`` exposition (parsed back into typed samples by
:func:`trivy_tpu.obs.metrics.parse_text`, the renderer's inverse) and the
live progress of that replica's in-flight shard jobs on a cadence
(``--fleet-telemetry-interval`` / ``TRIVY_TPU_FLEET_TELEMETRY_INTERVAL``;
0 = off with zero threads, buffers, or gauges — this module is not even
imported then, ``bench --smoke`` asserts it). Scrapes fold into bounded
per-replica :class:`ReplicaHealth` timeseries — link MB/s, device busy
ratio, arena free slabs, admission queue depth, breaker state — each with
a :meth:`ReplicaHealth.headroom` score in [0, 1]: the exact input surface
item 4(c)'s headroom-weighted dispatch will consume.

Aggregated surfaces fed from here:

- ``trivy_tpu_fleet_*{replica="host:port"}`` gauges re-exported on the
  coordinator's own process registry (so a coordinator that is itself a
  server re-exposes fleet health on its ``/metrics``); label rows retire
  at poller stop, and concurrent fleets with distinct replica sets keep
  disjoint label sets by construction.
- per-replica counter tracks in the one merged Perfetto timeline and a
  ``fleet`` block in ``--metrics-out`` / ``--timeseries-out`` (via
  ``ctx.fleet``, attached at poller stop).
- the fleet ``--live`` line fragment and the heartbeat fleet fragment.

Lifecycle mirrors :class:`trivy_tpu.obs.timeseries.Sampler`: baseline
tick before the thread starts, daemon thread parked on an Event between
ticks, idempotent :meth:`ReplicaPoller.stop` from the coordinator's
``finally`` with a final tick and per-replica gauge retirement. A dead
replica's scrape failure is recorded (headroom 0, breaker state), never
raised — a dying replica must not kill the telemetry tick.
"""

from __future__ import annotations

import threading
import time

from trivy_tpu import log
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs.timeseries import Timeseries
from trivy_tpu.tuning import DEFAULT_FLEET_TELEMETRY_INTERVAL

logger = log.logger("fleet:telemetry")

# replica-side gauge families the scrape folds (name -> series name)
_SCRAPE_FOLD = {
    "trivy_tpu_link_mbs": "link_mbs",
    "trivy_tpu_arena_free_slabs": "arena_free_slabs",
}

# coordinator-side re-export gauges, all labeled {replica="host:port"}
_FLEET_GAUGE_SPECS = (
    ("trivy_tpu_fleet_link_mbs",
     "Per-replica host->device link bandwidth (MB/s), scraped by the "
     "fleet coordinator"),
    ("trivy_tpu_fleet_device_busy_ratio",
     "Per-replica max device busy fraction, scraped by the fleet "
     "coordinator"),
    ("trivy_tpu_fleet_arena_free_slabs",
     "Per-replica free feed-arena slabs, scraped by the fleet "
     "coordinator"),
    ("trivy_tpu_fleet_queue_depth",
     "Per-replica admission queue depth (all tenants), scraped by the "
     "fleet coordinator"),
    ("trivy_tpu_fleet_breaker_open",
     "1 when the coordinator's circuit breaker for this replica is open "
     "or its last scrape failed"),
    ("trivy_tpu_fleet_headroom",
     "Per-replica dispatch headroom score in [0,1] (0 = unreachable or "
     "breaker-open)"),
    ("trivy_tpu_fleet_weight",
     "Per-replica placement weight assigned by the fleet controller "
     "(absent when headroom-weighted dispatch is off)"),
)

# consecutive failed scrapes before the poller declares a replica dead
# and trips its breaker out-of-band (a replica that took work and died
# must not park its shard in 'dispatched' until the job timeout)
DEAD_SCRAPE_STREAK = 2


def _fleet_gauge(name: str, help: str) -> obs_metrics.Gauge:
    return obs_metrics.REGISTRY.gauge(name, help, labelnames=("replica",))


class ReplicaHealth:
    """One replica's bounded health series plus scrape bookkeeping.

    Series timestamps are seconds relative to the owning scan context's
    creation (same clock as local spans and sampler series), so the
    per-replica counter tracks join the merged timeline with no base
    shift. Scalar snapshot fields (``breaker_open``, ``reachable``,
    scrape counts) are written by the poller thread only; readers get
    last-tick values, which is all a headroom consumer needs.
    """

    def __init__(self, host: str):
        self.host = host
        self.series = Timeseries()
        self.scrapes = 0
        self.scrape_failures = 0
        self.reachable = False  # last scrape succeeded
        self.breaker_open = False  # coordinator breaker OR unreachable
        self.last: dict[str, float] = {}  # latest folded values

    def note_scrape(self, t: float, parsed: dict) -> None:
        """Fold one parsed ``/metrics`` body at timestamp ``t``."""
        self.scrapes += 1
        self.reachable = True
        vals: dict[str, float] = {}
        for metric, series in _SCRAPE_FOLD.items():
            fam = parsed.get(metric)
            v = fam.first() if fam is not None else None
            if v is not None:
                vals[series] = v
        busy = parsed.get("trivy_tpu_device_busy_ratio")
        if busy is not None and busy.samples:
            vals["device_busy_ratio"] = busy.max()
        queue = parsed.get("trivy_tpu_admission_queue_depth")
        # a replica without admission control exports no queue gauge:
        # treat as depth 0 (nothing queued), not unknown
        vals["queue_depth"] = queue.sum() if queue is not None else 0.0
        breaker = parsed.get("trivy_tpu_device_breaker_open")
        if breaker is not None and breaker.samples:
            vals["device_breaker_open"] = breaker.max()
        inflight = parsed.get("trivy_tpu_requests_in_flight")
        if inflight is not None and inflight.first() is not None:
            vals["requests_in_flight"] = inflight.first()
        for name, v in vals.items():
            self.series.record(name, t, v)
        self.last.update(vals)

    def note_failure(self, t: float) -> None:
        self.scrapes += 1
        self.scrape_failures += 1
        self.reachable = False
        self.series.record("headroom", t, 0.0)

    def note_progress(self, t: float, ratio: float, jobs: int) -> None:
        self.series.record("progress_ratio", t, ratio)
        self.series.record("jobs_active", t, float(jobs))
        self.last["progress_ratio"] = ratio

    def headroom(self) -> float:
        """Dispatch headroom in [0, 1] — the 4(c) placement input.

        0.0 when the replica is unreachable or its breaker is open
        (dispatching there is wasted work regardless of its last-known
        load); otherwise ``(1 - busy) / (1 + queue_depth)``, halved when
        the feed arena is starved (0 free slabs: accepted work would
        stall on allocation, not run).
        """
        if not self.reachable or self.breaker_open:
            return 0.0
        busy = min(1.0, max(0.0, self.last.get("device_busy_ratio", 0.0)))
        queue = max(0.0, self.last.get("queue_depth", 0.0))
        score = (1.0 - busy) / (1.0 + queue)
        arena = self.last.get("arena_free_slabs")
        if arena is not None and arena <= 0:
            score *= 0.5
        return round(min(1.0, max(0.0, score)), 4)

    def to_doc(self) -> dict:
        """Wire form for the ``fleet`` block: summary always, full series
        points for ``--timeseries-out`` via ``series``."""
        return {
            "headroom": self.headroom(),
            "breaker_open": bool(self.breaker_open),
            "reachable": bool(self.reachable),
            "scrapes": self.scrapes,
            "scrape_failures": self.scrape_failures,
            "summary": self.series.summary(),
            "series": self.series.to_doc(),
        }


class ReplicaPoller:
    """The coordinator's fleet telemetry thread (see module docstring)."""

    def __init__(self, coordinator, ctx, interval: float,
                 clock=time.perf_counter):
        self.coord = coordinator
        self.ctx = ctx
        self.interval = interval
        self.clock = clock
        self.hosts = list(coordinator.cfg.hosts)
        self.health = {h: ReplicaHealth(h) for h in self.hosts}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._gauge_rows: set[str] = set()  # replica labels we ever set
        # elastic plane hooks: the placement controller (set by the
        # coordinator when headroom-weighted dispatch is on — the poller
        # drives its ticks) and per-host dead-scrape streaks
        self.controller = None
        self._dead_streaks: dict[str, int] = {}

    # -- one tick ------------------------------------------------------------

    def scrape_once(self) -> None:
        from trivy_tpu.rpc.client import RPCError, get_metrics_text

        cfg = self.coord.cfg
        self._sync_hosts()
        # a dead replica must not stall the tick for the default RPC
        # timeout: the scrape deadline tracks the poll cadence (floor
        # 0.5 s so a loaded replica still answers), so one vanished host
        # costs at most ~one interval, not 5 s of serial head-of-line
        deadline = min(5.0, max(0.5, self.interval))
        for i, host in enumerate(self.hosts):
            t = self.clock() - self.ctx.created
            rh = self.health[host]
            coord_open = bool(self.coord.breaker.is_open(i))
            try:
                text = get_metrics_text(host, token=cfg.token,
                                        timeout=deadline)
                parsed = obs_metrics.parse_text(text)
            except (RPCError, obs_metrics.ParseError, OSError) as e:
                # a dead replica is telemetry, not an error: headroom
                # drops to 0 and the breaker row flips — the tick lives
                logger.debug("telemetry scrape of %s failed: %s", host, e)
                rh.breaker_open = True
                rh.note_failure(t)
                streak = self._dead_streaks.get(host, 0) + 1
                self._dead_streaks[host] = streak
                if streak >= DEAD_SCRAPE_STREAK:
                    # the death verdict: trip the breaker NOW so the
                    # shard this replica took re-dispatches instead of
                    # sitting out the job timeout (note_replica_dead is
                    # idempotent)
                    note_dead = getattr(
                        self.coord, "note_replica_dead", None
                    )
                    if note_dead is not None:
                        note_dead(
                            i, f"{streak} consecutive dead telemetry "
                               f"scrapes"
                        )
                self._export(host, rh)
                continue
            self._dead_streaks[host] = 0
            alive = getattr(self.coord, "note_replica_alive", None)
            if alive is not None:
                alive(i)
            rh.breaker_open = coord_open
            rh.note_scrape(t, parsed)
            draining = parsed.get("trivy_tpu_server_draining")
            if draining is not None and draining.samples \
                    and draining.max() >= 1.0:
                # the replica announced a clean drain on its own metrics:
                # hand its queued shards back before the rejected-job
                # round trips even land
                note_drain = getattr(
                    self.coord, "note_replica_draining", None
                )
                if note_drain is not None:
                    note_drain(i)
            self._poll_progress(i, host, rh, t)
            rh.series.record("headroom", t, rh.headroom())
            self._export(host, rh)
        ctrl = self.controller
        if ctrl is not None:
            # the controller is tickless — this scrape loop IS its clock
            fired = ctrl.tick(
                {h: self.health[h].headroom() for h in self.hosts}
            )
            apply_p = getattr(self.coord, "apply_placement", None)
            if apply_p is not None:
                apply_p(ctrl.weights(), len(fired))

    def _sync_hosts(self) -> None:
        """Pick up replicas that joined mid-sweep: the coordinator's host
        list is append-only, so mirroring its tail keeps scrape indexes
        aligned with breaker/driver slots."""
        cur = list(self.coord.cfg.hosts)
        for h in cur[len(self.hosts):]:
            self.hosts.append(h)
            self.health[h] = ReplicaHealth(h)

    def _poll_progress(self, i: int, host: str, rh: ReplicaHealth,
                       t: float) -> None:
        """Fold the replica's active shard jobs' live progress (advisory:
        any failure is skipped, the shard result path owns correctness)."""
        jobs = self.coord.active_jobs(host)
        if not jobs:
            return
        ratios = []
        driver = self.coord.drivers[i]
        for job_id in jobs:
            try:
                snap = driver.progress(job_id)
            except Exception:
                continue
            total = float(snap.get("BytesWalked") or 0)
            if total > 0:
                ratios.append(
                    min(1.0, float(snap.get("BytesScanned") or 0) / total)
                )
            elif snap.get("Ratio") is not None:
                ratios.append(min(1.0, float(snap["Ratio"])))
        if ratios:
            rh.note_progress(t, sum(ratios) / len(ratios), len(jobs))

    def _export(self, host: str, rh: ReplicaHealth) -> None:
        """Mirror a replica's latest health to the coordinator-side
        ``trivy_tpu_fleet_*{replica=}`` gauges."""
        self._gauge_rows.add(host)
        ctrl = self.controller
        vals = {
            "trivy_tpu_fleet_link_mbs": rh.last.get("link_mbs"),
            "trivy_tpu_fleet_device_busy_ratio":
                rh.last.get("device_busy_ratio"),
            "trivy_tpu_fleet_arena_free_slabs":
                rh.last.get("arena_free_slabs"),
            "trivy_tpu_fleet_queue_depth": rh.last.get("queue_depth"),
            "trivy_tpu_fleet_breaker_open": 1.0 if rh.breaker_open else 0.0,
            "trivy_tpu_fleet_headroom": rh.headroom(),
            # the weight row exists only when headroom-weighted dispatch
            # is on (None skips the set; bench --smoke asserts no rows)
            "trivy_tpu_fleet_weight":
                ctrl.weights().get(host) if ctrl is not None else None,
        }
        for name, help in _FLEET_GAUGE_SPECS:
            v = vals[name]
            if v is not None:
                _fleet_gauge(name, help).set(round(v, 4), replica=host)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaPoller":
        # baseline tick so even a sub-interval fan-out gets one sample
        # per replica (and the fleet gauges exist from the first moment
        # a scrape of the coordinator could observe the fleet)
        try:
            self.scrape_once()
        except Exception as e:
            logger.debug("baseline fleet telemetry tick failed: %s", e)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-telemetry-{self.ctx.trace_id[:8]}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from trivy_tpu import obs

        with obs.activate(self.ctx):
            while not self._stop.wait(self.interval):
                try:
                    self.scrape_once()
                except Exception as e:  # no tick may kill the poller
                    logger.debug("fleet telemetry tick failed: %s", e)

    def stop(self, timeout: float = 5.0) -> None:
        """Idempotent: stop the thread, take one final tick so every
        series carries the end state, retire this fleet's gauge label
        rows (concurrent fleets' rows — different replica addresses —
        survive untouched), and attach the fleet doc to the context."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
            try:
                self.scrape_once()
            except Exception as e:
                logger.debug("final fleet telemetry tick failed: %s", e)
        for name, help in _FLEET_GAUGE_SPECS:
            g = _fleet_gauge(name, help)
            for host in self._gauge_rows:
                g.remove(replica=host)
        self._gauge_rows.clear()
        self.ctx.fleet = self.fleet_doc()

    # -- aggregated surfaces -------------------------------------------------

    def fleet_doc(self) -> dict:
        doc = {
            "interval_s": self.interval,
            "replicas": {h: self.health[h].to_doc() for h in self.hosts},
        }
        if self.controller is not None:
            doc["controller"] = self.controller.doc()
        return doc

    def live_fragment(self) -> str:
        """Compact per-replica status for the ``--live`` line, e.g.
        ``fleet[r0 83% 412MB/s q0 | r1 OPEN]``."""
        parts = []
        for k, host in enumerate(self.hosts):
            rh = self.health[host]
            if rh.breaker_open or not rh.reachable:
                parts.append(f"r{k} OPEN")
                continue
            busy = rh.last.get("device_busy_ratio", 0.0) * 100.0
            mbs = rh.last.get("link_mbs", 0.0)
            q = int(rh.last.get("queue_depth", 0))
            parts.append(f"r{k} {busy:.0f}% {mbs:.0f}MB/s q{q}")
        return "fleet[" + " | ".join(parts) + "]"

    def status(self) -> dict:
        """Heartbeat-sized aggregate: replicas healthy / breaker-open and
        the summed fleet link MB/s (latest tick)."""
        healthy = open_ = 0
        mbs = 0.0
        for rh in self.health.values():
            if rh.breaker_open or not rh.reachable:
                open_ += 1
            else:
                healthy += 1
                mbs += rh.last.get("link_mbs", 0.0)
        return {
            "replicas": len(self.hosts),
            "healthy": healthy,
            "breaker_open": open_,
            "fleet_mbs": round(mbs, 1),
        }


def start_poller(coordinator, ctx, interval: float | None = None):
    """Spawn the fleet poller unless telemetry is off. ``interval`` None
    resolves the tuning default; <= 0 disables everything — no thread, no
    ReplicaHealth buffers, no fleet gauges (callers must gate the import
    of this module on the interval too; see ``FleetCoordinator.run``)."""
    if interval is None:
        interval = DEFAULT_FLEET_TELEMETRY_INTERVAL
    if interval <= 0:
        return None
    return ReplicaPoller(coordinator, ctx, interval=interval).start()
