"""Observability subsystem: per-scan trace contexts, span histograms,
stall attribution, Chrome-trace/metrics export, and a Prometheus registry.

Replaces the old ``trace.py`` flat global span table (kept as a thin compat
shim). The design borrows the two instrumentation surfaces a training/
inference stack leans on:

- **Dapper-style span trees** (:class:`TraceContext`): every span carries a
  trace id, span id, and parent span id. Contexts are carried in a
  contextvar — ``commands.run`` and ``ScanServer.scan`` each enter a fresh
  one — so concurrent server-mode scans record into disjoint tables instead
  of interleaving into one process-global dict. Worker threads that outlive
  the contextvar (the secret scanner's device thread, the confirm pool)
  re-enter the parent scan's context with :func:`activate`.
- **JAX-profiler-style stage tracks**: spans are exportable as Chrome
  trace-event JSON (:mod:`trivy_tpu.obs.export`, loadable in Perfetto) with
  one track per pipeline stage and device stream, and aggregate to
  p50/p95/max histograms plus a per-pipeline stall-attribution verdict
  (:mod:`trivy_tpu.obs.stall`) — ``feed-starved 72% / device-bound 18% /
  confirm-bound 10%`` — so perf rounds can pick targets from attribution,
  not totals.

Disabled contexts cost one attribute check per span site (the acceptance
bar: < 1% overhead with tracing off).
"""

from __future__ import annotations

import contextvars
import itertools
import os
import random
import sys
import threading
import time
from contextlib import contextmanager

__all__ = [
    "Span",
    "TraceContext",
    "activate",
    "add",
    "count",
    "current",
    "disable",
    "enable",
    "enabled",
    "health_count",
    "heartbeat",
    "note_scan_degraded",
    "parse_traceparent",
    "report",
    "sample",
    "scan_context",
    "span",
    "traceparent",
]

# span ids are seeded with 40 random bits per process so spans from a
# client and a remote server joined into one trace don't collide (a pid
# seed would: containerized client and server are both pid 1), keeping
# parent/child links in a merged export unambiguous; ids stay < 2**64 so
# the traceparent %016x rendering never truncates
_span_ids = itertools.count(
    (int.from_bytes(os.urandom(5), "big") << 24) + 1
)

# raw span-event cap per context: aggregates (histograms, counters, stall
# attribution) never drop, but the per-event list backing the Chrome trace
# export is bounded so a multi-million-file scan cannot hold every event.
# Exports report ``dropped_events`` — truncation is never silent.
MAX_EVENTS = 200_000
# per-stage percentile reservoir: running count/total/max are exact for any
# span volume; p50/p95 come from a uniform reservoir sample (Algorithm R)
# so a 10M-file traced scan holds a few thousand floats per stage, not
# tens of millions
RESERVOIR = 8192
# per-name cap on raw sample() observations (queue depths): running
# count/sum/max stay exact past it
MAX_SAMPLES = 8192


class _StageAgg:
    """Running per-stage duration aggregate: exact count/total/max plus a
    bounded uniform reservoir for percentile estimation, and the set of
    recording thread idents (stall attribution normalizes concurrent-pool
    stages by it)."""

    __slots__ = ("count", "total", "vmax", "values", "threads")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmax = 0.0
        self.values: list[float] = []
        self.threads: set[int] = set()

    def add(self, dur: float, thread: int) -> None:
        self.count += 1
        self.total += dur
        if dur > self.vmax:
            self.vmax = dur
        if len(self.values) < RESERVOIR:
            self.values.append(dur)
        else:
            i = random.randrange(self.count)
            if i < RESERVOIR:
                self.values[i] = dur
        self.threads.add(thread)


class Span:
    """One finished (or in-flight) timed region."""

    __slots__ = ("name", "span_id", "parent_id", "start", "duration", "thread")

    def __init__(self, name, span_id, parent_id, start, duration, thread):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start  # perf_counter at entry
        self.duration = duration  # seconds
        self.thread = thread  # recording thread ident

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration": self.duration,
            "thread": self.thread,
        }


class _NullSpan:
    """Shared no-op context manager returned when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()

# flight-recorder span hook (trivy_tpu/obs/recorder.py): installed once at
# recorder import when TRIVY_TPU_FLIGHT_RECORDER is on, so span boundaries
# above the recorder's latency floor land in the black-box ring. One global
# None-check per recorded span when off.
_flight_hook = None


class _SpanCM:
    __slots__ = ("ctx", "name", "sp")

    def __init__(self, ctx: "TraceContext", name: str):
        self.ctx = ctx
        self.name = name

    def __enter__(self) -> Span:
        ctx = self.ctx
        stack = ctx._stack()
        # a root span of a joined trace parents to the remote caller's span
        parent = stack[-1].span_id if stack else ctx.parent_span_id
        sp = Span(
            self.name,
            next(_span_ids),
            parent,
            time.perf_counter(),
            0.0,
            threading.get_ident(),
        )
        stack.append(sp)
        self.sp = sp
        return sp

    def __exit__(self, *exc) -> bool:
        sp = self.sp
        sp.duration = time.perf_counter() - sp.start
        stack = self.ctx._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        self.ctx._record(sp)
        return False


class TraceContext:
    """Per-scan span table: raw events (bounded), per-name duration lists,
    integer counters, and numeric samples (queue depths), all thread-safe.

    Span parenting is tracked per recording thread: nested ``span()`` calls
    on one thread chain parent ids; spans from worker threads that entered
    via :func:`activate` parent to whatever is open on *their* stack.

    Cross-process: ``trace_id`` is a W3C-trace-context-shaped 32-hex id.
    A server joining a client's trace passes the incoming ids —
    ``trace_id`` plus ``parent_span_id`` (the client's RPC span), so its
    root spans parent under the caller — and ships its span table back in
    the scan response; the client folds it in with :meth:`ingest_remote`
    so one export carries both sides of the wire.
    """

    def __init__(self, name: str = "scan", enabled: bool = False,
                 trace_id: str | None = None,
                 parent_span_id: int | None = None):
        self.name = name
        self.trace_id = trace_id or os.urandom(16).hex()
        self.parent_span_id = parent_span_id
        self.enabled = enabled
        self.created = time.perf_counter()
        self.created_wall = time.time()
        self._lock = threading.Lock()
        self.events: list[Span] = []
        self.dropped_events = 0
        self.durations: dict[str, _StageAgg] = {}
        self.counters: dict[str, int] = {}
        # name -> [count, sum, max, bounded raw values]
        self.samples: dict[str, list] = {}
        # scan-health events (degradations, skipped files): recorded even
        # with tracing off — they feed the report summary, not the trace
        self.health: dict[str, int] = {}
        # serialized remote context docs (export.context_doc) joined into
        # this trace — a server's half of a client-mode scan
        self.remote: list[dict] = []
        # per-rule / per-bucket cost profile, created lazily by profile()
        self._profile = None
        # live telemetry (obs/timeseries.py): the scan's bounded time
        # series, set by an attached Sampler; None on unsampled scans
        self.timeseries = None
        # tuning surface (trivy_tpu/tuning.py): the resolved knob config
        # (a plain dict, set by the command layer) and the attached online
        # controller (exposes .doc(), set by the pipeline when the
        # controller is on) — tuning_doc() merges both for export
        self.tuning: dict | None = None
        self.tuning_controller = None
        # compressed-feed wire accounting (trivy_tpu/secret/compress.py):
        # run-level compression ratio + byte counters, set by the scan run
        # on close when the codec is active; None on uncompressed scans so
        # exports show no empty block
        self.wire: dict | None = None
        # fleet telemetry (trivy_tpu/fleet/telemetry.py): the per-replica
        # health doc attached at poller stop, plus the coordinator's
        # heartbeat-status and --live-fragment callables — all None on
        # non-fleet scans so exports show no empty block
        self.fleet: dict | None = None
        self.fleet_status = None
        self.fleet_live = None
        # always-on scan progress (bytes/files walked vs scanned), created
        # lazily by progress() — like health, NOT gated on `enabled`
        self._progress = None
        # telemetry probes: cheap callables returning {series: value},
        # registered by pipeline internals (feed arena, dispatch layer) and
        # polled only while a sampler thread is attached
        self._probes: list = []
        self._local = threading.local()

    # -- recording ----------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, sp: Span) -> None:
        with self._lock:
            agg = self.durations.get(sp.name)
            if agg is None:
                agg = self.durations[sp.name] = _StageAgg()
            agg.add(sp.duration, sp.thread)
            if len(self.events) < MAX_EVENTS:
                self.events.append(sp)
            else:
                self.dropped_events += 1
        hook = _flight_hook
        if hook is not None:
            hook(self, sp)

    def span(self, name: str):
        """Context manager timing a block under ``name``; no-op when off."""
        if not self.enabled:
            return _NULL
        return _SpanCM(self, name)

    def add(self, name: str, seconds: float) -> None:
        """Record an externally-timed duration as a span ending now."""
        if not self.enabled:
            return
        self._record(
            Span(
                name,
                next(_span_ids),
                self.parent_span_id,
                time.perf_counter() - seconds,
                seconds,
                threading.get_ident(),
            )
        )

    def current_span_id(self) -> int | None:
        """The innermost open span on the calling thread (the parent a
        child process should link under), falling back to this context's
        own inherited parent."""
        stack = getattr(self._local, "stack", None)
        if stack:
            return stack[-1].span_id
        return self.parent_span_id

    def profile(self):
        """This scan's per-rule/per-bucket cost profile
        (:class:`trivy_tpu.obs.profile.ScanProfile`), created lazily.
        Pipelines guard recording on ``self.enabled`` themselves."""
        from trivy_tpu.obs.profile import ScanProfile

        with self._lock:
            if self._profile is None:
                self._profile = ScanProfile()
            return self._profile

    def progress(self):
        """This scan's :class:`trivy_tpu.obs.timeseries.ScanProgress`,
        created lazily. Always-on like the health channel: the progress
        API and heartbeat must work on untraced scans, and the cost is a
        lock + integer adds per file."""
        from trivy_tpu.obs.timeseries import ScanProgress

        with self._lock:
            if self._progress is None:
                self._progress = ScanProgress()
            return self._progress

    def progress_peek(self):
        """The progress tracker if any producer created one, else None —
        readers (heartbeat, sampler, --live) must not conjure an empty
        tracker that would then report a scan at 0% forever."""
        return self._progress

    def add_probe(self, fn) -> None:
        """Register a telemetry probe: a cheap callable returning a
        ``{series_name: float}`` dict. Names ending ``_total`` are
        cumulative counters (the sampler derives rates); everything else
        is an instantaneous gauge. Registration is O(1) and unconditional;
        the probe is only ever *called* by an attached sampler thread."""
        with self._lock:
            self._probes.append(fn)

    def remove_probe(self, fn) -> None:
        with self._lock:
            if fn in self._probes:
                self._probes.remove(fn)

    def probe_values(self) -> dict[str, float]:
        """Merged snapshot of every registered probe. A probe that raises
        (e.g. mid-teardown of a degrading pipeline) is skipped — telemetry
        must never take the scan down with it."""
        with self._lock:
            probes = list(self._probes)
        out: dict[str, float] = {}
        for fn in probes:
            try:
                out.update(fn())
            except Exception:
                pass
        return out

    def ingest_remote(self, doc: dict) -> None:
        """Join a remote scan's serialized context
        (:func:`trivy_tpu.obs.export.context_doc`) into this trace: its
        tracks ride the same Chrome-trace export, its stage totals feed the
        unified stall verdict, and its profile merges into this scan's."""
        if not isinstance(doc, dict):
            return
        with self._lock:
            self.remote.append(doc)

    def remote_stage_totals(self) -> dict[str, tuple[float, int]]:
        """Stage totals of every joined remote context, with the pipeline
        component prefixed ``server:`` so the stall verdict reports e.g.
        ``server:driver`` and ``server:secret`` lines distinct from the
        local pipelines."""
        with self._lock:
            docs = list(self.remote)
        out: dict[str, tuple[float, int]] = {}
        for doc in docs:
            for name, s in (doc.get("spans") or {}).items():
                key = f"server:{name}"
                total, threads = out.get(key, (0.0, 0))
                out[key] = (
                    total + float(s.get("total", 0.0)),
                    max(threads, int(s.get("threads", 1))),
                )
        return out

    def tuning_doc(self) -> dict | None:
        """The scan's tuning state for export: the resolved knob config
        (with per-knob provenance) plus — when an online controller is
        attached — its decision log snapshot. None when neither exists, so
        pre-tuning consumers see no empty block."""
        ctl = self.tuning_controller
        if self.tuning is None and ctl is None:
            return None
        doc = dict(self.tuning or {})
        if ctl is not None:
            try:
                doc["controller"] = ctl.doc()
            except Exception:  # a dying controller must not kill export
                pass
        return doc

    def merged_profile_dict(self) -> dict:
        """Local profile plus every joined remote profile as one dict —
        what ``--profile-out`` writes and the report table renders."""
        from trivy_tpu.obs.profile import ScanProfile

        with self._lock:
            local = self._profile
            docs = list(self.remote)
        merged = ScanProfile()
        if local is not None:
            merged.merge_dict(local.to_dict())
        for doc in docs:
            p = doc.get("profile")
            if p:
                merged.merge_dict(p)
        return merged.to_dict()

    def count(self, name: str, n: int = 1) -> None:
        """Accumulate an integer counter (byte/item tallies)."""
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def sample(self, name: str, value: float) -> None:
        """Record one observation of a fluctuating quantity (queue depth,
        in-flight count); count/sum/max stay exact, raw values are bounded."""
        if not self.enabled:
            return
        value = float(value)
        with self._lock:
            s = self.samples.get(name)
            if s is None:
                s = self.samples[name] = [0, 0.0, value, []]
            s[0] += 1
            s[1] += value
            if value > s[2]:
                s[2] = value
            if len(s[3]) < MAX_SAMPLES:
                s[3].append(value)

    def health_count(self, name: str, n: int = 1) -> None:
        """Accumulate a scan-health event (``scan.degraded``,
        ``walk.skipped``, ``cache.degraded``). Unlike :meth:`count` this is
        NOT gated on ``enabled`` — degradations must reach the report
        summary even on untraced scans. A few events per scan, so the
        always-on cost is a dict increment."""
        with self._lock:
            self.health[name] = self.health.get(name, 0) + n

    def health_snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self.health)

    def reset(self) -> None:
        with self._lock:
            self.events.clear()
            self.dropped_events = 0
            self.durations.clear()
            self.counters.clear()
            self.samples.clear()
            self.health.clear()
            self.remote.clear()
            self._profile = None
            self._progress = None
            self._probes.clear()
            self.timeseries = None
            self.tuning = None
            self.tuning_controller = None
            self.wire = None
            if getattr(self, "_flight_ring", None) is not None:
                self._flight_ring = None

    # -- aggregation --------------------------------------------------------

    def snapshot(self) -> dict[str, list[float]]:
        """Copy of the per-name duration values. Bounded: past RESERVOIR
        spans per stage this is a uniform sample, not the full list — use
        :meth:`stage_totals` / :meth:`stage_stats` for exact totals."""
        with self._lock:
            return {k: list(v.values) for k, v in self.durations.items()}

    def stage_totals(self) -> dict[str, tuple[float, int]]:
        """name -> (exact total seconds, distinct recording threads)."""
        with self._lock:
            return {
                k: (v.total, len(v.threads))
                for k, v in self.durations.items()
                if v.count
            }

    def stage_stats(self) -> dict[str, dict[str, float]]:
        """name -> {count, total, mean, p50, p95, max} in seconds.
        count/total/mean/max are exact; p50/p95 come from the reservoir."""
        with self._lock:
            aggs = {
                k: (v.count, v.total, v.vmax, list(v.values))
                for k, v in self.durations.items()
            }
        out = {}
        for name, (count, total, vmax, values) in sorted(aggs.items()):
            if not count:
                continue
            out[name] = {
                "count": count,
                "total": total,
                "mean": total / count,
                "p50": percentile(values, 50),
                "p95": percentile(values, 95),
                "max": vmax,
            }
        return out

    # -- reporting ----------------------------------------------------------

    def report(self, out=None) -> None:
        """Aggregate span table (count / total / mean / p50 / p95 / max),
        widest totals first, then counters and queue-depth samples, then the
        per-pipeline stall-attribution verdict."""
        if not self.enabled:
            return
        out = out or sys.stderr
        stats = self.stage_stats()
        with self._lock:
            counters = sorted(self.counters.items())
            samples = {
                k: (v[0], v[1], v[2]) for k, v in sorted(self.samples.items())
            }
            remote_docs = list(self.remote)
        # joined remote (server-side) stages render in the same table,
        # prefixed "server:", so one report covers both sides of the wire
        for doc in remote_docs:
            for name, s in sorted((doc.get("spans") or {}).items()):
                agg = wire_span_stats(s)
                if agg["count"]:
                    stats[f"server:{name}"] = agg
            for name, value in sorted((doc.get("counters") or {}).items()):
                counters.append((f"server:{name}", value))
        prof_doc = self.merged_profile_dict()
        has_profile = bool(prof_doc.get("rules") or prof_doc.get("buckets")
                           or prof_doc.get("fleet"))
        if not stats and not counters and not samples and not has_profile:
            return
        rows = sorted(stats.items(), key=lambda kv: -kv[1]["total"])
        out.write("\n-- trace " + "-" * 71 + "\n")
        if rows:
            out.write(
                f"{'span':<34}{'count':>7}{'total':>10}{'mean':>9}"
                f"{'p50':>9}{'p95':>9}{'max':>9}\n"
            )
            for name, s in rows:
                out.write(
                    f"{name:<34}{s['count']:>7}{s['total']:>9.3f}s"
                    f"{s['mean']:>8.4f}s{s['p50']:>8.4f}s"
                    f"{s['p95']:>8.4f}s{s['max']:>8.4f}s\n"
                )
        if counters:
            out.write(f"{'counter':<55}{'value':>15}\n")
            for name, value in counters:
                out.write(f"{name:<55}{value:>15}\n")
        if samples:
            out.write(f"{'sample':<40}{'count':>8}{'mean':>10}{'max':>10}\n")
            for name, (count, total, vmax) in samples.items():
                out.write(
                    f"{name:<40}{count:>8}"
                    f"{total / max(1, count):>10.1f}{vmax:>10.1f}\n"
                )
        from trivy_tpu.obs import stall

        lines = stall.verdict_lines(self)
        if lines:
            out.write("-- stall attribution " + "-" * 59 + "\n")
            for line in lines:
                out.write(line + "\n")
        from trivy_tpu.obs import profile as _profile

        prof_lines = _profile.table_lines(prof_doc)
        if prof_lines:
            out.write(
                f"-- hottest rules (top {_profile.TOP_K} by confirm cost) "
                + "-" * 33 + "\n"
            )
            for line in prof_lines:
                out.write(line + "\n")
        fleet_lines = _profile.fleet_table_lines(prof_doc)
        if fleet_lines:
            # fleet efficiency verdict: per-replica busy/idle/stalled/dead
            # buckets (sum 100%) — the distributed twin of the stall verdict
            out.write("-- fleet efficiency " + "-" * 60 + "\n")
            for line in fleet_lines:
                out.write(line + "\n")
        if self.dropped_events:
            out.write(
                f"(note: {self.dropped_events} raw span events dropped past "
                f"the {MAX_EVENTS}-event cap; aggregates above are complete)\n"
            )
        out.write("-" * 80 + "\n")


def percentile(values: list[float], p: float) -> float:
    """Nearest-rank percentile over an unsorted list."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = int(round((p / 100.0) * (len(s) - 1)))
    return s[max(0, min(idx, len(s) - 1))]


def wire_span_stats(s: dict) -> dict:
    """Aggregate one serialized stage entry (a ``context_doc`` ``spans``
    value off the wire) into count/total/mean/p50/p95/max — the single
    place the remote span schema is parsed, shared by :meth:`report` and
    the metrics ``remote`` block."""
    count = int(s.get("count", 0))
    total = float(s.get("total", 0.0))
    values = list(s.get("values") or [])
    return {
        "count": count,
        "total": total,
        "mean": total / count if count else 0.0,
        "p50": percentile(values, 50),
        "p95": percentile(values, 95),
        "max": float(s.get("max", 0.0)),
    }


# -- module-level surface ---------------------------------------------------

# default context: library users who never enter scan_context() (or worker
# threads that never activate() one) record here, preserving the old
# process-global trace.* behavior behind the same API
_default_ctx = TraceContext(name="process")
_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "trivy_tpu_obs_ctx", default=None
)


def current() -> TraceContext:
    """The active trace context (contextvar, falling back to the process
    default)."""
    return _current.get() or _default_ctx


@contextmanager
def scan_context(name: str = "scan", enabled: bool | None = None,
                 trace_id: str | None = None,
                 parent_span_id: int | None = None):
    """Enter a fresh per-scan context. ``enabled=None`` inherits the process
    default's enabled bit (set by :func:`enable` / the ``--trace`` flag).
    ``trace_id``/``parent_span_id`` join an existing distributed trace (a
    server handling a client's ``traceparent``) instead of minting one."""
    ctx = TraceContext(
        name=name,
        enabled=_default_ctx.enabled if enabled is None else enabled,
        trace_id=trace_id,
        parent_span_id=parent_span_id,
    )
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextmanager
def activate(ctx: TraceContext):
    """Re-enter an existing context from a worker thread. Contextvars do not
    propagate into threads started before (or outside) a scan, so pipeline
    worker loops wrap themselves in ``activate(ctx)`` with the context their
    spawner captured."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def enable() -> None:
    """Enable tracing on the process-default context and on future
    ``scan_context(enabled=None)`` scopes (the ``--trace`` flag)."""
    _default_ctx.enabled = True


def disable() -> None:
    _default_ctx.enabled = False


def enabled() -> bool:
    return current().enabled


def span(name: str):
    return current().span(name)


def add(name: str, seconds: float) -> None:
    current().add(name, seconds)


def count(name: str, n: int = 1) -> None:
    current().count(name, n)


def health_count(name: str, n: int = 1) -> None:
    current().health_count(name, n)


def note_scan_degraded() -> None:
    """Record one scan-degradation event everywhere it must surface: the
    always-on health channel (folded into the report's ``Degraded`` flag)
    and the process-global Prometheus counter on ``GET /metrics``. Shared
    by every rung that degrades (device loop, license scorer, backend-init
    fallback) so the two surfaces cannot drift apart."""
    from trivy_tpu.obs import metrics as obs_metrics
    from trivy_tpu.obs import recorder as _recorder

    current().health_count("scan.degraded")
    obs_metrics.REGISTRY.counter(
        "trivy_tpu_scan_degraded_total",
        "Scans that completed on a degraded (host-fallback) path",
    ).inc()
    _recorder.record("degrade", "scan.degraded")


def sample(name: str, value: float) -> None:
    current().sample(name, value)


def report(out=None) -> None:
    current().report(out)


_HEX = set("0123456789abcdef")


def traceparent(span: Span | None = None) -> str:
    """W3C-style ``traceparent`` header for the active context:
    ``00-<32-hex trace id>-<16-hex parent span id>-01``. The parent id is
    ``span``'s (when the caller holds one open) or the calling thread's
    innermost open span; an all-zero parent means "join the trace id, no
    parent link" (tracing off on the client side)."""
    ctx = current()
    sid = span.span_id if span is not None else ctx.current_span_id()
    return f"00-{ctx.trace_id}-{(sid or 0):016x}-01"


def parse_traceparent(value: str | None) -> tuple[str, int | None] | None:
    """``(trace_id, parent_span_id)`` from a traceparent header, or None
    when absent/malformed. A zero parent id maps to None (no parent)."""
    if not value:
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    _ver, tid, pid, _flags = parts
    if len(tid) != 32 or len(pid) != 16:
        return None
    if set(tid) - _HEX or set(pid) - _HEX:
        return None
    if tid == "0" * 32:
        return None
    parent = int(pid, 16)
    return tid, (parent or None)


class heartbeat:
    """Progress logging for long-running operations: while the block runs,
    log one line every ``interval`` seconds (elapsed time, the scan's live
    telemetry summary — progress %, instantaneous MB/s, ETA — plus an
    optional ``progress()`` string) so server operators can tell a long
    scan from a hung one, and roughly *where* it is. Zero threads when the
    block finishes before the first beat fires is not attempted — the
    thread parks on an Event and exits quietly.
    """

    def __init__(self, logger, what: str, interval: float = 30.0, progress=None):
        self.logger = logger
        self.what = what
        self.interval = interval
        self.progress = progress
        self._stop = threading.Event()
        self._t0 = 0.0
        self._last_bytes: tuple[float, int] | None = None
        self._ctx: TraceContext | None = None

    def _telemetry(self) -> str:
        """The scan's live progress (bytes walked vs scanned, MB/s between
        beats, ETA) as one compact fragment, or '' when nothing has
        registered progress yet. The MB/s here is *instantaneous* — the
        delta since the previous beat — so a stalled pipeline shows 0.0
        even when the cumulative average still looks healthy."""
        ctx = self._ctx
        prog = ctx.progress_peek() if ctx is not None else None
        if prog is None:
            return ""
        snap = prog.snapshot()
        now = time.perf_counter()
        mbs = snap["mbs"]
        if self._last_bytes is not None:
            t0, b0 = self._last_bytes
            dt = now - t0
            if dt > 0:
                mbs = (snap["bytes_scanned"] - b0) / dt / (1 << 20)
        self._last_bytes = (now, snap["bytes_scanned"])
        parts = [f"{snap['ratio'] * 100:.1f}%", f"{mbs:.1f} MB/s"]
        if snap.get("eta_s") is not None:
            parts.append(f"ETA {snap['eta_s']:.0f}s")
        # effective-knob fragment: the live values when a controller is
        # adapting them, else the resolved config — so beats from two
        # differently-tuned scans stay comparable in the logs
        knobs = None
        ctl = ctx.tuning_controller if ctx is not None else None
        if ctl is not None:
            try:
                knobs = ctl.adapter.knobs()
            except Exception:
                knobs = None
        elif ctx is not None and isinstance(ctx.tuning, dict):
            cfg = ctx.tuning.get("config") or {}
            if cfg.get("feed_streams") or cfg.get("inflight"):
                knobs = cfg
        if knobs:
            frag = f"knobs s{knobs.get('feed_streams', 0)}" \
                   f"/i{knobs.get('inflight', 0)}"
            if knobs.get("arena_slabs"):
                frag += f"/a{knobs['arena_slabs']}"
            if ctl is not None:
                frag += f" ({len(ctl.decisions)} decisions)"
            parts.append(frag)
        # fleet fragment: the coordinator registers a status callable for
        # the duration of a fan-out (works with the telemetry poller off
        # too — replica health then degrades to breaker state, MB/s is
        # unknown), so a fleet scan's beats carry shard and replica counts
        status = getattr(ctx, "fleet_status", None) if ctx is not None \
            else None
        if status is not None:
            try:
                st = status()
                frag = (
                    f"fleet {st['shards_done']}/{st['shards_total']} "
                    f"shards, {st['healthy']}/{st['replicas']} healthy"
                )
                if st.get("breaker_open"):
                    frag += f", {st['breaker_open']} open"
                if st.get("fleet_mbs") is not None:
                    frag += f", {st['fleet_mbs']:.1f} MB/s"
                parts.append(frag)
            except Exception:
                pass
        # device fragment (flight recorder): compile count with per-beat
        # delta and HBM residency; a recompile storm since the previous
        # beat surfaces here immediately
        try:
            from trivy_tpu.obs import recorder as _recorder

            frag = _recorder.heartbeat_fragment(self)
            if frag:
                parts.append(frag)
        except Exception:
            pass
        return " [" + ", ".join(parts) + "]"

    def _loop(self) -> None:
        # the beat thread re-enters the spawning scan's context so the log
        # line (and the json formatter's trace_id field) correlates with
        # the client trace that caused this work
        with activate(self._ctx or _default_ctx):
            while not self._stop.wait(self.interval):
                extra = ""
                if self.progress is not None:
                    try:
                        extra = f" ({self.progress()})"
                    except Exception:
                        pass
                try:
                    extra = self._telemetry() + extra
                except Exception:
                    pass
                self.logger.info(
                    "%s in progress: %.0fs elapsed%s [trace %s]",
                    self.what,
                    time.perf_counter() - self._t0,
                    extra,
                    self._ctx.trace_id if self._ctx else "-",
                )

    def __enter__(self) -> "heartbeat":
        self._t0 = time.perf_counter()
        self._ctx = current()
        threading.Thread(target=self._loop, daemon=True).start()
        return self

    def __exit__(self, *exc) -> bool:
        self._stop.set()
        return False
