"""Minimal Prometheus metric registry (text exposition format 0.0.4).

Counters, gauges, and histograms with optional labels, rendered by
:meth:`Registry.render` for the scan server's ``GET /metrics``. No external
client library — the container pins its dependency set — and the subset
here (no summaries, no exemplars, no timestamps) is everything the server
surface needs: scan counts, per-stage latency histograms, cache hit/miss,
dedup bytes, and an in-flight gauge.

:func:`parse_text` is the renderer's inverse: the fleet telemetry poller
scrapes each replica's ``GET /metrics`` and parses the exposition text back
into typed samples. Parser and renderer are property-tested as a round
trip, which pins the label-value escaping rules (``\\`` first, then ``"``
and newline) on both sides.
"""

from __future__ import annotations

import re
import threading

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# prometheus default latency buckets (seconds) — right for RPC requests
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# whole-scan / pipeline-stage buckets: scans of large corpora run minutes
# (the north-star itself is ~60 s), so the ladder must resolve well past
# the request-latency range or every observation lands in +Inf
SCAN_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    1800.0, 3600.0,
)


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    # HELP lines escape only backslash and newline (the exposition format's
    # rule — quotes stay literal there, unlike label values); an unescaped
    # newline in help text used to split the line and corrupt every metric
    # rendered after it
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labelnames: tuple[str, ...], labelvalues: tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(
        f'{n}="{_escape(str(v))}"' for n, v in zip(labelnames, labelvalues)
    )
    return "{" + pairs + "}"


def _fmt_value(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, kwargs: dict) -> tuple[str, ...]:
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kwargs)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(kwargs[n]) for n in self.labelnames)


class _ValueMetric(_Metric):
    """Shared per-label-set scalar storage for counters and gauges."""

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def collect(self) -> dict[tuple[str, ...], float]:
        """Snapshot of every recorded label set -> value (label values in
        declared ``labelnames`` order). Lets decision logic — the admission
        controller reading breaker/utilization gauges — consume live state
        without parsing the text exposition."""
        with self._lock:
            return dict(self._values)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_fmt_labels(self.labelnames, k)} {_fmt_value(v)}"
            for k, v in items
        ] or ([f"{self.name} 0"] if not self.labelnames else [])


class Counter(_ValueMetric):
    kind = "counter"


class Gauge(_ValueMetric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def setdefault(self, value: float, **labels) -> None:
        """Register a label row only if absent — initializers (a new
        CircuitBreaker publishing healthy rows) must not clobber live
        state another writer already holds under the same labels."""
        with self._lock:
            self._values.setdefault(self._key(labels), float(value))

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def remove(self, **labels) -> None:
        """Retire one label set (e.g. a finished scan's ``trace`` label)
        so per-scan labels can't grow gauge cardinality without bound."""
        with self._lock:
            self._values.pop(self._key(labels), None)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))
        # per label-set: [per-bucket counts..., +Inf count], sum
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] += value

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
        for key, counts in items:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                ln = _fmt_labels(self.labelnames + ("le",), key + (str(b),))
                lines.append(f"{self.name}_bucket{ln} {cum}")
            cum += counts[-1]
            ln = _fmt_labels(self.labelnames + ("le",), key + ("+Inf",))
            lines.append(f"{self.name}_bucket{ln} {cum}")
            ln = _fmt_labels(self.labelnames, key)
            lines.append(f"{self.name}_sum{ln} {_fmt_value(sums[key])}")
            lines.append(f"{self.name}_count{ln} {cum}")
        return lines


class Registry:
    """Named metric collection; get-or-create accessors are idempotent so
    call sites need no registration ceremony."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name} already registered as {m.kind}")
            elif tuple(labelnames) != m.labelnames:
                # a silent get-or-create here would hand back an instrument
                # whose inc()/set() then fails far from the offending
                # registration — duplicate registration under a different
                # shape must be loud at the registration site
                raise ValueError(
                    f"metric {name} already registered with labels "
                    f"{list(m.labelnames)}, not {list(labelnames)}"
                )
            elif "buckets" in kw and tuple(sorted(kw["buckets"])) != m.buckets:
                raise ValueError(
                    f"histogram {name} already registered with different "
                    f"buckets"
                )
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def render(self) -> str:
        out = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            if m.help:
                out.append(f"# HELP {name} {_escape_help(m.help)}")
            out.append(f"# TYPE {name} {m.kind}")
            out.extend(m.render())
        return "\n".join(out) + "\n"


# process-global registry for callers without a server-scoped one
REGISTRY = Registry()


# -- exposition-text parser (the renderer's inverse) --------------------------

_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")


def _unescape(value: str) -> str:
    """Inverse of :func:`_escape` (label values: ``\\\\``, ``\\"``,
    ``\\n``); an unknown escape keeps the backslash literally, matching
    the Prometheus reference parser's tolerance."""
    if "\\" not in value:
        return value
    out = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _unescape_help(value: str) -> str:
    """Inverse of :func:`_escape_help` (``\\\\`` and ``\\n`` only)."""
    if "\\" not in value:
        return value
    out = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


class ParseError(ValueError):
    """A line the exposition grammar cannot account for. Loud by design:
    a half-parsed scrape silently missing gauges would feed the fleet
    headroom scorer fabricated zeros."""


class ParsedMetric:
    """One metric family from a parsed exposition: declared ``kind`` /
    ``help`` (from TYPE/HELP lines; ``untyped``/empty when undeclared) and
    every sample line as ``(labels dict, value)`` pairs under the sample's
    full name (histograms surface as their ``_bucket``/``_sum``/``_count``
    series)."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str = "untyped", help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        self.samples: list[tuple[dict, float]] = []

    def value(self, **labels) -> float | None:
        """First sample whose labels equal ``labels`` exactly, or None."""
        want = {k: str(v) for k, v in labels.items()}
        for lbl, v in self.samples:
            if lbl == want:
                return v
        return None

    def first(self) -> float | None:
        return self.samples[0][1] if self.samples else None

    def max(self) -> float | None:
        return max((v for _, v in self.samples), default=None)

    def sum(self) -> float:
        return sum(v for _, v in self.samples)


def _parse_labels(text: str, line: str) -> tuple[dict, str]:
    """Parse ``{name="value",...}`` off the front of ``text`` (label
    values honor the escape rules); returns (labels, remainder)."""
    labels: dict[str, str] = {}
    i = 1  # past '{'
    n = len(text)
    while True:
        if i >= n:
            raise ParseError(f"unterminated label set: {line!r}")
        if text[i] == "}":
            i += 1
            break
        m = _LABEL_NAME_RE.match(text, i)
        if m is None:
            raise ParseError(f"bad label name at col {i}: {line!r}")
        lname = m.group(0)
        i = m.end()
        if not text.startswith('="', i):
            raise ParseError(f"expected '=\"' after label {lname}: {line!r}")
        i += 2
        buf = []
        while True:
            if i >= n:
                raise ParseError(f"unterminated label value: {line!r}")
            c = text[i]
            if c == "\\" and i + 1 < n:
                buf.append(text[i:i + 2])
                i += 2
                continue
            if c == '"':
                i += 1
                break
            buf.append(c)
            i += 1
        labels[lname] = _unescape("".join(buf))
        if i < n and text[i] == ",":
            i += 1
    return labels, text[i:]


def parse_text(text: str) -> dict[str, ParsedMetric]:
    """Parse exposition text back into metric families — the inverse of
    :meth:`Registry.render`, used by the fleet poller on scraped replica
    ``/metrics`` bodies. The result is keyed by sample name; TYPE/HELP
    declarations attach kind/help to their family (and histogram
    ``_bucket``/``_sum``/``_count`` samples inherit the base family's
    kind). A malformed line raises :class:`ParseError`. Two registries
    concatenated into one scrape (the server renders its own plus the
    process-global one) parse fine: duplicate TYPE/HELP redeclarations are
    tolerated, samples accumulate."""
    declared: dict[str, tuple[str, str]] = {}  # name -> (kind, help)
    out: dict[str, ParsedMetric] = {}

    def family(name: str) -> ParsedMetric:
        fam = out.get(name)
        if fam is None:
            # histogram series inherit the base declaration
            kind, hlp = declared.get(name, ("", ""))
            if not kind:
                for suffix in ("_bucket", "_sum", "_count"):
                    if name.endswith(suffix):
                        base = name[: -len(suffix)]
                        bkind, bhelp = declared.get(base, ("", ""))
                        if bkind == "histogram":
                            kind, hlp = bkind, bhelp
                        break
            fam = out[name] = ParsedMetric(name, kind or "untyped", hlp)
        return fam

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                kind = parts[3] if len(parts) > 3 else "untyped"
                prev = declared.get(parts[2], ("", ""))
                declared[parts[2]] = (kind, prev[1])
                if parts[2] in out:
                    out[parts[2]].kind = kind
            elif len(parts) >= 3 and parts[1] == "HELP":
                hlp = _unescape_help(parts[3]) if len(parts) > 3 else ""
                prev = declared.get(parts[2], ("", ""))
                declared[parts[2]] = (prev[0], hlp)
                if parts[2] in out:
                    out[parts[2]].help = hlp
            # other comments are ignored per the format
            continue
        m = _METRIC_NAME_RE.match(line)
        if m is None:
            raise ParseError(f"bad sample line: {line!r}")
        name = m.group(0)
        rest = line[m.end():]
        labels: dict[str, str] = {}
        if rest.startswith("{"):
            labels, rest = _parse_labels(rest, line)
        value_str = rest.split()[0] if rest.split() else ""
        try:
            value = float(value_str)
        except ValueError:
            raise ParseError(
                f"bad sample value {value_str!r}: {line!r}"
            ) from None
        family(name).samples.append((labels, value))
    for name, (kind, hlp) in declared.items():
        # a declared family with no samples still parses (labeled metric
        # with zero label sets renders TYPE-only)
        if name not in out:
            out[name] = ParsedMetric(name, kind or "untyped", hlp)
        elif hlp and not out[name].help:
            out[name].help = hlp
    return out
