"""Minimal Prometheus metric registry (text exposition format 0.0.4).

Counters, gauges, and histograms with optional labels, rendered by
:meth:`Registry.render` for the scan server's ``GET /metrics``. No external
client library — the container pins its dependency set — and the subset
here (no summaries, no exemplars, no timestamps) is everything the server
surface needs: scan counts, per-stage latency histograms, cache hit/miss,
dedup bytes, and an in-flight gauge.
"""

from __future__ import annotations

import threading

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# prometheus default latency buckets (seconds) — right for RPC requests
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# whole-scan / pipeline-stage buckets: scans of large corpora run minutes
# (the north-star itself is ~60 s), so the ladder must resolve well past
# the request-latency range or every observation lands in +Inf
SCAN_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    1800.0, 3600.0,
)


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labelnames: tuple[str, ...], labelvalues: tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(
        f'{n}="{_escape(str(v))}"' for n, v in zip(labelnames, labelvalues)
    )
    return "{" + pairs + "}"


def _fmt_value(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, kwargs: dict) -> tuple[str, ...]:
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kwargs)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(kwargs[n]) for n in self.labelnames)


class _ValueMetric(_Metric):
    """Shared per-label-set scalar storage for counters and gauges."""

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def collect(self) -> dict[tuple[str, ...], float]:
        """Snapshot of every recorded label set -> value (label values in
        declared ``labelnames`` order). Lets decision logic — the admission
        controller reading breaker/utilization gauges — consume live state
        without parsing the text exposition."""
        with self._lock:
            return dict(self._values)

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_fmt_labels(self.labelnames, k)} {_fmt_value(v)}"
            for k, v in items
        ] or ([f"{self.name} 0"] if not self.labelnames else [])


class Counter(_ValueMetric):
    kind = "counter"


class Gauge(_ValueMetric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def setdefault(self, value: float, **labels) -> None:
        """Register a label row only if absent — initializers (a new
        CircuitBreaker publishing healthy rows) must not clobber live
        state another writer already holds under the same labels."""
        with self._lock:
            self._values.setdefault(self._key(labels), float(value))

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def remove(self, **labels) -> None:
        """Retire one label set (e.g. a finished scan's ``trace`` label)
        so per-scan labels can't grow gauge cardinality without bound."""
        with self._lock:
            self._values.pop(self._key(labels), None)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))
        # per label-set: [per-bucket counts..., +Inf count], sum
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] += value

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
        for key, counts in items:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                ln = _fmt_labels(self.labelnames + ("le",), key + (str(b),))
                lines.append(f"{self.name}_bucket{ln} {cum}")
            cum += counts[-1]
            ln = _fmt_labels(self.labelnames + ("le",), key + ("+Inf",))
            lines.append(f"{self.name}_bucket{ln} {cum}")
            ln = _fmt_labels(self.labelnames, key)
            lines.append(f"{self.name}_sum{ln} {_fmt_value(sums[key])}")
            lines.append(f"{self.name}_count{ln} {cum}")
        return lines


class Registry:
    """Named metric collection; get-or-create accessors are idempotent so
    call sites need no registration ceremony."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name} already registered as {m.kind}")
            elif tuple(labelnames) != m.labelnames:
                # a silent get-or-create here would hand back an instrument
                # whose inc()/set() then fails far from the offending
                # registration — duplicate registration under a different
                # shape must be loud at the registration site
                raise ValueError(
                    f"metric {name} already registered with labels "
                    f"{list(m.labelnames)}, not {list(labelnames)}"
                )
            elif "buckets" in kw and tuple(sorted(kw["buckets"])) != m.buckets:
                raise ValueError(
                    f"histogram {name} already registered with different "
                    f"buckets"
                )
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def render(self) -> str:
        out = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            if m.help:
                out.append(f"# HELP {name} {m.help}")
            out.append(f"# TYPE {name} {m.kind}")
            out.extend(m.render())
        return "\n".join(out) + "\n"


# process-global registry for callers without a server-scoped one
REGISTRY = Registry()
