"""Continuous scan telemetry: a per-scan time-series sampler.

Every other observability surface here is post-hoc — the stall verdict,
the per-rule profile, and the Perfetto timeline all materialize after the
scan finishes. This module is the live half: a background sampler thread
per scan snapshots in-flight pipeline state on a fixed interval (default
250 ms, knob ``--telemetry-interval``, 0 = off) into bounded ring buffers,
the Perfetto/Prometheus *counter track* model from the tracing literature.

What gets sampled (each producer registers a cheap probe on the scan's
:class:`~trivy_tpu.obs.TraceContext`; the sampler merges them per tick):

- arena occupancy (``secret.arena_free_slabs`` — the snapshot the feed
  path always computed but never exported live)
- per-transfer-stream in-flight window depth, feeder/confirm queue depths
- per-device busy fraction (``device.dN.busy_ratio``), derived from the
  dispatch/fetch busy-interval accounting in :mod:`trivy_tpu.parallel.mesh`
- instantaneous link bandwidth (``secret.link_mbs`` =
  Δ``bytes_uploaded``/Δt)
- scan progress (:class:`ScanProgress`: bytes/files walked vs scanned)

The series land in four places: Perfetto **counter tracks** appended to
``--trace-out`` timelines, per-scan JSON via ``--timeseries-out``, live
Prometheus gauges on ``GET /metrics`` (``trivy_tpu_link_mbs``,
``trivy_tpu_device_busy_ratio{device=}``, ``trivy_tpu_arena_free_slabs``,
``trivy_tpu_scan_progress_ratio{trace=}``), and the scan server's
``GET /scan/<trace_id>/progress`` API plus the ``--live`` CLI line.

Zero-cost-when-off: no sampler thread spawns unless telemetry is enabled
(``start_sampler`` returns None for interval 0), probes are registered but
never called, and :class:`ScanProgress` costs one lock+add per *file* —
the always-on budget the health channel already set. ``bench.py --smoke``
enforces both properties (no sampler thread on untraced reps, measured
overhead bound).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque

from trivy_tpu import log

logger = log.logger("obs:timeseries")

# default sampling cadence; --telemetry-interval / TRIVY_TPU_TELEMETRY_INTERVAL
DEFAULT_INTERVAL = 0.25
# per-series point bound: at the default cadence this holds ~17 min of
# samples; older points drop (counted, never silent) so a day-long scan
# cannot hold an unbounded series
RING_CAPACITY = 4096
# bounded per-series points shipped in a context_doc (scan responses ride
# HTTP; the receiver gets a uniform stride, not a biased prefix)
WIRE_POINTS = 512

# cumulative-counter series (names ending _total) derive a rate series per
# tick; these two shapes get friendly names instead of the generic
# "<base>_per_s" (link bandwidth in MB/s, busy-seconds-per-second = ratio)
_LINK_COUNTER = "secret.bytes_uploaded_total"
_LINK_SERIES = "secret.link_mbs"
_BUSY_RE = re.compile(r"^device\.(d\w+)\.busy_seconds_total$")


def default_interval() -> float:
    """Sampler cadence from ``TRIVY_TPU_TELEMETRY_INTERVAL`` (seconds),
    falling back to :data:`DEFAULT_INTERVAL`; 0 disables. Negative, NaN,
    infinite, or non-numeric values raise — a silently-swallowed typo used
    to hand the sampler a degenerate cadence (always-default, or a
    busy-spinning thread) the user only saw in the symptoms."""
    raw = os.environ.get("TRIVY_TPU_TELEMETRY_INTERVAL", "")
    if raw:
        from trivy_tpu.tuning import validate_interval

        return validate_interval(raw, "TRIVY_TPU_TELEMETRY_INTERVAL")
    return DEFAULT_INTERVAL


class RingBuffer:
    """Bounded (t, value) series: append drops the oldest point past
    ``capacity`` and counts the drop — truncation is never silent."""

    __slots__ = ("points", "dropped", "capacity")

    def __init__(self, capacity: int = RING_CAPACITY):
        self.capacity = max(1, capacity)
        self.points: deque[tuple[float, float]] = deque(maxlen=self.capacity)
        self.dropped = 0

    def append(self, t: float, value: float) -> None:
        if len(self.points) == self.capacity:
            self.dropped += 1
        self.points.append((t, value))

    def __len__(self) -> int:
        return len(self.points)


class Timeseries:
    """Named, bounded time series for one scan (thread-safe). Timestamps
    are seconds relative to the owning context's creation, so they align
    with span timestamps in the Chrome-trace export."""

    def __init__(self, capacity: int = RING_CAPACITY):
        self._lock = threading.Lock()
        self._series: dict[str, RingBuffer] = {}
        self._capacity = capacity

    def record(self, name: str, t: float, value: float) -> None:
        with self._lock:
            rb = self._series.get(name)
            if rb is None:
                rb = self._series[name] = RingBuffer(self._capacity)
            rb.append(t, float(value))

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, name: str) -> list[tuple[float, float]]:
        with self._lock:
            rb = self._series.get(name)
            return list(rb.points) if rb is not None else []

    def values(self, name: str) -> list[float]:
        return [v for _, v in self.points(name)]

    def latest(self, name: str) -> float | None:
        with self._lock:
            rb = self._series.get(name)
            if rb is None or not rb.points:
                return None
            return rb.points[-1][1]

    def to_doc(self, max_points: int = WIRE_POINTS) -> dict:
        """Wire/JSON form: ``{name: {"points": [[t, v], ...], "dropped"}}``
        with a uniform stride past ``max_points`` (a plain prefix would
        bias consumers toward the scan's warm-up)."""
        with self._lock:
            items = [
                (name, list(rb.points), rb.dropped)
                for name, rb in sorted(self._series.items())
            ]
        out = {}
        for name, pts, dropped in items:
            n = len(pts)
            if n > max_points:
                step = n / max_points
                pts = [pts[int(i * step)] for i in range(max_points)]
                dropped += n - max_points
            out[name] = {
                "points": [[round(t, 4), round(v, 6)] for t, v in pts],
                "dropped": dropped,
            }
        return out

    def summary(self) -> dict:
        """Per-series {count, mean, max, p50, p95} — the aggregate view
        bench embeds (full points ride --timeseries-out)."""
        from trivy_tpu.obs import percentile

        out = {}
        with self._lock:
            items = [(n, [v for _, v in rb.points])
                     for n, rb in sorted(self._series.items())]
        for name, vals in items:
            if not vals:
                continue
            out[name] = {
                "count": len(vals),
                "mean": round(sum(vals) / len(vals), 6),
                "max": round(max(vals), 6),
                "p50": round(percentile(vals, 50), 6),
                "p95": round(percentile(vals, 95), 6),
            }
        return out


class ScanProgress:
    """Always-on progress counters for one scan: bytes/files *walked*
    (discovered by the artifact walk) vs *scanned* (fully processed by the
    analyzer loop / device pipeline). Cheap enough to run untraced — one
    lock + integer adds per file, the same budget as the health channel.

    ``ratio`` is clamped monotonically non-decreasing: the walk can burst
    ahead of scanning (discovering new bytes shrinks the raw quotient),
    but a progress API must never tell a poller the scan went backwards.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.files_walked = 0
        self.bytes_walked = 0
        self.files_scanned = 0
        self.bytes_scanned = 0
        self.walk_complete = False
        self.done = False
        self.started = time.perf_counter()
        self._max_ratio = 0.0
        self.remote: dict | None = None  # latest joined server-side snapshot

    def note_walked(self, nbytes: int, files: int = 1) -> None:
        with self._lock:
            self.files_walked += files
            self.bytes_walked += nbytes

    def note_scanned(self, nbytes: int, files: int = 1) -> None:
        with self._lock:
            self.files_scanned += files
            self.bytes_scanned += nbytes

    def finish_walk(self) -> None:
        with self._lock:
            self.walk_complete = True

    def finish(self) -> None:
        with self._lock:
            self.done = True

    def merge_remote(self, snapshot: dict) -> None:
        """Latest server-side progress of a joined remote scan (client
        mode): kept verbatim so `--live`/heartbeat can show both sides."""
        if isinstance(snapshot, dict):
            with self._lock:
                self.remote = snapshot

    def ratio(self) -> float:
        with self._lock:
            return self._ratio_locked()

    def _ratio_locked(self) -> float:
        if self.done:
            self._max_ratio = 1.0
            return 1.0
        if self.bytes_walked > 0:
            r = self.bytes_scanned / self.bytes_walked
        elif self.files_walked > 0:
            r = self.files_scanned / self.files_walked
        else:
            r = 0.0
        # never 1.0 before finish(): the denominator may still grow before
        # walk_complete, and even with every walked byte scanned there are
        # trailing phases (batched-analyzer finalize, detection, report)
        # the walked/scanned pair doesn't see — 99.9% is the honest cap
        # for a scan that hasn't actually completed
        r = min(r, 0.999)
        if r > self._max_ratio:
            self._max_ratio = r
        return self._max_ratio

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = time.perf_counter() - self.started
            ratio = self._ratio_locked()
            mbs = self.bytes_scanned / elapsed / (1 << 20) if elapsed > 0 else 0.0
            eta = None
            if (
                not self.done
                and self.walk_complete
                and self.bytes_scanned > 0
                and self.bytes_walked > self.bytes_scanned
            ):
                rate = self.bytes_scanned / elapsed
                if rate > 0:
                    eta = (self.bytes_walked - self.bytes_scanned) / rate
            doc = {
                "files_walked": self.files_walked,
                "bytes_walked": self.bytes_walked,
                "files_scanned": self.files_scanned,
                "bytes_scanned": self.bytes_scanned,
                "walk_complete": self.walk_complete,
                "done": self.done,
                "ratio": round(ratio, 6),
                "elapsed_s": round(elapsed, 3),
                "mbs": round(mbs, 3),
                "eta_s": round(eta, 1) if eta is not None else None,
            }
            if self.remote is not None:
                doc["remote"] = self.remote
            return doc


def _registry():
    from trivy_tpu.obs import metrics as obs_metrics

    return obs_metrics.REGISTRY


def live_utilization() -> dict:
    """Snapshot of the process-level utilization gauges this module
    maintains, for decision logic (the admission controller's admit/shed
    rules read exactly these). Keys whose gauge carries no live value are
    ``None`` — the caller must treat "no telemetry" and "telemetry says
    idle" differently (an unsampled vuln-only server is not saturated).

    - ``link_mbs``: instantaneous host->device bandwidth
    - ``busy_max``: max per-device busy fraction across sampled devices
    - ``arena_free``: free slabs in the most recent sampled feed arena
    - ``samplers``: live sampler count (0 = nothing sampling right now)
    """
    reg = _registry()
    link = reg.gauge(
        "trivy_tpu_link_mbs",
        "Instantaneous host->device link bandwidth (MB/s)",
    ).collect()
    arena = reg.gauge(
        "trivy_tpu_arena_free_slabs",
        "Free slabs in the secret feed's chunk arena",
    ).collect()
    busy = reg.gauge(
        "trivy_tpu_device_busy_ratio",
        "Fraction of the last sampling interval the device had "
        "work in flight",
        labelnames=("device",),
    ).collect()
    with _live_lock:
        samplers = _live_samplers
    return {
        "link_mbs": next(iter(link.values()), None),
        "arena_free": next(iter(arena.values()), None),
        "busy_max": max(busy.values()) if busy else None,
        "samplers": samplers,
    }


# live-sampler accounting for the process-level gauges: the unlabeled
# gauges (link MB/s, arena free slabs) and the per-device busy ratios are
# "most recent sampled value in this process" — concurrent scans overwrite
# each other (last writer wins; per-scan series stay exact in each scan's
# ring buffers). When the LAST live sampler stops, the gauges retire so a
# scrape after the fleet goes idle reads 0, not the final scan's last
# value frozen forever (the admission controller reads these).
_live_lock = threading.Lock()
_live_samplers = 0
_busy_devices: set[str] = set()


def _note_sampler_started() -> None:
    global _live_samplers
    with _live_lock:
        _live_samplers += 1


def _note_sampler_stopped() -> None:
    global _live_samplers
    with _live_lock:
        _live_samplers = max(0, _live_samplers - 1)
        if _live_samplers:
            return
        devices = sorted(_busy_devices)
        _busy_devices.clear()
    reg = _registry()
    reg.gauge(
        "trivy_tpu_link_mbs",
        "Instantaneous host->device link bandwidth (MB/s)",
    ).remove()
    reg.gauge(
        "trivy_tpu_arena_free_slabs",
        "Free slabs in the secret feed's chunk arena",
    ).remove()
    busy = reg.gauge(
        "trivy_tpu_device_busy_ratio",
        "Fraction of the last sampling interval the device had "
        "work in flight",
        labelnames=("device",),
    )
    for d in devices:
        busy.remove(device=d)


class Sampler:
    """One scan's background sampler thread.

    Lifecycle mirrors ``obs.heartbeat``: the thread re-enters the spawning
    scan's :class:`TraceContext` (so probe-side ``obs.current()`` calls and
    json log lines correlate), parks on an Event between ticks, and exits
    on :meth:`stop` — which the owning scope calls from a ``finally``, so
    scan death, feed poison, and the degraded host-fallback path all stop
    the thread the same way completion does. A final tick runs at stop so
    the series always carry the end state.
    """

    def __init__(self, ctx, interval: float = DEFAULT_INTERVAL,
                 clock=time.perf_counter):
        self.ctx = ctx
        self.interval = interval
        self.clock = clock
        self.ts = Timeseries()
        ctx.timeseries = self.ts
        self._stop = threading.Event()
        self._last: dict[str, tuple[float, float]] = {}
        self._progress_gauge_set = False
        self._counted_live = False
        self._thread: threading.Thread | None = None

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> None:
        """One tick: merge every probe, record gauge series directly,
        derive rate series for cumulative ``*_total`` counters, fold in
        scan progress, and mirror the headline values to the process
        Prometheus gauges."""
        now = self.clock()
        t = now - self.ctx.created
        vals = self.ctx.probe_values()
        reg = _registry()
        for name, v in vals.items():
            self.ts.record(name, t, v)
            if not name.endswith("_total"):
                continue
            prev = self._last.get(name)
            self._last[name] = (now, v)
            if prev is None:
                continue
            dt = now - prev[0]
            if dt <= 0:
                continue
            rate = max(0.0, (v - prev[1]) / dt)
            m = _BUSY_RE.match(name)
            if name == _LINK_COUNTER:
                mbs = rate / (1 << 20)
                self.ts.record(_LINK_SERIES, t, mbs)
                reg.gauge(
                    "trivy_tpu_link_mbs",
                    "Instantaneous host->device link bandwidth (MB/s)",
                ).set(round(mbs, 3))
            elif m:
                ratio = min(1.0, rate)
                self.ts.record(f"device.{m.group(1)}.busy_ratio", t, ratio)
                reg.gauge(
                    "trivy_tpu_device_busy_ratio",
                    "Fraction of the last sampling interval the device had "
                    "work in flight",
                    labelnames=("device",),
                ).set(round(ratio, 4), device=m.group(1))
                with _live_lock:
                    _busy_devices.add(m.group(1))
            else:
                self.ts.record(name[: -len("_total")] + "_per_s", t, rate)
        if "secret.arena_free_slabs" in vals:
            reg.gauge(
                "trivy_tpu_arena_free_slabs",
                "Free slabs in the secret feed's chunk arena",
            ).set(vals["secret.arena_free_slabs"])
        prog = self.ctx.progress_peek()
        if prog is not None:
            snap = prog.snapshot()
            self.ts.record("progress.ratio", t, snap["ratio"])
            self.ts.record("progress.files_walked", t, snap["files_walked"])
            self.ts.record("progress.files_scanned", t, snap["files_scanned"])
            self.ts.record("progress.bytes_scanned_total", t,
                           snap["bytes_scanned"])
            reg.gauge(
                "trivy_tpu_scan_progress_ratio",
                "Live scan progress (bytes scanned / bytes walked)",
                labelnames=("trace",),
            ).set(snap["ratio"], trace=self.ctx.trace_id)
            self._progress_gauge_set = True

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Sampler":
        # baseline tick before the thread parks on its first interval, so
        # even a sub-interval scan gets a (start, stop) pair and its rate
        # series (link MB/s, busy ratio) have a delta to derive from
        # count this sampler live BEFORE its first gauge write: a
        # concurrently-stopping last sampler must not retire the gauges
        # this scan's baseline tick just set
        _note_sampler_started()
        self._counted_live = True
        try:
            self.sample_once()
        except Exception as e:
            logger.debug("baseline telemetry tick failed: %s", e)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"telemetry-sampler-{self.ctx.trace_id[:8]}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from trivy_tpu import obs

        with obs.activate(self.ctx):
            while not self._stop.wait(self.interval):
                try:
                    self.sample_once()
                except Exception as e:  # a dying probe must not kill ticks
                    logger.debug("telemetry tick failed: %s", e)

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the thread (idempotent), take one final sample so the
        series end at the scan's end state, and retire this scan's
        progress gauge label so /metrics cardinality stays bounded. When
        this was the last live sampler in the process, the shared gauges
        (link, busy, arena) retire too — an idle fleet scrapes as 0, not
        as the final scan's last values frozen forever."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        try:
            self.sample_once()
        except Exception as e:
            logger.debug("final telemetry tick failed: %s", e)
        if self._progress_gauge_set:
            _registry().gauge(
                "trivy_tpu_scan_progress_ratio",
                "Live scan progress (bytes scanned / bytes walked)",
                labelnames=("trace",),
            ).remove(trace=self.ctx.trace_id)
            self._progress_gauge_set = False
        if self._counted_live:
            self._counted_live = False
            _note_sampler_stopped()


def start_sampler(ctx, interval: float | None = None) -> Sampler | None:
    """Spawn a sampler for ``ctx`` unless telemetry is off. ``interval``
    None resolves the env knob; 0 (the ``--telemetry-interval 0`` spelling)
    disables everything — no thread, no ring buffers, no gauges."""
    if interval is None:
        interval = default_interval()
    if interval <= 0:
        return None
    return Sampler(ctx, interval=interval).start()


class LiveProgress:
    """The ``--live`` CLI surface: one carriage-returned status line on a
    short cadence — progress %, MB/s, ETA, device busy %, arena occupancy
    — fed from :class:`ScanProgress` plus the sampler's latest points.
    Prints to ``stream`` (stderr by default) and finishes with a newline
    so the report output below it stays clean."""

    def __init__(self, ctx, stream=None, interval: float = 0.5):
        import sys

        self.ctx = ctx
        self.stream = stream or sys.stderr
        self.interval = interval
        self._stop = threading.Event()
        self._wrote = False
        self._thread: threading.Thread | None = None

    def line(self) -> str:
        prog = self.ctx.progress_peek()
        snap = prog.snapshot() if prog is not None else {}
        parts = []
        if snap:
            parts.append(f"{snap['ratio'] * 100:5.1f}%")
            parts.append(f"{snap['mbs']:.1f} MB/s")
            if snap.get("eta_s") is not None:
                parts.append(f"ETA {snap['eta_s']:.0f}s")
            remote = snap.get("remote")
            if remote and remote.get("Ratio") is not None:
                parts.append(f"server {float(remote['Ratio']) * 100:.0f}%")
        ts = getattr(self.ctx, "timeseries", None)
        if ts is not None:
            link = ts.latest(_LINK_SERIES)
            if link is not None:
                parts.append(f"link {link:.1f} MB/s")
            busy = [
                ts.latest(n)
                for n in ts.names()
                if n.startswith("device.") and n.endswith(".busy_ratio")
            ]
            busy = [b for b in busy if b is not None]
            if busy:
                parts.append(f"busy {100 * sum(busy) / len(busy):.0f}%")
            free = ts.latest("secret.arena_free_slabs")
            if free is not None:
                parts.append(f"arena free {free:.0f}")
        # fleet column: the telemetry poller's per-replica fragment
        # (busy % / MB/s / queue depth / breaker state) — set by the
        # coordinator only while a fleet fan-out with polling is live
        fleet_live = getattr(self.ctx, "fleet_live", None)
        if fleet_live is not None:
            try:
                parts.append(fleet_live())
            except Exception:
                pass
        # device-lane column: compile count + HBM residency from the
        # flight recorder ("compiles 12 hbm 61%", plus a STORM marker
        # the moment a recompile storm is detected mid-scan)
        try:
            from trivy_tpu.obs import recorder as _recorder

            dev = _recorder.live_fragment()
            if dev:
                parts.append(dev)
        except Exception:
            pass
        # online-tuning column: current knob set + decision count, so an
        # operator watching --live sees every mid-scan adaptation land
        ctl = getattr(self.ctx, "tuning_controller", None)
        if ctl is not None:
            try:
                k = ctl.adapter.knobs()
                parts.append(
                    f"tune s{k['feed_streams']}/i{k['inflight']} "
                    f"({len(ctl.decisions)} dec)"
                )
            except Exception:
                pass
        return "scan " + " | ".join(parts) if parts else "scan starting..."

    def start(self) -> "LiveProgress":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-live",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from trivy_tpu import obs

        with obs.activate(self.ctx):
            while not self._stop.wait(self.interval):
                self._emit()

    def _emit(self) -> None:
        try:
            self.stream.write("\r\x1b[2K" + self.line())
            self.stream.flush()
            self._wrote = True
        except (ValueError, OSError):  # closed stream on teardown
            self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._emit()
        if self._wrote:
            try:
                self.stream.write("\n")
                self.stream.flush()
            except (ValueError, OSError):
                pass
