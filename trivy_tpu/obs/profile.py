"""Per-rule / per-bucket cost attribution for one scan ("scan profile").

Stall attribution (:mod:`trivy_tpu.obs.stall`) says *which stage* of a
pipeline is slow; this module says *which rule* and *which dispatch bucket*
— the difference between "confirm-bound 40%" and "confirm-bound 40%,
of which `aws-secret-access-key` burns 31% confirming device hits that the
exact host engine rejects". The batched-NFA design makes this essential:
one pathological rule (a hot keyword gate with a high host-confirm
false-positive rate) can dominate device time and confirm stalls while
staying invisible in per-stage totals.

Recorded per rule id:

- ``gate_hits`` — device prefilter hits ((row, rule) pairs the kernel
  flagged, including rows served from the dedup hit cache: a cached hit is
  still a logical device hit that will cost a confirm)
- ``confirms`` / ``confirm_s`` — exact host confirmations run for the rule
  and their wall time (on the CPU backend and the degraded host-fallback
  path this is the full rule evaluation, so a degraded scan still produces
  a complete profile)
- ``findings`` — locations that survived confirmation
- ``wasted_confirms`` / ``wasted_confirm_s`` — confirms that produced zero
  findings: pure false-positive cost. ``fp_rate`` = wasted / confirms is
  the gate false-positive rate the bucket-ladder and keyword-gate tuning
  rounds need.

Recorded per dispatch bucket (the batch-shape ladder — ``"1024"``,
``"512"``, ... for the secret pipeline; ``"license.gate:64"`` /
``"license.score:64"`` for the license corpus shards): dispatches, rows,
and blocking device-wait seconds, so the ladder is tunable from data
instead of folklore.

A :class:`ScanProfile` lives on a :class:`trivy_tpu.obs.TraceContext`
(created lazily via ``ctx.profile()``); serialized profiles (the ``Trace``
block of a remote scan response, or a saved ``--profile-out`` file) fold
into another profile with :meth:`ScanProfile.merge_dict`, which is how the
client merges its own pipeline profile with the server's.
"""

from __future__ import annotations

import os
import threading

# per-scan bound on rule label cardinality exported to Prometheus and the
# report table; the full profile still lands in --profile-out


def _topk_from_env() -> int:
    try:
        return max(1, int(os.environ.get("TRIVY_TPU_PROFILE_TOPK", "10")))
    except ValueError:
        return 10


TOP_K = _topk_from_env()

# internal per-rule slots: gate_hits, confirms, confirm_s, findings,
# wasted_confirms, wasted_confirm_s, prefilter_hits
_R = 7


class ScanProfile:
    """Thread-safe per-rule and per-bucket accumulators for one scan."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: dict[str, list] = {}
        self._buckets: dict[str, list] = {}  # key -> [dispatches, rows, wait_s]
        # keyword-prefilter pass totals: [rows inspected, rows whose batch
        # skipped the anchored/NFA dispatch, rows with >=1 candidate rule]
        self._pre = [0, 0, 0]
        # fleet cost attribution: per-shard rows (replica, bytes, wall,
        # steal/speculation provenance, attempts) + the per-replica
        # efficiency verdict the coordinator computes at fan-out end
        self._shards: list[dict] = []
        self._fleet_replicas: dict[str, dict] = {}

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._rules or self._buckets or self._shards
                        or self._fleet_replicas)

    def _rule(self, rule_id: str) -> list:
        r = self._rules.get(rule_id)
        if r is None:
            r = self._rules[rule_id] = [0, 0, 0.0, 0, 0, 0.0, 0]
        return r

    # -- recording ----------------------------------------------------------

    def gate_hit(self, rule_id: str, n: int = 1) -> None:
        """The device matcher flagged ``rule_id`` on ``n`` rows."""
        with self._lock:
            self._rule(rule_id)[0] += n

    def prefilter_hit(self, rule_id: str, n: int = 1) -> None:
        """The keyword prefilter made ``rule_id`` a candidate on ``n``
        rows. Per-rule candidate selectivity = prefilter_hits / the scan's
        prefiltered row total — the signal that says which rules' keywords
        are too common to gate anything."""
        with self._lock:
            self._rule(rule_id)[6] += n

    def prefilter_rows(self, rows: int, skipped: int, hit_rows: int = 0) -> None:
        """The prefilter pass inspected ``rows`` more rows, of which
        ``skipped`` rode a batch that skipped the anchored dispatch and
        ``hit_rows`` carried at least one candidate rule."""
        with self._lock:
            self._pre[0] += rows
            self._pre[1] += skipped
            self._pre[2] += hit_rows

    def confirm(self, rule_id: str, seconds: float, findings: int) -> None:
        """One exact host evaluation of ``rule_id`` took ``seconds`` and
        yielded ``findings`` surviving locations."""
        with self._lock:
            r = self._rule(rule_id)
            r[1] += 1
            r[2] += seconds
            r[3] += findings
            if findings == 0:
                r[4] += 1
                r[5] += seconds

    def bucket_dispatch(self, bucket, rows: int, wait_s: float) -> None:
        """One device dispatch of ``rows`` live rows in shape-bucket
        ``bucket`` spent ``wait_s`` in the blocking result fetch."""
        key = str(bucket)
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = [0, 0, 0.0]
            b[0] += 1
            b[1] += rows
            b[2] += wait_s

    def note_shard(self, replica: str, nbytes: int, wall_s: float,
                   stolen: bool = False, speculated: bool = False,
                   attempts: int = 1) -> None:
        """One completed fleet shard's cost row: which replica ran it,
        how many planned bytes it covered, its winning-attempt wall time,
        and how it got there (stolen from a peer's queue / a speculative
        twin / after ``attempts - 1`` retries)."""
        with self._lock:
            self._shards.append({
                "replica": replica,
                "bytes": int(nbytes),
                "wall_ms": round(wall_s * 1e3, 3),
                "stolen": bool(stolen),
                "speculated": bool(speculated),
                "attempts": int(attempts),
            })

    def note_fleet(self, replicas: dict[str, dict]) -> None:
        """Attach the coordinator's per-replica efficiency verdict:
        ``{host: {"busy": %, "idle": %, "stalled_on_coordinator": %,
        "dead": %, ...}}`` — the four buckets sum to 100 per replica."""
        with self._lock:
            self._fleet_replicas.update(replicas)

    def merge_dict(self, doc: dict) -> None:
        """Fold a serialized profile (:meth:`to_dict` output) into this one
        — used to merge a remote scan's profile into the client's."""
        pre = doc.get("prefilter") or {}
        if pre:
            with self._lock:
                rows = int(pre.get("rows", 0))
                self._pre[0] += rows
                self._pre[1] += int(pre.get("rows_nfa_skipped", 0))
                self._pre[2] += int(
                    pre.get("hit_rows", round(pre.get("selectivity", 0.0) * rows))
                )
        for rid, f in (doc.get("rules") or {}).items():
            with self._lock:
                r = self._rule(rid)
                r[0] += int(f.get("gate_hits", 0))
                r[1] += int(f.get("confirms", 0))
                r[2] += float(f.get("confirm_ms", 0.0)) / 1e3
                r[3] += int(f.get("findings", 0))
                r[4] += int(f.get("wasted_confirms", 0))
                r[5] += float(f.get("wasted_confirm_ms", 0.0)) / 1e3
                r[6] += int(f.get("prefilter_hits", 0))
        for key, bf in (doc.get("buckets") or {}).items():
            with self._lock:
                b = self._buckets.get(key)
                if b is None:
                    b = self._buckets[key] = [0, 0, 0.0]
                b[0] += int(bf.get("dispatches", 0))
                b[1] += int(bf.get("rows", 0))
                b[2] += float(bf.get("device_wait_ms", 0.0)) / 1e3
        fleet = doc.get("fleet") or {}
        if fleet:
            with self._lock:
                self._shards.extend(fleet.get("shards") or [])
                self._fleet_replicas.update(fleet.get("replicas") or {})

    # -- serialization ------------------------------------------------------

    def to_dict(self, top_k: int | None = None) -> dict:
        """JSON-serializable profile; rules ordered hottest-first (confirm
        time, then gate hits). ``top_k`` bounds the rule list for embedded
        copies (bench reps); None keeps every rule."""
        with self._lock:
            rules = {k: list(v) for k, v in self._rules.items()}
            buckets = {k: list(v) for k, v in self._buckets.items()}
            pre_rows, pre_skipped, pre_hit_rows = self._pre
            shards = [dict(s) for s in self._shards]
            fleet_replicas = {k: dict(v)
                              for k, v in self._fleet_replicas.items()}
        items = sorted(rules.items(), key=lambda kv: (-kv[1][2], -kv[1][0], kv[0]))
        if top_k is not None:
            items = items[:top_k]
        doc = {
            "rules": {
                rid: {
                    "gate_hits": g,
                    "confirms": c,
                    "confirm_ms": round(cs * 1e3, 3),
                    "findings": f,
                    "wasted_confirms": wc,
                    "wasted_confirm_ms": round(wcs * 1e3, 3),
                    "fp_rate": round(wc / c, 4) if c else 0.0,
                    "prefilter_hits": p,
                    # per-rule candidate selectivity: what fraction of all
                    # prefiltered rows this rule's keywords flagged
                    "prefilter_selectivity": (
                        round(p / pre_rows, 6) if pre_rows else 0.0
                    ),
                }
                for rid, (g, c, cs, f, wc, wcs, p) in items
            },
            "buckets": {
                k: {
                    "dispatches": d,
                    "rows": rows,
                    "device_wait_ms": round(s * 1e3, 3),
                }
                for k, (d, rows, s) in sorted(buckets.items())
            },
        }
        if shards or fleet_replicas:
            doc["fleet"] = {
                "shards": shards,
                "replicas": fleet_replicas,
            }
        if pre_rows:
            doc["prefilter"] = {
                "rows": pre_rows,
                "rows_nfa_skipped": pre_skipped,
                "hit_rows": pre_hit_rows,
                # scan-level selectivity: fraction of rows carrying >=1
                # candidate rule — the knob the smoke gate sanity-checks
                "selectivity": round(pre_hit_rows / pre_rows, 6),
            }
        return doc


def top_rules(doc: dict, k: int | None = None) -> list[tuple[str, dict]]:
    """Hottest rules of a serialized profile: by confirm time, then gate
    hits. ``k`` defaults to the TOP_K export bound."""
    items = sorted(
        (doc.get("rules") or {}).items(),
        key=lambda kv: (-kv[1].get("confirm_ms", 0.0), -kv[1].get("gate_hits", 0), kv[0]),
    )
    return items[: TOP_K if k is None else k]


def fleet_table_lines(doc: dict) -> list[str]:
    """Formatted fleet efficiency verdict for the --trace report: one row
    per replica with shard count, bytes, and the four 100%-sum buckets
    (``busy`` scanning shards / ``idle`` waiting for work / ``stalled``
    on the coordinator's tail / ``dead`` behind an open breaker)."""
    fleet = doc.get("fleet") or {}
    replicas = fleet.get("replicas") or {}
    if not replicas:
        return []
    per_host: dict[str, list] = {}  # host -> [shards, bytes, wall_ms]
    for row in fleet.get("shards") or []:
        agg = per_host.setdefault(row.get("replica", "?"), [0, 0, 0.0])
        agg[0] += 1
        agg[1] += int(row.get("bytes", 0))
        agg[2] += float(row.get("wall_ms", 0.0))
    lines = [
        f"{'replica':<28}{'shards':>7}{'MB':>9}{'busy%':>7}{'idle%':>7}"
        f"{'stall%':>7}{'dead%':>6}"
    ]
    for host in sorted(replicas):
        v = replicas[host]
        shards, nbytes, _ = per_host.get(host, [0, 0, 0.0])
        lines.append(
            f"{host:<28}{shards:>7}{nbytes / 1e6:>9.1f}"
            f"{v.get('busy', 0.0):>7.1f}{v.get('idle', 0.0):>7.1f}"
            f"{v.get('stalled_on_coordinator', 0.0):>7.1f}"
            f"{v.get('dead', 0.0):>6.1f}"
        )
    return lines


def table_lines(doc: dict, k: int | None = None) -> list[str]:
    """Formatted top-K "hottest rules" table for the --trace report."""
    rows = top_rules(doc, k)
    if not rows:
        return []
    lines = [
        f"{'rule':<34}{'gate_hits':>10}{'confirms':>9}{'confirm':>10}"
        f"{'fp%':>7}{'wasted':>10}{'found':>6}"
    ]
    for rid, f in rows:
        lines.append(
            f"{rid:<34}{f.get('gate_hits', 0):>10}{f.get('confirms', 0):>9}"
            f"{f.get('confirm_ms', 0.0):>8.1f}ms"
            f"{100.0 * f.get('fp_rate', 0.0):>6.1f}%"
            f"{f.get('wasted_confirm_ms', 0.0):>8.1f}ms"
            f"{f.get('findings', 0):>6}"
        )
    return lines
