"""Flight recorder + device-lane forensics (the always-on black box).

Three cooperating pieces, all near-zero-cost and strictly bounded:

- **Flight recorder ring** — every subsystem already emitting to ``obs``
  feeds one cheap :func:`record` hook with its *significant* events (span
  boundaries above a latency floor, batch retries / OOM splits, breaker
  transitions, degrade events, tuning and fleet-controller decisions,
  admission sheds, warm-store cold starts, injected faults). Events land
  in a byte-bounded ring on the active scan context (concurrent scans
  keep disjoint rings) and mirror into one process ring so a long-lived
  server can answer "what last went wrong" without a scan handle.
- **Device-lane accounting** — :func:`instrument_jit` wraps the jit/stage
  compilation sites in ``parallel/mesh.py`` and the kernel entry points
  to count compiles and compile wall per (kernel, shape-bucket), detect
  recompile storms (same kernel compiled more than
  ``TRIVY_TPU_RECOMPILE_STORM`` times → loud warning + counter), and a
  live HBM ledger (:func:`note_resident`) tracks resident corpus / CVE /
  arena bytes against device memory. Both export as
  ``trivy_tpu_compile_*`` / ``trivy_tpu_hbm_*`` gauges, Perfetto counter
  tracks, and the ``device`` block in ``--metrics-out``.
- **Diagnostic bundles** — on any terminal failure, degraded completion,
  breaker trip, or dead-replica declaration, :func:`auto_emit` writes a
  self-contained gzipped bundle (ring dump, last metrics/tuning/fleet
  snapshots, stall verdict, compile/HBM ledgers, and a one-paragraph
  machine-built verdict naming the first anomalous event) under
  ``--debug-dir`` / ``TRIVY_TPU_DEBUG_DIR`` with bounded retention.
  ``trivy-tpu debug <bundle>`` renders the timeline + verdict; the scan
  server serves its live state over ``GET /debug/bundle`` so a fleet
  coordinator can merge replica bundles into one incident document.

Zero-cost-when-off discipline (``TRIVY_TPU_FLIGHT_RECORDER=0``): no ring
objects, no span hook on the trace context, no recorder gauges in the
process registry, no threads — :func:`record` is one global ``None``
check and :func:`instrument_jit` hands back the bare jitted callable
(``bench --smoke`` asserts all of it). The recorder itself never starts
a thread in either mode: the ring is passive memory, written in-line by
its callers.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time

from trivy_tpu import log

logger = log.logger("obs:recorder")

ENV_ENABLED = "TRIVY_TPU_FLIGHT_RECORDER"
ENV_RING_KB = "TRIVY_TPU_RECORDER_RING_KB"
ENV_SPAN_FLOOR_MS = "TRIVY_TPU_RECORDER_SPAN_FLOOR_MS"
ENV_STORM = "TRIVY_TPU_RECOMPILE_STORM"
ENV_DEBUG_DIR = "TRIVY_TPU_DEBUG_DIR"
ENV_DEBUG_KEEP = "TRIVY_TPU_DEBUG_KEEP"

# ring bounds: a ring holds at most RING_MAX_EVENTS events AND at most
# ring_kb() kilobytes (approximate accounting; oldest events evict first).
# 512 events x ~200 bytes sits well under the default 256 KB byte bound,
# so the count cap normally bites first and the byte bound is the
# flood-of-huge-details backstop
RING_MAX_EVENTS = 512
DEFAULT_RING_KB = 256
# span boundaries only enter the ring above this duration — the ring is
# for *significant* events, not a second span table
DEFAULT_SPAN_FLOOR_MS = 50.0
# same kernel compiled more than this many times in one process = a
# recompile storm (the default 3-rung bucket ladder compiles each kernel
# 3x by design; the headroom above that is deliberate)
DEFAULT_STORM_THRESHOLD = 6
DEFAULT_DEBUG_KEEP = 8
# per-event detail strings are truncated so one giant error repr cannot
# evict the whole ring
DETAIL_MAX_CHARS = 200

BUNDLE_SCHEMA = "trivy-tpu-debug-bundle/v1"

# event kinds that count as anomalous for the machine verdict, most
# severe first — the verdict names the FIRST (earliest) anomalous event,
# ties broken by this ranking
ANOMALOUS_KINDS = (
    "fault", "error", "oom", "dead", "breaker", "degrade", "storm",
    "retry", "shed",
)

_EVENT_BASE_BYTES = 96  # approximate fixed per-event overhead


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


def ring_bytes() -> int:
    """The ring's byte bound (``TRIVY_TPU_RECORDER_RING_KB``)."""
    return max(1, _env_int(ENV_RING_KB, DEFAULT_RING_KB)) * 1024


class Ring:
    """Byte- and count-bounded event ring (oldest evicts first)."""

    __slots__ = ("max_events", "max_bytes", "_lock", "_events", "_bytes",
                 "dropped")

    def __init__(self, max_events: int = RING_MAX_EVENTS,
                 max_bytes: int | None = None):
        self.max_events = max_events
        self.max_bytes = max_bytes or ring_bytes()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._bytes = 0
        self.dropped = 0

    @staticmethod
    def _size(ev: dict) -> int:
        n = _EVENT_BASE_BYTES + len(ev.get("what", ""))
        for k, v in (ev.get("detail") or {}).items():
            n += len(k) + len(str(v))
        return n

    def append(self, ev: dict) -> None:
        sz = self._size(ev)
        with self._lock:
            self._events.append(ev)
            self._bytes += sz
            while self._events and (
                len(self._events) > self.max_events
                or self._bytes > self.max_bytes
            ):
                old = self._events.pop(0)
                self._bytes -= self._size(old)
                self.dropped += 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def approx_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def last(self, *kinds: str) -> dict | None:
        """Most recent event whose kind is in ``kinds`` (any kind when
        empty), or None."""
        with self._lock:
            for ev in reversed(self._events):
                if not kinds or ev.get("kind") in kinds:
                    return dict(ev)
        return None


class _State:
    """Per-process recorder state: the process ring, the compile and HBM
    ledgers, and the bundle-emission bookkeeping. Exists ONLY while the
    recorder is enabled."""

    def __init__(self):
        self.ring = Ring()
        self.lock = threading.Lock()
        self.span_floor_s = _env_float(
            ENV_SPAN_FLOOR_MS, DEFAULT_SPAN_FLOOR_MS
        ) / 1e3
        self.storm_threshold = _env_int(ENV_STORM, DEFAULT_STORM_THRESHOLD)
        self.debug_dir: str = os.environ.get(ENV_DEBUG_DIR, "")
        self.debug_keep = max(1, _env_int(ENV_DEBUG_KEEP, DEFAULT_DEBUG_KEEP))
        # compile ledger: per-kernel [count, wall_s], per (kernel, bucket)
        # count, and the set of kernels already storm-warned (warn ONCE
        # per kernel, not once per extra compile)
        self.compiles: dict[str, list] = {}
        self.compile_buckets: dict[tuple[str, str], int] = {}
        self.storms: set[str] = set()
        # HBM ledger: category -> resident bytes
        self.resident: dict[str, int] = {}
        self._capacity: int | None = None
        # bundle bookkeeping: (trace8, reason) pairs already emitted, so a
        # breaker flapping mid-scan yields one bundle, not a flood
        self.emitted: set[tuple[str, str]] = set()

    # -- device memory capacity ---------------------------------------------

    def capacity_bytes(self) -> int:
        if self._capacity is not None:
            return self._capacity
        cap = 0
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            cap = int(stats.get("bytes_limit", 0) or 0)
        except Exception:
            cap = 0
        if cap <= 0:
            # the admission controller's HBM proxy budget (MB); the CPU
            # backend has no memory_stats so the budget stands in
            cap = _env_int("TRIVY_TPU_HBM_BUDGET_MB", 1024) * (1 << 20)
        self._capacity = cap
        return cap


_STATE: _State | None = None
_STATE_LOCK = threading.Lock()
_ENABLED: bool | None = None


def enabled() -> bool:
    """One cached env read: ``TRIVY_TPU_FLIGHT_RECORDER`` (default on)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get(ENV_ENABLED, "1").strip().lower() not in (
            "0", "off", "false", "no",
        )
    return _ENABLED


def _state() -> _State | None:
    global _STATE
    st = _STATE
    if st is not None:
        return st
    if not enabled():
        return None
    with _STATE_LOCK:
        if _STATE is None:
            _STATE = _State()
            _install_hook()
        return _STATE


def _install_hook() -> None:
    from trivy_tpu import obs

    obs._flight_hook = _span_hook


def configure(enabled_override: bool | None = None) -> None:
    """Re-read the environment and reset recorder state (rings, ledgers,
    emitted-bundle memory, gauge *values* stay — the registry cannot
    unregister). Test/bench hook: production code never calls this."""
    global _STATE, _ENABLED
    from trivy_tpu import obs

    with _STATE_LOCK:
        _STATE = None
        _ENABLED = enabled_override
        obs._flight_hook = None
    if enabled_override is None:
        enabled()  # re-read env
    if enabled():
        _state()


def set_debug_dir(path: str | None) -> None:
    """Install the bundle destination (``--debug-dir``); no-op when the
    recorder is off."""
    st = _state()
    if st is not None and path:
        st.debug_dir = path


def debug_dir() -> str:
    st = _STATE
    return st.debug_dir if st is not None else ""


# -- the one cheap event hook -----------------------------------------------


def _ctx_ring(ctx) -> Ring:
    ring = getattr(ctx, "_flight_ring", None)
    if ring is None:
        with _STATE_LOCK:
            ring = getattr(ctx, "_flight_ring", None)
            if ring is None:
                ring = ctx._flight_ring = Ring()
    return ring


def record(kind: str, what: str, detail: dict | None = None,
           ctx=None) -> None:
    """Append one significant event to the active scan's ring and the
    process ring. The cheap hook every subsystem calls; a no-op (one
    global check) when the recorder is off."""
    st = _STATE if _ENABLED else _state()
    if st is None:
        st = _state()
        if st is None:
            return
    from trivy_tpu import obs

    if ctx is None:
        ctx = obs.current()
    ev: dict = {
        "t": time.time(),
        "kind": kind,
        "what": what,
        "trace": ctx.trace_id[:8],
    }
    if detail:
        ev["detail"] = {
            k: (v if isinstance(v, (int, float, bool)) or v is None
                else str(v)[:DETAIL_MAX_CHARS])
            for k, v in detail.items()
        }
    _ctx_ring(ctx).append(ev)
    st.ring.append(ev)


def _span_hook(ctx, sp) -> None:
    """Installed as ``obs._flight_hook``: span boundaries above the
    latency floor become ring events."""
    st = _STATE
    if st is None or sp.duration < st.span_floor_s:
        return
    record(
        "span", sp.name, {"seconds": round(sp.duration, 4)}, ctx=ctx,
    )


# -- device-lane accounting: compiles ---------------------------------------


def _metric_counter(name: str, help: str, labelnames=()):
    from trivy_tpu.obs import metrics as obs_metrics

    return obs_metrics.REGISTRY.counter(name, help, labelnames)


def _metric_gauge(name: str, help: str, labelnames=()):
    from trivy_tpu.obs import metrics as obs_metrics

    return obs_metrics.REGISTRY.gauge(name, help, labelnames)


def note_compile(kernel: str, bucket: str, seconds: float) -> None:
    """One XLA/Mosaic compile of ``kernel`` for shape-bucket ``bucket``
    took ``seconds`` of wall. Feeds the compile ledger, the
    ``trivy_tpu_compile_*`` instruments, the ring, and the recompile-storm
    detector."""
    st = _STATE
    if st is None:
        return
    with st.lock:
        tot = st.compiles.setdefault(kernel, [0, 0.0])
        tot[0] += 1
        tot[1] += seconds
        count = tot[0]
        key = (kernel, bucket)
        st.compile_buckets[key] = st.compile_buckets.get(key, 0) + 1
        storm = count > st.storm_threshold and kernel not in st.storms
        if storm:
            st.storms.add(kernel)
    _metric_counter(
        "trivy_tpu_compile_total",
        "Kernel compiles observed by the flight recorder",
        labelnames=("kernel",),
    ).inc(kernel=kernel)
    _metric_counter(
        "trivy_tpu_compile_seconds_total",
        "Kernel compile wall time",
        labelnames=("kernel",),
    ).inc(seconds, kernel=kernel)
    record("compile", kernel, {
        "bucket": bucket, "seconds": round(seconds, 4), "n": count,
    })
    if storm:
        _metric_counter(
            "trivy_tpu_compile_storms_total",
            "Kernels that recompiled past the storm threshold",
            labelnames=("kernel",),
        ).inc(kernel=kernel)
        record("storm", kernel, {
            "compiles": count, "threshold": st.storm_threshold,
        })
        logger.warning(
            "RECOMPILE STORM: kernel %s compiled %d times (threshold %d) — "
            "a shape bucket or rung ladder is churning the compile cache",
            kernel, count, st.storm_threshold,
        )


def _shape_bucket(args) -> str:
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            parts.append(f"{getattr(a, 'dtype', '?')}{tuple(shape)}")
        elif isinstance(a, (tuple, list)):
            parts.append("(" + _shape_bucket(a) + ")")
        else:
            parts.append(type(a).__name__)
    return ",".join(parts)


def instrument_jit(kernel: str, fn, **jit_kwargs):
    """``jax.jit(fn)`` with compile accounting: the first call per
    argument shape-bucket is timed as a compile (trace + compile wall)
    and fed to :func:`note_compile`. With the recorder off this returns
    the bare jitted callable — zero wrapper, zero per-call cost."""
    import jax

    jfn = jax.jit(fn, **jit_kwargs)
    if _state() is None:
        return jfn
    seen: set[str] = set()
    lock = threading.Lock()

    def call(*args):
        bucket = _shape_bucket(args)
        with lock:
            first = bucket not in seen
            if first:
                seen.add(bucket)
        if not first:
            return jfn(*args)
        t0 = time.perf_counter()
        out = jfn(*args)
        note_compile(kernel, bucket, time.perf_counter() - t0)
        return out

    call.__wrapped__ = jfn
    return call


def compile_count() -> int:
    """Total compiles observed so far (bench's per-rep regression metric
    differences two reads of this)."""
    st = _STATE
    if st is None:
        return 0
    with st.lock:
        return sum(c for c, _ in st.compiles.values())


def storm_count() -> int:
    st = _STATE
    if st is None:
        return 0
    with st.lock:
        return len(st.storms)


# -- device-lane accounting: HBM ledger -------------------------------------


def note_resident(category: str, nbytes: int) -> None:
    """``nbytes`` more of ``category`` (corpus / cve / arena) became
    device-resident. Negative deltas release."""
    st = _STATE
    if st is None or not nbytes:
        return
    with st.lock:
        now = st.resident.get(category, 0) + int(nbytes)
        st.resident[category] = max(0, now)
        total = sum(st.resident.values())
    _metric_gauge(
        "trivy_tpu_hbm_resident_bytes",
        "Device-resident bytes tracked by the flight recorder's HBM "
        "ledger, by category",
        labelnames=("category",),
    ).set(st.resident[category], category=category)
    _metric_gauge(
        "trivy_tpu_hbm_device_capacity_bytes",
        "Device memory capacity the HBM ledger scores residency against",
    ).set(st.capacity_bytes())
    record("hbm", category, {
        "delta": int(nbytes), "resident": st.resident[category],
        "total": total,
    })


def release_resident(category: str, nbytes: int) -> None:
    note_resident(category, -abs(int(nbytes)))


def hbm_ratio() -> float:
    """Resident bytes / device capacity, 0.0 with the recorder off."""
    st = _STATE
    if st is None:
        return 0.0
    with st.lock:
        total = sum(st.resident.values())
    cap = st.capacity_bytes()
    return total / cap if cap > 0 else 0.0


# -- export surfaces --------------------------------------------------------


def device_doc() -> dict | None:
    """The ``device`` block for ``--metrics-out``: compile ledger, storm
    set, and HBM residency. None when the recorder is off or nothing was
    observed — off-mode exports stay byte-identical."""
    st = _STATE
    if st is None:
        return None
    with st.lock:
        if not st.compiles and not st.resident:
            return None
        compiles = {
            k: {"count": c, "wall_s": round(w, 4)}
            for k, (c, w) in sorted(st.compiles.items())
        }
        buckets = {
            f"{k}|{b}": n
            for (k, b), n in sorted(st.compile_buckets.items())
        }
        storms = sorted(st.storms)
        resident = dict(sorted(st.resident.items()))
    cap = st.capacity_bytes()
    total = sum(resident.values())
    return {
        "compiles": compiles,
        "compile_total": sum(v["count"] for v in compiles.values()),
        "compile_wall_s": round(
            sum(v["wall_s"] for v in compiles.values()), 4
        ),
        "shape_buckets": buckets,
        "recompile_storms": storms,
        "storm_threshold": st.storm_threshold,
        "hbm": {
            "resident_bytes": resident,
            "resident_total_bytes": total,
            "device_capacity_bytes": cap,
            "ratio": round(total / cap, 4) if cap else 0.0,
        },
    }


def counter_series(ctx) -> dict:
    """Perfetto counter tracks derived from the scan ring: cumulative
    compile count and HBM-resident bytes over the scan's timeline (the
    same ``{"points": [(t, v)]}`` shape the sampler's series use)."""
    st = _STATE
    ring = getattr(ctx, "_flight_ring", None) if st is not None else None
    if ring is None:
        return {}
    compiles: list = []
    hbm: list = []
    n = 0
    for ev in ring.snapshot():
        t = ev["t"] - ctx.created_wall
        if ev["kind"] == "compile":
            n += 1
            compiles.append((round(t, 6), n))
        elif ev["kind"] == "hbm":
            hbm.append((round(t, 6), (ev.get("detail") or {}).get("total", 0)))
    out = {}
    if compiles:
        out["device.compiles_total"] = {"points": compiles}
    if hbm:
        out["device.hbm_resident_bytes"] = {"points": hbm}
    return out


def _iso(t: float) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        t, datetime.timezone.utc
    ).isoformat(timespec="milliseconds")


def healthz_doc() -> dict:
    """The ``/healthz`` forensics fields: last error / degrade / breaker
    trip from the process ring, each as event + ISO timestamp. Empty dict
    when the recorder is off or nothing bad ever happened."""
    st = _STATE
    if st is None:
        return {}
    out = {}
    for field, kinds in (
        ("LastError", ("error", "fault", "oom")),
        ("LastDegraded", ("degrade",)),
        ("LastBreakerTrip", ("breaker",)),
    ):
        ev = st.ring.last(*kinds)
        if field == "LastBreakerTrip" and ev is not None and (
            "OPEN" not in ev.get("what", "")
        ):
            # breaker events cover open AND close; the trip field reports
            # the last OPEN specifically
            for cand in reversed(st.ring.snapshot()):
                if cand.get("kind") == "breaker" and "OPEN" in cand.get(
                    "what", ""
                ):
                    ev = cand
                    break
            else:
                ev = None
        if ev is not None:
            out[field] = {
                "Event": f"{ev['kind']} {ev['what']}",
                "Time": _iso(ev["t"]),
            }
    return out


# -- live fragments (heartbeat / --live) ------------------------------------


def live_fragment() -> str:
    """Stateless compact device fragment for the ``--live`` line:
    ``compiles 12 hbm 61%`` (plus a storm marker). Empty when the
    recorder is off or nothing was observed."""
    st = _STATE
    if st is None:
        return ""
    n = compile_count()
    ratio = hbm_ratio()
    if not n and not ratio:
        return ""
    frag = f"compiles {n}"
    if ratio:
        frag += f" hbm {ratio * 100:.0f}%"
    if storm_count():
        frag += " STORM"
    return frag


def heartbeat_fragment(carrier) -> str:
    """Heartbeat device fragment with a per-beat delta:
    ``compiles 12 (+0) hbm 61%``; a recompile storm since the previous
    beat surfaces immediately (``RECOMPILE-STORM <kernel>``). ``carrier``
    is any object the per-beat state can hang off (the heartbeat
    instance)."""
    st = _STATE
    if st is None:
        return ""
    n = compile_count()
    ratio = hbm_ratio()
    storms = storm_count()
    last_n = getattr(carrier, "_rec_last_compiles", None)
    last_storms = getattr(carrier, "_rec_last_storms", 0)
    carrier._rec_last_compiles = n
    carrier._rec_last_storms = storms
    if not n and not ratio:
        return ""
    frag = f"compiles {n}"
    if last_n is not None:
        frag += f" (+{max(0, n - last_n)})"
    if ratio:
        frag += f" hbm {ratio * 100:.0f}%"
    if storms > last_storms:
        with st.lock:
            names = sorted(st.storms)
        frag += f" RECOMPILE-STORM {names[-1] if names else '?'}"
    return frag


# -- diagnostic bundles -----------------------------------------------------


def _verdict(reason: str, ctx, events: list[dict],
             error: str | None = None) -> str:
    """One machine-built paragraph naming the first anomalous event."""
    st = _STATE
    anomalous = [e for e in events if e.get("kind") in ANOMALOUS_KINDS]
    first = None
    if anomalous:
        rank = {k: i for i, k in enumerate(ANOMALOUS_KINDS)}
        t0 = min(e["t"] for e in anomalous)
        # earliest wins; among events in the same 10 ms window the most
        # severe kind names the verdict (a fault and the degrade it caused
        # land near-simultaneously — the fault is the cause)
        window = [e for e in anomalous if e["t"] - t0 <= 0.010]
        first = min(window, key=lambda e: rank.get(e["kind"], 99))
    parts = [f"Scan {ctx.trace_id[:8]}: {reason}."]
    if first is not None:
        rel = first["t"] - ctx.created_wall
        parts.append(
            f"First anomalous event: {first['kind']} {first['what']} at "
            f"{_iso(first['t'])} (+{rel:.2f}s into the scan)."
        )
        if len(anomalous) > 1:
            kinds: dict[str, int] = {}
            for e in anomalous:
                kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
            parts.append(
                f"{len(anomalous)} anomalous events total ("
                + ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
                + ")."
            )
    else:
        parts.append("No anomalous events in the ring.")
    if error:
        parts.append(f"Last error: {str(error)[:DETAIL_MAX_CHARS]}.")
    if st is not None:
        n = compile_count()
        if n:
            with st.lock:
                wall = sum(w for _, w in st.compiles.values())
                kernels = len(st.compiles)
            parts.append(
                f"Device lane: {n} compiles / {wall:.2f}s compile wall "
                f"across {kernels} kernels; HBM resident "
                f"{hbm_ratio() * 100:.0f}% of device memory."
            )
    return " ".join(parts)


def build_bundle(ctx=None, reason: str = "on-demand",
                 error=None) -> dict:
    """Assemble a self-contained diagnostic bundle as a dict. Works with
    the recorder off too (empty ring, no ledgers) so the server route can
    answer honestly either way."""
    from trivy_tpu import obs
    from trivy_tpu.obs import export as obs_export
    from trivy_tpu.obs import stall as obs_stall

    if ctx is None:
        ctx = obs.current()
    st = _STATE
    ring = getattr(ctx, "_flight_ring", None)
    events = ring.snapshot() if ring is not None else []
    process_events = st.ring.snapshot() if st is not None else []
    doc: dict = {
        "schema": BUNDLE_SCHEMA,
        "reason": reason,
        "created": _iso(time.time()),
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "verdict": _verdict(
            reason, ctx, events or process_events,
            error=str(error) if error is not None else None,
        ),
        "events": events,
        "health": ctx.health_snapshot(),
    }
    if error is not None:
        doc["error"] = str(error)[:1000]
    if process_events and process_events != events:
        doc["process_events"] = process_events
    try:
        doc["stall"] = obs_stall.attribution(ctx)
    except Exception:
        pass
    dev = device_doc()
    if dev is not None:
        doc["device"] = dev
    try:
        doc["metrics"] = obs_export.metrics_dict(ctx)
    except Exception as e:  # a dying context must not kill the bundle
        doc["metrics_error"] = str(e)
    tuning = ctx.tuning_doc()
    if tuning is not None:
        doc["tuning"] = tuning
    fleet = getattr(ctx, "fleet", None)
    if fleet:
        doc["fleet"] = fleet
    return doc


def write_bundle(doc: dict, dest_dir: str, keep: int | None = None) -> str:
    """Write one bundle as gzipped JSON under ``dest_dir`` and enforce
    retention (newest ``keep`` bundles survive). Returns the path."""
    st = _STATE
    keep = keep or (st.debug_keep if st is not None else DEFAULT_DEBUG_KEEP)
    os.makedirs(dest_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    trace8 = str(doc.get("trace_id", ""))[:8] or "proc"
    reason = str(doc.get("reason", "bundle")).replace("/", "-")
    path = os.path.join(
        dest_dir, f"bundle-{stamp}-{trace8}-{reason}.json.gz"
    )
    # a same-second re-emit for the same scan must not clobber
    n = 1
    while os.path.exists(path):
        n += 1
        path = os.path.join(
            dest_dir, f"bundle-{stamp}-{trace8}-{reason}.{n}.json.gz"
        )
    with gzip.open(path, "wt") as f:
        json.dump(doc, f)
    bundles = sorted(
        (
            os.path.join(dest_dir, name)
            for name in os.listdir(dest_dir)
            if name.startswith("bundle-") and name.endswith(".json.gz")
        ),
        key=os.path.getmtime,
    )
    for old in bundles[:-keep]:
        try:
            os.unlink(old)
        except OSError:
            pass
    return path


def read_bundle(path: str) -> dict:
    """Load a bundle written by :func:`write_bundle` (gz or plain JSON)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def auto_emit(reason: str, ctx=None, error=None, extra: dict | None = None,
              ) -> str | None:
    """Emit a diagnostic bundle for a failure-shaped moment (terminal
    failure, degraded completion, breaker trip, dead replica). At most
    ONE bundle per (scan, reason); a no-op unless a debug dir is
    configured (``--debug-dir`` / ``TRIVY_TPU_DEBUG_DIR``). Never raises:
    forensics must not take the scan down with it."""
    st = _STATE
    if st is None or not st.debug_dir:
        return None
    from trivy_tpu import obs

    if ctx is None:
        ctx = obs.current()
    key = (ctx.trace_id[:8], reason)
    with st.lock:
        if key in st.emitted:
            return None
        st.emitted.add(key)
    try:
        doc = build_bundle(ctx=ctx, reason=reason, error=error)
        if extra:
            doc.update(extra)
        path = write_bundle(doc, st.debug_dir)
    except Exception as e:
        logger.warning("debug bundle emit (%s) failed: %s", reason, e)
        return None
    logger.warning("diagnostic bundle written: %s (%s)", path, reason)
    return path


# install the span hook at import when enabled: importing this module is
# how a subsystem opts its process into the recorder (commands, the scan
# server, mesh, and bench all do)
if enabled():
    _state()
