"""Trace/metrics/profile file export.

- :func:`write_chrome_trace` — Chrome trace-event JSON (the format Perfetto
  and ``chrome://tracing`` load): one ``X`` complete event per span, one
  named track per pipeline stage (and per device stream — mesh dispatch
  spans are named per device), thread_name metadata events labeling tracks.
  Remote contexts joined via ``TraceContext.ingest_remote`` (a server's
  half of a client-mode scan) render as additional processes (pid 2, 3,
  ...) in the same timeline, timestamp-aligned via wall clocks, so one
  file shows client tracks + server tracks + device streams under one
  trace id.
- :func:`write_metrics_json` — the aggregate view: per-stage histograms
  (count/total/mean/p50/p95/max), counters, sample stats, the stall-
  attribution verdict, and the per-rule/per-bucket profile. ``bench.py``
  embeds this dict into BENCH reps.
- :func:`write_profile_json` — just the cost-attribution view: the merged
  (client+server) per-rule/per-bucket profile plus the stall verdict and
  stage totals it must stay consistent with.
- :func:`context_doc` — the wire form of a context (bounded events +
  aggregates + profile) a scan server returns in its response.
- :func:`write_timeseries_json` — the live-telemetry series (link MB/s,
  arena occupancy, queue depths, per-device busy, progress) recorded by
  an attached :class:`trivy_tpu.obs.timeseries.Sampler`; the same series
  render into ``--trace-out`` timelines as Perfetto **counter tracks**
  (``"ph": "C"`` events), local and remote alike.

Every path-based writer gzips transparently when the destination ends in
``.gz`` — merged cross-process traces get large.
"""

from __future__ import annotations

import json
import os

from trivy_tpu.obs import TraceContext, percentile, wire_span_stats
from trivy_tpu.obs import recorder as _recorder
from trivy_tpu.obs import stall as _stall

# bounds for the wire form of a context (a scan response rides HTTP):
# events beyond the cap are dropped from the remote timeline — aggregates
# and the profile never drop — and per-stage reservoirs are truncated
WIRE_MAX_EVENTS = 4096
WIRE_RESERVOIR = 256


def _dump(doc: dict, dest, indent: int | None = None) -> None:
    """Write JSON to a file object or path; paths ending in .gz gzip."""
    if hasattr(dest, "write"):
        json.dump(doc, dest, indent=indent)
        return
    if str(dest).endswith(".gz"):
        import gzip

        with gzip.open(dest, "wt") as f:
            json.dump(doc, f, indent=indent)
    else:
        with open(dest, "w") as f:
            json.dump(doc, f, indent=indent)


def _wire_values(values: list[float]) -> list[float]:
    """Bound a stage's duration reservoir for the wire by a uniform strided
    pick — a plain ``[:n]`` prefix would bias the receiver's percentiles
    toward the earliest (cold-cache, warm-up) spans of the scan."""
    n = len(values)
    if n <= WIRE_RESERVOIR:
        return values
    step = n / WIRE_RESERVOIR
    return [values[int(i * step)] for i in range(WIRE_RESERVOIR)]


def context_doc(ctx: TraceContext, max_events: int = WIRE_MAX_EVENTS) -> dict:
    """Serialize a context for the wire: bounded raw events (start times
    rebased to the context's creation so the receiver can align them via
    ``created_wall``), exact per-stage aggregates with a bounded percentile
    reservoir, counters, samples, and the scan profile."""
    with ctx._lock:
        events = [
            {
                "name": sp.name,
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "start": round(sp.start - ctx.created, 6),
                "duration": round(sp.duration, 6),
                "thread": sp.thread,
            }
            for sp in ctx.events[:max_events]
        ]
        dropped = ctx.dropped_events + max(0, len(ctx.events) - max_events)
        spans = {
            name: {
                "count": a.count,
                "total": round(a.total, 6),
                "max": round(a.vmax, 6),
                "threads": len(a.threads),
                "values": [round(v, 6) for v in _wire_values(a.values)],
            }
            for name, a in ctx.durations.items()
            if a.count
        }
        counters = dict(ctx.counters)
        samples = {k: [v[0], v[1], v[2]] for k, v in ctx.samples.items()}
        prof = ctx._profile
        prog = ctx._progress
        ts = ctx.timeseries
    doc = {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "pid": os.getpid(),
        "created_wall": ctx.created_wall,
        "root_parent_id": ctx.parent_span_id,
        "events": events,
        "spans": spans,
        "counters": counters,
        "samples": samples,
        "dropped_events": dropped,
    }
    if prof is not None:
        doc["profile"] = prof.to_dict()
    if prog is not None:
        doc["progress"] = prog.snapshot()
    if ts is not None:
        # live-telemetry series ride the wire too (bounded), so a merged
        # client export carries the server's counter tracks
        doc["timeseries"] = ts.to_doc()
    tuning = ctx.tuning_doc()
    if tuning is not None:
        # resolved knobs + the controller decision log: a client-mode scan
        # can replay the SERVER's mid-scan adaptations from its own export
        doc["tuning"] = tuning
    wire = getattr(ctx, "wire", None)
    if wire is not None:
        # the server's compressed-feed wire accounting rides its response
        doc["wire"] = wire
    return doc


def chrome_trace_events(ctx: TraceContext) -> list[dict]:
    """Flatten a context — plus any joined remote contexts — into
    trace-event dicts (sorted by start time per process)."""
    events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": f"trivy-tpu {ctx.name} [{ctx.trace_id}]"},
        }
    ]
    # track per (pid, stage, thread): a stage whose spans run concurrently
    # in N threads (the confirm pool) gets N tracks ("stage", "stage #2",
    # ...) instead of one track with overlapping slices Perfetto would
    # mangle; tids are globally unique across processes
    tids: dict[tuple[int, str, int], int] = {}
    per_stage_threads: dict[tuple[int, str], int] = {}

    def tid_for(pid: int, name: str, thread: int) -> int:
        key = (pid, name, thread)
        t = tids.get(key)
        if t is None:
            t = tids[key] = len(tids) + 1
            skey = (pid, name)
            n = per_stage_threads[skey] = per_stage_threads.get(skey, 0) + 1
            label = name if n == 1 else f"{name} #{n}"
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": t,
                    "args": {"name": label},
                }
            )
        return t

    def emit(pid: int, trace_id: str, name: str, thread: int, span_id,
             parent_id, ts_us: float, dur_s: float) -> None:
        args = {"trace_id": trace_id, "span_id": span_id}
        if parent_id is not None:
            args["parent_span_id"] = parent_id
        events.append(
            {
                "name": name,
                "cat": name.split(".", 1)[0],
                "ph": "X",
                "pid": pid,
                "tid": tid_for(pid, name, thread),
                # clamp: add()-style backdated spans can start a hair
                # before the context's own creation timestamp
                "ts": max(0.0, round(ts_us, 3)),
                "dur": round(dur_s * 1e6, 3),
                "args": args,
            }
        )

    def emit_counters(pid: int, series: dict, base_us: float = 0.0) -> None:
        """Perfetto counter tracks (``"ph": "C"``): one track per telemetry
        series, point timestamps aligned with the span clock."""
        for name, doc in sorted(series.items()):
            for t, v in doc.get("points", ()):
                events.append(
                    {
                        "name": name,
                        "cat": "telemetry",
                        "ph": "C",
                        "pid": pid,
                        "tid": 0,
                        "ts": max(0.0, round(base_us + t * 1e6, 3)),
                        "args": {"value": v},
                    }
                )

    def emit_tuning(pid: int, tuning: dict, base_us: float = 0.0) -> None:
        """Online-controller decisions as Perfetto INSTANT events
        (``"ph": "i"``, process-scoped): each carries the rule, the knob
        delta, and the gauge snapshot that fired it, landing on the same
        timeline as the knob-value counter tracks — so an operator can
        point at any mid-scan knob step and read why."""
        ctl = tuning.get("controller") or {}
        for d in ctl.get("decision_log", ()):
            events.append(
                {
                    "name": f"tuning:{d.get('rule', '?')}",
                    "cat": "tuning",
                    "ph": "i",
                    "s": "p",
                    "pid": pid,
                    "tid": 0,
                    "ts": max(0.0, round(base_us + d.get("t", 0.0) * 1e6, 3)),
                    "args": {
                        "knob": d.get("knob"),
                        "from": d.get("from"),
                        "to": d.get("to"),
                        "gauges": d.get("gauges", {}),
                    },
                }
            )

    with ctx._lock:
        spans = list(ctx.events)
        remote_docs = list(ctx.remote)
        ts = ctx.timeseries
    for sp in sorted(spans, key=lambda s: s.start):
        emit(
            1, ctx.trace_id, sp.name, sp.thread, sp.span_id, sp.parent_id,
            (sp.start - ctx.created) * 1e6, sp.duration,
        )
    if ts is not None:
        emit_counters(1, ts.to_doc())
    # device-lane counter tracks (flight recorder): cumulative compile
    # count and HBM-resident bytes over the scan timeline
    dev_series = _recorder.counter_series(ctx)
    if dev_series:
        emit_counters(1, dev_series)
    local_tuning = ctx.tuning_doc()
    if local_tuning is not None:
        emit_tuning(1, local_tuning)
    for i, doc in enumerate(remote_docs):
        pid = 2 + i
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {
                    "name": f"trivy-tpu {doc.get('name', 'remote')} "
                            f"[{doc.get('trace_id', '')}] (remote)"
                },
            }
        )
        # perf_counter clocks don't compare across processes; align the
        # remote timeline by the wall-clock delta between context creations
        base_us = (
            doc.get("created_wall", ctx.created_wall) - ctx.created_wall
        ) * 1e6
        remote_spans = sorted(doc.get("events", []), key=lambda s: s["start"])
        if remote_spans:
            # wall clocks skew across hosts: a server clock running behind
            # would push the aligned track negative, and the per-event
            # clamp would collapse its early spans onto t=0 — shift the
            # whole track instead so relative timing survives
            first_us = base_us + remote_spans[0]["start"] * 1e6
            if first_us < 0:
                base_us -= first_us
        for sp in remote_spans:
            emit(
                pid, doc.get("trace_id", ""), sp["name"],
                sp.get("thread", 0), sp.get("span_id"), sp.get("parent_id"),
                base_us + sp["start"] * 1e6, sp.get("duration", 0.0),
            )
        if doc.get("timeseries"):
            emit_counters(pid, doc["timeseries"], base_us)
        if doc.get("tuning"):
            emit_tuning(pid, doc["tuning"], base_us)
    fleet = getattr(ctx, "fleet", None)
    if fleet:
        # fleet replicas join the one merged timeline as their own
        # processes, after the remote shard-trace pids: the poller's
        # per-replica health series (scraped on the coordinator's own
        # clock, so base shift 0) render as counter tracks
        for i, (host, rep) in enumerate(
            sorted((fleet.get("replicas") or {}).items())
        ):
            pid = 2 + len(remote_docs) + i
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": f"trivy-tpu fleet replica {host}"},
                }
            )
            if rep.get("series"):
                emit_counters(pid, rep["series"])
    return events


def write_chrome_trace(ctx: TraceContext, dest) -> None:
    """Write Perfetto-loadable trace-event JSON to a path or file object
    (transparent gzip when the path ends in .gz)."""
    with ctx._lock:
        remote_dropped = sum(d.get("dropped_events", 0) for d in ctx.remote)
    doc = {
        "traceEvents": chrome_trace_events(ctx),
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": ctx.trace_id,
            "name": ctx.name,
            "dropped_events": ctx.dropped_events + remote_dropped,
        },
    }
    _dump(doc, dest)


def metrics_dict(ctx: TraceContext) -> dict:
    """Aggregate metrics as one JSON-serializable dict."""
    with ctx._lock:
        counters = dict(sorted(ctx.counters.items()))
        samples = {
            k: (v[0], v[1], v[2]) for k, v in sorted(ctx.samples.items())
        }
        remote_docs = list(ctx.remote)
    doc = {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "spans": {
            name: {k: round(v, 6) for k, v in s.items()}
            for name, s in ctx.stage_stats().items()
        },
        "counters": counters,
        "samples": {
            name: {
                "count": count,
                "mean": round(total / max(1, count), 3),
                "max": vmax,
            }
            for name, (count, total, vmax) in samples.items()
            if count
        },
        "stall": _stall.attribution(ctx),
        "profile": ctx.merged_profile_dict(),
        "dropped_events": ctx.dropped_events,
    }
    if ctx.timeseries is not None:
        # aggregate view of the live-telemetry series (count/mean/max/
        # p50/p95 per series); full points ride --timeseries-out
        doc["timeseries"] = ctx.timeseries.summary()
    if ctx._progress is not None:
        doc["progress"] = ctx._progress.snapshot()
    tuning = ctx.tuning_doc()
    if tuning is not None:
        # effective knobs + decision log: --metrics-out consumers (and the
        # bench reps embedding this dict) see WHAT the scan ran with and
        # every mid-scan change the controller made
        doc["tuning"] = tuning
    wire = getattr(ctx, "wire", None)
    if wire is not None:
        # compressed-feed wire accounting: run-level compression ratio plus
        # the gate/fallback byte counters behind it — only present when the
        # codec actually ran, so compression-off exports stay byte-identical
        doc["wire"] = wire
    dev = _recorder.device_doc()
    if dev is not None:
        # device-lane accounting (flight recorder): compile ledger per
        # (kernel, shape-bucket), recompile storms, and the HBM residency
        # ledger — only present when the recorder observed device work, so
        # recorder-off exports stay byte-identical
        doc["device"] = dev
    fleet = getattr(ctx, "fleet", None)
    if fleet:
        # fleet telemetry plane: per-replica headroom/health summaries
        # (full series points ride --timeseries-out, same split as the
        # local sampler series) — only present on fleet scans with the
        # poller on, so single-host exports stay byte-identical
        doc["fleet"] = {
            "interval_s": fleet.get("interval_s"),
            "replicas": {
                host: {k: v for k, v in rep.items() if k != "series"}
                for host, rep in (fleet.get("replicas") or {}).items()
            },
        }
    if remote_docs:
        doc["remote"] = [
            {
                "trace_id": d.get("trace_id"),
                "name": d.get("name"),
                "spans": {
                    name: {
                        k: round(v, 6) if isinstance(v, float) else v
                        for k, v in wire_span_stats(s).items()
                    }
                    for name, s in sorted((d.get("spans") or {}).items())
                },
                "counters": dict(sorted((d.get("counters") or {}).items())),
            }
            for d in remote_docs
        ]
    return doc


def write_metrics_json(ctx: TraceContext, dest) -> None:
    _dump(metrics_dict(ctx), dest, indent=2)


def profile_dict(ctx: TraceContext) -> dict:
    """The cost-attribution view: merged client+server profile, the stall
    verdict it refines, and local stage totals (ms) so consumers can check
    the per-rule times sum consistently with the pipeline stages."""
    return {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "profile": ctx.merged_profile_dict(),
        "stall": _stall.attribution(ctx),
        "stage_total_ms": {
            name: round(s["total"] * 1e3, 3)
            for name, s in ctx.stage_stats().items()
        },
    }


def write_profile_json(ctx: TraceContext, dest) -> None:
    _dump(profile_dict(ctx), dest, indent=2)


def timeseries_dict(ctx: TraceContext) -> dict:
    """The full live-telemetry view: every sampled series' points (local
    plus any joined remote contexts'), the per-series summary, and the
    final progress snapshot — what ``--timeseries-out`` writes."""
    with ctx._lock:
        remote_docs = list(ctx.remote)
        ts = ctx.timeseries
        prog = ctx._progress
    doc = {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "series": ts.to_doc(max_points=ts._capacity) if ts is not None else {},
        "summary": ts.summary() if ts is not None else {},
    }
    if prog is not None:
        doc["progress"] = prog.snapshot()
    tuning = ctx.tuning_doc()
    if tuning is not None:
        doc["tuning"] = tuning
    remote = [
        {
            "trace_id": d.get("trace_id"),
            "name": d.get("name"),
            "series": d["timeseries"],
            **({"progress": d["progress"]} if d.get("progress") else {}),
        }
        for d in remote_docs
        if d.get("timeseries")
    ]
    if remote:
        doc["remote"] = remote
    fleet = getattr(ctx, "fleet", None)
    if fleet:
        # the full-points twin of metrics_dict's fleet summary block
        doc["fleet"] = fleet
    return doc


def write_timeseries_json(ctx: TraceContext, dest) -> None:
    _dump(timeseries_dict(ctx), dest, indent=2)
