"""Trace/metrics file export.

- :func:`write_chrome_trace` — Chrome trace-event JSON (the format Perfetto
  and ``chrome://tracing`` load): one ``X`` complete event per span, one
  named track per pipeline stage (and per device stream — mesh dispatch
  spans are named per device), thread_name metadata events labeling tracks.
- :func:`write_metrics_json` — the aggregate view: per-stage histograms
  (count/total/mean/p50/p95/max), counters, sample stats, and the stall-
  attribution verdict. ``bench.py`` embeds this dict into BENCH reps.
"""

from __future__ import annotations

import json

from trivy_tpu.obs import TraceContext
from trivy_tpu.obs import stall as _stall


def chrome_trace_events(ctx: TraceContext) -> list[dict]:
    """Flatten a context into trace-event dicts (sorted by start time)."""
    events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": f"trivy-tpu {ctx.name} [{ctx.trace_id}]"},
        }
    ]
    # track per (stage, thread): a stage whose spans run concurrently in N
    # threads (the confirm pool) gets N tracks ("stage", "stage #2", ...)
    # instead of one track with overlapping slices Perfetto would mangle
    tids: dict[tuple[str, int], int] = {}
    per_stage_threads: dict[str, int] = {}

    def tid_for(name: str, thread: int) -> int:
        key = (name, thread)
        t = tids.get(key)
        if t is None:
            t = tids[key] = len(tids) + 1
            n = per_stage_threads[name] = per_stage_threads.get(name, 0) + 1
            label = name if n == 1 else f"{name} #{n}"
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": t,
                    "args": {"name": label},
                }
            )
        return t

    with ctx._lock:
        spans = list(ctx.events)
    for sp in sorted(spans, key=lambda s: s.start):
        args = {"trace_id": ctx.trace_id, "span_id": sp.span_id}
        if sp.parent_id is not None:
            args["parent_span_id"] = sp.parent_id
        events.append(
            {
                "name": sp.name,
                "cat": sp.name.split(".", 1)[0],
                "ph": "X",
                "pid": 1,
                "tid": tid_for(sp.name, sp.thread),
                # clamp: add()-style backdated spans can start a hair
                # before the context's own creation timestamp
                "ts": max(0.0, round((sp.start - ctx.created) * 1e6, 3)),
                "dur": round(sp.duration * 1e6, 3),
                "args": args,
            }
        )
    return events


def write_chrome_trace(ctx: TraceContext, dest) -> None:
    """Write Perfetto-loadable trace-event JSON to a path or file object."""
    doc = {
        "traceEvents": chrome_trace_events(ctx),
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": ctx.trace_id,
            "name": ctx.name,
            "dropped_events": ctx.dropped_events,
        },
    }
    if hasattr(dest, "write"):
        json.dump(doc, dest)
    else:
        with open(dest, "w") as f:
            json.dump(doc, f)


def metrics_dict(ctx: TraceContext) -> dict:
    """Aggregate metrics as one JSON-serializable dict."""
    with ctx._lock:
        counters = dict(sorted(ctx.counters.items()))
        samples = {
            k: (v[0], v[1], v[2]) for k, v in sorted(ctx.samples.items())
        }
    return {
        "trace_id": ctx.trace_id,
        "name": ctx.name,
        "spans": {
            name: {k: round(v, 6) for k, v in s.items()}
            for name, s in ctx.stage_stats().items()
        },
        "counters": counters,
        "samples": {
            name: {
                "count": count,
                "mean": round(total / max(1, count), 3),
                "max": vmax,
            }
            for name, (count, total, vmax) in samples.items()
            if count
        },
        "stall": _stall.attribution(ctx),
        "dropped_events": ctx.dropped_events,
    }


def write_metrics_json(ctx: TraceContext, dest) -> None:
    if hasattr(dest, "write"):
        json.dump(metrics_dict(ctx), dest, indent=2)
    else:
        with open(dest, "w") as f:
            json.dump(metrics_dict(ctx), f, indent=2)
