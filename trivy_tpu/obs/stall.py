"""Stall attribution: turn span aggregates into a per-pipeline verdict.

Span names follow ``pipeline.stage`` (``secret.device_wait``,
``license.dispatch``, ``mesh.d0.dispatch``). The trailing stage component
maps to an attribution bucket; for each pipeline with recorded bucketed
time, the bucket shares are normalized to exactly 100% (largest-remainder
rounding) and printed as a one-line verdict::

    secret: feed-starved 72% / device-bound 18% / confirm-bound 10%

Buckets name the *cause* a pipeline is slow:

- ``queue-bound`` — the scan sat in the server's admission queue before
  it was allowed to start
- ``feed-starved`` — the device loop sat waiting for host batches (walk,
  read, chunk/pack could not keep the accelerator fed)
- ``upload-bound`` — time in dispatch/device_put (host→device link)
- ``device-bound`` — blocking waits on device results (kernel time)
- ``confirm-bound`` — exact host confirmation / host finalize
- ``parse-bound`` / ``eval-bound`` — misconf file parsing vs check eval
"""

from __future__ import annotations

from trivy_tpu.obs import TraceContext

# trailing stage-name component -> attribution bucket
BUCKETS = {
    "queue_wait": "queue-bound",  # admission-queue wait before the scan ran
    "warm_hit": "warm-hit",  # batched persistent dedup-store lookups —
    # a warm re-scan's time goes here instead of upload/device buckets
    "feed_wait": "feed-starved",
    "dispatch": "upload-bound",
    "compress": "codec-bound",  # host-side slab encode (compressed feed)
    "decompress": "codec-bound",  # wire-frame placement + decode launch
    "device_wait": "device-bound",
    "prefilter": "device-bound",  # blocking prefilter-result fetch
    "confirm": "confirm-bound",
    "finalize": "confirm-bound",
    "host_fallback": "confirm-bound",  # degraded-mode exact host rescans
    "parse": "parse-bound",
    "eval": "eval-bound",
}

# stable display order for verdict lines
ORDER = [
    "queue-bound",
    "warm-hit",
    "feed-starved",
    "upload-bound",
    "codec-bound",
    "device-bound",
    "confirm-bound",
    "parse-bound",
    "eval-bound",
]


def _largest_remainder_pcts(totals: dict[str, float]) -> dict[str, int]:
    """Integer percentages summing to exactly 100."""
    grand = sum(totals.values())
    if grand <= 0:
        return {}
    raw = {k: v / grand * 100.0 for k, v in totals.items()}
    floors = {k: int(v) for k, v in raw.items()}
    short = 100 - sum(floors.values())
    # hand the leftover points to the largest fractional remainders
    for k in sorted(raw, key=lambda k: raw[k] - floors[k], reverse=True)[:short]:
        floors[k] += 1
    return floors


def attribution(ctx: TraceContext) -> dict[str, dict[str, int]]:
    """pipeline -> {bucket: integer pct}; percentages sum to 100 per
    pipeline. Pipelines with no bucketed span time are omitted. Remote
    contexts joined via ``ctx.ingest_remote`` contribute their own
    pipelines under a ``server:`` prefix (``server:secret``, ...), so a
    client-mode scan's verdict covers both sides of the wire.

    Stage totals are normalized by the number of distinct threads that
    recorded the stage: confirm-pool spans run in N concurrent workers, so
    their raw sum is up to N× wall time while the device-loop stages
    (feed_wait/dispatch/device_wait) partition one thread's wall time —
    mixing them unnormalized would crown an overlapped confirm pool the
    bottleneck even when the pipeline is device-limited. Dividing by the
    recording-thread count yields each stage's per-worker wall-time share,
    commensurable across serial and pooled stages."""
    items = list(ctx.stage_totals().items())
    items.extend(ctx.remote_stage_totals().items())
    totals: dict[str, dict[str, float]] = {}
    for name, (total, n_threads) in items:
        if "." not in name:
            continue
        pipeline, stage = name.split(".", 1)
        bucket = BUCKETS.get(stage.rsplit(".", 1)[-1])
        if bucket is None:
            continue
        b = totals.setdefault(pipeline, {})
        b[bucket] = b.get(bucket, 0.0) + total / max(1, n_threads)
    return {
        pipeline: pcts
        for pipeline, buckets in sorted(totals.items())
        if (pcts := _largest_remainder_pcts(buckets))
    }


def verdict_lines(ctx: TraceContext) -> list[str]:
    """One formatted verdict line per pipeline, buckets in stable order."""
    lines = []
    for pipeline, pcts in attribution(ctx).items():
        parts = [f"{b} {pcts[b]}%" for b in ORDER if b in pcts]
        lines.append(f"{pipeline}: " + " / ".join(parts))
    return lines
