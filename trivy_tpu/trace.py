"""Lightweight tracing surface (the ``--trace`` analog).

The reference's only tracing is rego evaluation traces plumbed through an
io.Writer (ref: pkg/iac/rego/options.go:34-35, pkg/misconf ScannerOption
Trace). Here spans time the batched pipelines (device dispatch, host
confirm, misconf evaluation, walk) and ``report()`` prints an aggregate
table — the per-batch timing surface SURVEY §5 asks for.

Disabled (zero overhead beyond one bool check) unless ``enable()`` runs,
which the ``--trace`` flag does.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import defaultdict
from contextlib import contextmanager

_enabled = False
_lock = threading.Lock()
_spans: dict[str, list[float]] = defaultdict(list)
_counters: dict[str, int] = defaultdict(int)


def enable() -> None:
    global _enabled
    _enabled = True


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _lock:
        _spans.clear()
        _counters.clear()


@contextmanager
def span(name: str):
    """Time a block under ``name``; no-op when tracing is off."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _spans[name].append(dt)


def add(name: str, seconds: float) -> None:
    if _enabled:
        with _lock:
            _spans[name].append(seconds)


def count(name: str, n: int = 1) -> None:
    """Accumulate an integer counter (byte/item tallies, e.g. the secret
    feed path's bytes_packed / bytes_uploaded / bytes_dedup_hit); no-op
    when tracing is off."""
    if _enabled:
        with _lock:
            _counters[name] += n


def report(out=None) -> None:
    """Aggregate span table (count / total / mean), widest totals first,
    followed by the integer counters."""
    if not _enabled:
        return
    out = out or sys.stderr
    with _lock:
        rows = [
            (name, len(times), sum(times))
            for name, times in _spans.items()
        ]
        counters = sorted(_counters.items())
    if not rows and not counters:
        return
    rows.sort(key=lambda r: -r[2])
    out.write("\n-- trace " + "-" * 51 + "\n")
    if rows:
        out.write(f"{'span':<38}{'count':>7}{'total':>10}{'mean':>10}\n")
        for name, cnt, total in rows:
            out.write(
                f"{name:<38}{cnt:>7}{total:>9.3f}s{total / cnt:>9.4f}s\n"
            )
    if counters:
        out.write(f"{'counter':<45}{'value':>15}\n")
        for name, value in counters:
            out.write(f"{name:<45}{value:>15}\n")
    out.write("-" * 60 + "\n")
