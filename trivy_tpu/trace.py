"""Compat shim over :mod:`trivy_tpu.obs` (the old flat-span-table surface).

The global span table this module used to own is gone: spans now live on
per-scan :class:`trivy_tpu.obs.TraceContext` objects carried in a
contextvar, so back-to-back ``commands.run`` calls and concurrent
server-mode scans no longer accumulate into one process-global dict.
These functions keep the historical call-site spelling and route to the
*current* context — new code should import :mod:`trivy_tpu.obs` directly.
"""

from __future__ import annotations

from trivy_tpu import obs


def enable() -> None:
    obs.enable()


def disable() -> None:
    obs.disable()


def enabled() -> bool:
    return obs.enabled()


def reset() -> None:
    obs.current().reset()


def span(name: str):
    """Time a block under ``name``; no-op when tracing is off."""
    return obs.span(name)


def add(name: str, seconds: float) -> None:
    obs.add(name, seconds)


def count(name: str, n: int = 1) -> None:
    obs.count(name, n)


def report(out=None) -> None:
    obs.report(out)
