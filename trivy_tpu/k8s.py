"""Kubernetes vertical: scan cluster workloads and aggregate per-resource
(ref: pkg/k8s — the reference enumerates a live cluster through the
trivy-kubernetes library, scans each resource, and renders summary/all
reports).

Sources, in order of preference:

- ``--manifests <dir-or-file>``: exported manifests / cluster dumps
  (``kubectl get ... -o yaml|json``, incl. List objects) — works with
  zero cluster access.
- a live cluster via the ``kubectl`` binary when present (``kubectl get
  <kinds> -A -o json``), the no-client-library analog of the reference's
  cluster enumeration.

Each workload document runs through the misconfiguration engine's
kubernetes checks; results aggregate into per-resource rows with a
severity summary, like the reference's summary writer (pkg/k8s/report).
Workload images additionally scan through the registry image source
(``--scan-images``; fanal/image_registry.py pulls them straight from
their registries, matching pkg/k8s scanning images per resource).
"""

from __future__ import annotations

import json
import os
import subprocess

from trivy_tpu import log
from trivy_tpu.misconf.scanner import MisconfScanner, ScannerOption

logger = log.logger("k8s")

WORKLOAD_KINDS = (
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
    "Job", "CronJob",
)
_KUBECTL_KINDS = "pods,deployments,statefulsets,daemonsets,replicasets,jobs,cronjobs"

SEVERITIES = ("CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN")


def _flatten(doc) -> list[dict]:
    """Expand List/Table objects into their items."""
    if not isinstance(doc, dict):
        return []
    if doc.get("kind", "").endswith("List") and isinstance(doc.get("items"), list):
        out = []
        for item in doc["items"]:
            out.extend(_flatten(item))
        return out
    if doc.get("kind") and doc.get("apiVersion"):
        return [doc]
    return []


def load_manifests(path: str) -> list[dict]:
    """Workload documents from a manifest file or directory tree."""
    import yaml

    docs: list[dict] = []
    errors: list[str] = []

    def load_file(p: str) -> None:
        try:
            with open(p, encoding="utf-8", errors="replace") as f:
                text = f.read()
            if p.endswith(".json"):
                docs.extend(_flatten(json.loads(text)))
            else:
                for d in yaml.safe_load_all(text):
                    docs.extend(_flatten(d))
        except Exception as e:
            errors.append(f"{p}: {e}")
            logger.warning("cannot parse %s: %s", p, e)

    if os.path.isdir(path):
        for root, _dirs, names in os.walk(path):
            for name in sorted(names):
                if name.endswith((".yaml", ".yml", ".json")):
                    load_file(os.path.join(root, name))
    else:
        load_file(path)
    if not docs and errors:
        # every input failed: a clean '0 workloads' report would lie
        raise RuntimeError(
            f"no parseable manifests in {path} ({len(errors)} errors; first: "
            f"{errors[0][:200]})"
        )
    return docs


def load_cluster(context: str | None = None) -> list[dict]:
    """Enumerate workloads with kubectl (the zero-dependency cluster path)."""
    cmd = ["kubectl", "get", _KUBECTL_KINDS, "-A", "-o", "json"]
    if context:
        cmd += ["--context", context]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except FileNotFoundError:
        raise RuntimeError(
            "kubectl not found — use --manifests with exported resources"
        ) from None
    except subprocess.TimeoutExpired as e:
        raise RuntimeError("kubectl timed out after 120s") from e
    if proc.returncode != 0:
        raise RuntimeError(f"kubectl failed: {proc.stderr.strip()[:300]}")
    try:
        return _flatten(json.loads(proc.stdout))
    except json.JSONDecodeError as e:
        raise RuntimeError(f"kubectl produced invalid JSON: {e}") from e


def scan_workloads(docs: list[dict], scanner: MisconfScanner | None = None,
                   secret_scanner=None):
    """Per-resource rows carrying both scanner classes the manifest itself
    can produce (ref: the k8s report aggregates every class per resource):
    [{namespace, kind, name, severities{...}, failures[...], secrets[...]}].
    Image vulnerabilities ride the separate --scan-images rows."""
    import yaml

    from trivy_tpu import k8s_node

    scanner = scanner or MisconfScanner(ScannerOption(file_types=["kubernetes"]))
    if secret_scanner is None:
        from trivy_tpu.secret.engine import SecretScanner

        secret_scanner = SecretScanner()
    rows = []
    for doc in docs:
        kind = doc.get("kind", "")
        if k8s_node.is_node_info(doc):
            # node-collector output in the dump: infra assessment rows
            mc = k8s_node.scan_node_info(doc)
            sev = {s: 0 for s in SEVERITIES}
            for f in mc.failures:
                sev[f.severity if f.severity in sev else "UNKNOWN"] += 1
            rows.append({
                "namespace": "node",
                "kind": "NodeInfo",
                "name": mc.file_path.split("/", 1)[-1],
                "severities": sev,
                "failures": list(mc.failures),
                "successes": list(mc.successes),
            })
            continue
        if kind not in WORKLOAD_KINDS:
            continue
        meta = doc.get("metadata", {}) or {}
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        text = yaml.safe_dump(doc, sort_keys=False)
        mc = scanner.scan_file(f"{namespace}/{kind}/{name}.yaml", text.encode(),
                               "kubernetes")
        failures = list(mc.failures) if mc else []
        secret = secret_scanner.scan_bytes(
            f"{namespace}/{kind}/{name}.yaml", text.encode()
        )
        sev = {s: 0 for s in SEVERITIES}
        for f in failures:
            sev[f.severity if f.severity in sev else "UNKNOWN"] += 1
        for sf in secret.findings:
            sev[sf.severity if sf.severity in sev else "UNKNOWN"] += 1
        rows.append({
            "namespace": namespace,
            "kind": kind,
            "name": name,
            "severities": sev,
            "failures": failures,
            "secrets": list(secret.findings),
        })
    rows.sort(key=lambda r: (r["namespace"], r["kind"], r["name"]))
    return rows


def write_summary(rows: list[dict], out, fmt: str = "table",
                  image_rows: list[dict] | None = None) -> None:
    if fmt == "json":
        doc = {
            "Resources": [
                {
                    "Namespace": r["namespace"],
                    "Kind": r["kind"],
                    "Name": r["name"],
                    "Summary": r["severities"],
                    "Misconfigurations": [f.to_dict() for f in r["failures"]],
                    "Secrets": [s.to_dict() for s in r.get("secrets", [])],
                }
                for r in rows
            ],
        }
        if image_rows is not None:
            doc["Images"] = [
                {
                    "Image": r["image"],
                    "Summary": r["severities"],
                    "Findings": r["findings"],
                    "Error": r["error"],
                }
                for r in image_rows
            ]
        json.dump(doc, out, indent=2)
        out.write("\n")
        return
    out.write("\nWorkload Assessment\n")
    header = f"{'Namespace':<16}{'Kind':<13}{'Name':<28}" + "".join(
        f"{s[0]:>4}" for s in SEVERITIES
    )
    out.write(header + "\n")
    out.write("-" * len(header) + "\n")
    for r in rows:
        out.write(
            f"{r['namespace']:<16}{r['kind']:<13}{r['name'][:27]:<28}"
            + "".join(f"{r['severities'][s]:>4}" for s in SEVERITIES)
            + "\n"
        )
    total = sum(sum(r["severities"].values()) for r in rows)
    out.write(f"\n{len(rows)} workloads, {total} misconfigurations\n")
    if image_rows is not None:
        write_image_summary(image_rows, out)


def write_image_summary(image_rows: list[dict], out) -> None:
    out.write("\nWorkload Images\n")
    for r in image_rows:
        sev = " ".join(f"{k[0]}:{v}" for k, v in r["severities"].items() if v)
        status = r["error"] or (sev or "clean")
        out.write(f"  {r['image']:<52} {status}\n")


def workload_images(docs: list[dict]) -> list[str]:
    """Unique container image references across workload pod specs."""
    images: set[str] = set()
    for doc in docs:
        if doc.get("kind") not in WORKLOAD_KINDS:
            continue
        spec = doc.get("spec", {}) or {}
        pod = spec
        # walk template chains (Deployment -> template -> spec, CronJob ->
        # jobTemplate -> template -> spec)
        for key in ("jobTemplate", "template"):
            t = pod.get(key)
            if isinstance(t, dict):
                pod = t.get("spec", t) or {}
        for ckey in ("containers", "initContainers", "ephemeralContainers"):
            for c in pod.get(ckey, []) or []:
                if isinstance(c, dict) and c.get("image"):
                    images.add(str(c["image"]))
    return sorted(images)


def scan_images(images: list[str], cache_dir: str | None = None,
                insecure: bool = False, scanners: list[str] | None = None,
                db=None) -> list[dict]:
    """Scan workload images via the registry source; per-image rows with a
    vulnerability/secret severity summary (pkg/k8s image scanning analog).
    Unreachable images degrade to an error row, never a failed scan."""
    from trivy_tpu.artifact.image import new_image_artifact
    from trivy_tpu.artifact.local_fs import ArtifactOption
    from trivy_tpu.cache import new_cache
    from trivy_tpu.scanner import Scanner
    from trivy_tpu.scanner.local_driver import LocalDriver, ScanOptions

    scanners = scanners or ["vuln", "secret"]
    cache = new_cache("fs" if cache_dir else "memory", cache_dir)
    rows: list[dict] = []
    for image in images:
        sev = {s: 0 for s in SEVERITIES}
        try:
            art = new_image_artifact(
                image, cache,
                ArtifactOption(insecure_registry=insecure),
            )
            report = Scanner(art, LocalDriver(cache, vuln_client=db)).scan_artifact(
                ScanOptions(scanners=scanners)
            )
            findings = []
            for r in report.results:
                for v in r.vulnerabilities:
                    s = v.severity if v.severity in sev else "UNKNOWN"
                    sev[s] += 1
                    findings.append(v.to_dict())
                for s_f in r.secrets:
                    s = s_f.severity if s_f.severity in sev else "UNKNOWN"
                    sev[s] += 1
                    findings.append(s_f.to_dict())
            rows.append({"image": image, "severities": sev,
                         "findings": findings, "error": ""})
        except Exception as e:
            logger.warning("image scan failed for %s: %s", image, e)
            rows.append({"image": image, "severities": sev,
                         "findings": [], "error": str(e)})
    return rows
