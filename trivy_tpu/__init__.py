"""trivy_tpu: a TPU-native security-scanning framework.

Capabilities modeled on the reference scanner (undistro/trivy v0.57.0): scan
container images, filesystems, repositories, SBOMs, VM images and Kubernetes
clusters for vulnerabilities, secrets, licenses and IaC misconfigurations.

The architecture keeps the reference's load-bearing contracts —
Artifact/Driver split (ref: pkg/scanner/scan.go:134-152), the normalized
BlobInfo intermediate (ref: pkg/fanal/types), the content-addressed cache
(ref: pkg/cache) and the analyzer registry (ref: pkg/fanal/analyzer) — but
re-implements the three data-parallel scan engines TPU-first:

* secret scanning: rules compile into a single batched multi-pattern DFA plus
  a keyword prefilter that runs as one-hot matmuls on the MXU
  (``trivy_tpu.ops``), over fixed-size overlapping chunks of file bytes.
* license classification: n-gram similarity as sharded vmap'd int32 matmul /
  top-k over corpus shards (``trivy_tpu.licensing``).
* SBOM -> CVE matching: version-constraint evaluation vectorized as sharded
  lookups (``trivy_tpu.detector``).

Multi-chip scaling uses ``jax.sharding.Mesh`` + ``shard_map`` with XLA
collectives over ICI (``trivy_tpu.parallel``), not RPC fan-out.
"""

__version__ = "0.1.0"
