"""Convert a real trivy-db (bbolt) into the flattened shard layout.

The reference consumes trivy-db directly through bbolt cursors
(ref: pkg/db/db.go, bucket layout shown in the reference's bolt fixtures —
pkg/detector/library/testdata/fixtures/pip.yaml, integration/testdata/
fixtures/db/*.yaml). This build flattens the same data once into per-bucket
JSON shards that load lazily — the host-side layout the batched device
version-compare path wants (advisory boundary versions encode once per
bucket load, constant-time bucket access thereafter).

Output layout (consumed by :class:`trivy_tpu.db.VulnDB`)::

    <out>/metadata.json              (copied when present next to the .db)
    <out>/manifest.json              {"buckets": {"<bucket>": "advisories/<n>.json"}}
    <out>/advisories/<n>.json        {"<bucket>": {"<pkg>": [advisory, ...]}}
    <out>/data-sources.json          {"<bucket>": {"ID":..,"Name":..,"URL":..}}
    <out>/vulnerability/<xx>.json    details sharded by id-hash byte

Advisory rows are normalized at conversion time: trivy-db stores Severity
and Status as integer enums (see integration/testdata/fixtures/db/
debian.yaml: ``Severity: 1.0``, ``Status: 7``); the shard layout stores the
string forms the scan pipeline uses.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

from trivy_tpu import log
from trivy_tpu.db.bolt import BoltDB

logger = log.logger("db:convert")

SEVERITY_NAMES = ["UNKNOWN", "LOW", "MEDIUM", "HIGH", "CRITICAL"]
# trivy-db types.Status enum order
STATUS_NAMES = [
    "unknown",
    "not_affected",
    "affected",
    "fixed",
    "under_investigation",
    "will_not_fix",
    "fix_deferred",
    "end_of_life",
]

DETAIL_SHARDS = 256


def _severity_name(v) -> str:
    if isinstance(v, str):
        return v
    try:
        return SEVERITY_NAMES[int(v)]
    except (ValueError, TypeError, IndexError):
        return "UNKNOWN"


def _status_name(v) -> str:
    if isinstance(v, str):
        return v
    try:
        return STATUS_NAMES[int(v)]
    except (ValueError, TypeError, IndexError):
        return ""


def normalize_advisory(vuln_id: str, raw: dict) -> dict:
    """trivy-db advisory JSON -> shard advisory row (string enums, the
    vulnerability ID denormalized out of the bolt key)."""
    out: dict = {"VulnerabilityID": vuln_id}
    for k in ("FixedVersion", "VulnerableVersions", "PatchedVersions", "Arches"):
        if raw.get(k):
            out[k] = raw[k]
    if "Severity" in raw and raw["Severity"] not in (None, 0, "0"):
        out["Severity"] = _severity_name(raw["Severity"])
    if raw.get("Status"):
        out["Status"] = _status_name(raw["Status"])
    if raw.get("DataSource"):
        out["DataSource"] = raw["DataSource"]
    return out


def detail_shard(vuln_id: str) -> str:
    return hashlib.sha256(vuln_id.encode()).hexdigest()[:2]


def convert_bolt(bolt_path: str, out_dir: str) -> dict:
    """Flatten one trivy-db bbolt file; returns conversion stats."""
    db = BoltDB(bolt_path)
    # idempotent: stale shards from a previous conversion must not merge
    # into (or outlive) this one — entries removed upstream stay removed
    for sub in ("advisories", "vulnerability"):
        path = os.path.join(out_dir, sub)
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)

    manifest: dict[str, str] = {}
    n_advisories = 0
    n_details = 0
    details: dict[str, dict[str, dict]] = {}
    pending_details = 0
    shard_i = 0

    def flush_details() -> None:
        """Merge buffered detail rows into their shard files; bounds RSS on
        a ~1M-row real DB instead of holding every decoded detail at once."""
        nonlocal pending_details
        for shard, rows in details.items():
            path = os.path.join(out_dir, "vulnerability", f"{shard}.json")
            if os.path.exists(path):
                with open(path) as f:
                    old = json.load(f)
                old.update(rows)
                rows = old
            with open(path, "w") as f:
                json.dump(rows, f)
        details.clear()
        pending_details = 0

    for name_b in db.buckets():
        name = name_b.decode("utf-8", "replace")
        if name == "vulnerability":
            for key, value, _sub in db.walk_bucket(name_b):
                vid = key.decode("utf-8", "replace")
                try:
                    details.setdefault(detail_shard(vid), {})[vid] = json.loads(value)
                    n_details += 1
                    pending_details += 1
                    if pending_details >= 100_000:
                        flush_details()
                except (json.JSONDecodeError, TypeError):
                    logger.warning("undecodable vulnerability detail %s", vid)
            continue
        if name == "data-source":
            sources = {}
            for key, value, _sub in db.walk_bucket(name_b):
                try:
                    sources[key.decode("utf-8", "replace")] = json.loads(value)
                except (json.JSONDecodeError, TypeError):
                    pass
            with open(os.path.join(out_dir, "data-sources.json"), "w") as f:
                json.dump(sources, f)
            continue
        # advisory bucket: "<family> <release>" or "<eco>::<source>"
        pkgs: dict[str, list[dict]] = {}
        for pkg_key, _value, sub in db.walk_bucket(name_b):
            pkg = pkg_key.decode("utf-8", "replace")
            rows = []
            for vid_b, raw in sorted(sub.items()):
                try:
                    rows.append(
                        normalize_advisory(
                            vid_b.decode("utf-8", "replace"), json.loads(raw)
                        )
                    )
                except (json.JSONDecodeError, TypeError):
                    logger.warning("undecodable advisory %s/%s", name, vid_b)
            if rows:
                pkgs.setdefault(pkg, []).extend(rows)
                n_advisories += len(rows)
        rel = f"advisories/{shard_i:04d}.json"
        shard_i += 1
        with open(os.path.join(out_dir, rel), "w") as f:
            json.dump({name: pkgs}, f)
        manifest[name] = rel

    flush_details()

    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump({"buckets": manifest, "detail_shards": True}, f)

    # the OCI artifact carries metadata.json next to trivy.db; keep it
    src_meta = os.path.join(os.path.dirname(bolt_path), "metadata.json")
    if os.path.exists(src_meta):
        shutil.copy(src_meta, os.path.join(out_dir, "metadata.json"))

    stats = {
        "buckets": len(manifest),
        "advisories": n_advisories,
        "details": n_details,
    }
    logger.info(
        "converted %s: %d buckets, %d advisories, %d details",
        bolt_path, stats["buckets"], n_advisories, n_details,
    )
    return stats
