"""Read-only bbolt (boltdb) file parser + minimal writer.

The real trivy-db ships as a bbolt B+tree file inside an OCI artifact
(ref: pkg/db/db.go:27-35; bucket schema per aquasecurity/trivy-db and the
reference's bolt fixtures, e.g.
pkg/detector/library/testdata/fixtures/pip.yaml: root buckets
``"<eco>::<source>"`` / ``"<family> <release>"`` / ``data-source`` /
``vulnerability``, one nested bucket per package, key = vulnerability ID,
value = JSON advisory). This module reads that file format directly so a
user-supplied ``trivy.db`` converts into the flattened shard layout without
any Go tooling; the writer exists to build fixture/scale DBs for tests and
benchmarks (the reference does the same with bolt-fixtures,
internal/dbtest/db.go:18-37).

bbolt on-disk format (github.com/etcd-io/bbolt, db.go/page.go):

- fixed-size pages; page header = id u64, flags u16, count u16, overflow u32
- flags: 0x01 branch, 0x02 leaf, 0x04 meta, 0x10 freelist
- meta page body: magic 0xED0CDAED u32, version=2 u32, pageSize u32,
  flags u32, root bucket (pgid u64 + sequence u64), freelist pgid u64,
  high-water pgid u64, txid u64, checksum u64 (FNV-1a over the first 56
  body bytes); two meta pages (0 and 1), highest valid txid wins
- branch element (16 B): pos u32, ksize u32, pgid u64; key at elem+pos
- leaf element (16 B): flags u32, pos u32, ksize u32, vsize u32; key at
  elem+pos, value right after the key; flags&0x01 marks a nested bucket
- nested bucket value = bucket header (root pgid u64, sequence u64);
  root pgid 0 means the bucket is *inline*: its page follows the header
  inside the value
- values larger than one page spill into ``overflow`` contiguous pages
"""

from __future__ import annotations

import os
import struct
from collections.abc import Iterator

MAGIC = 0xED0CDAED
VERSION = 2

FLAG_BRANCH = 0x01
FLAG_LEAF = 0x02
FLAG_META = 0x04
FLAG_FREELIST = 0x10

LEAF_BUCKET = 0x01

PAGE_HDR = 16  # id(8) flags(2) count(2) overflow(4)
LEAF_ELEM = 16
BRANCH_ELEM = 16
BUCKET_HDR = 16  # root pgid(8) sequence(8)

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


class BoltError(Exception):
    pass


class BoltDB:
    """Read-only view over a bbolt file; values returned as bytes."""

    def __init__(self, path: str):
        import mmap

        self.path = path
        self._f = open(path, "rb")
        try:
            # a real trivy-db is hundreds of MB; map it instead of slurping
            # (the parser only does random slicing)
            self._data = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError):  # empty file mmap fails on linux
            self._data = self._f.read()
        if len(self._data) < 2 * 4096:
            raise BoltError("file too small for bbolt meta pages")
        # both meta candidates assume the default 4 KiB page long enough to
        # read the real pageSize from the winning meta
        metas = []
        for off in (0, 4096):
            m = self._read_meta(off)
            if m is not None:
                metas.append(m)
        if not metas:
            raise BoltError("no valid bbolt meta page")
        meta = max(metas, key=lambda m: m["txid"])
        self.page_size = meta["page_size"]
        self.root_pgid = meta["root"]
        if self.page_size != 4096:
            # re-read metas at the true page size (page 1 moves)
            metas = [
                m
                for off in (0, self.page_size)
                if (m := self._read_meta(off)) is not None
            ]
            meta = max(metas, key=lambda m: m["txid"])
            self.root_pgid = meta["root"]

    def _read_meta(self, off: int) -> dict | None:
        body = self._data[off + PAGE_HDR : off + PAGE_HDR + 64]
        if len(body) < 64:
            return None
        magic, version, page_size, _flags = struct.unpack_from("<IIII", body, 0)
        if magic != MAGIC or version != VERSION:
            return None
        root, _seq, _freelist, _hw, txid, checksum = struct.unpack_from(
            "<QQQQQQ", body, 16
        )
        if checksum and checksum != _fnv1a(body[:56]):
            return None
        return {"page_size": page_size, "root": root, "txid": txid}

    # -- page access ---------------------------------------------------------

    def _page(self, pgid: int) -> tuple[int, int, int, int]:
        """(offset, flags, count, overflow) of a page."""
        off = pgid * self.page_size
        if off + PAGE_HDR > len(self._data):
            raise BoltError(f"page {pgid} out of range")
        _pid, flags, count, overflow = struct.unpack_from(
            "<QHHI", self._data, off
        )
        return off, flags, count, overflow

    # -- traversal ------------------------------------------------------------

    def _iter_leaf_at(
        self, base: int, count: int
    ) -> Iterator[tuple[int, bytes, bytes]]:
        """Yield (flags, key, value) from a leaf page body at ``base``
        (start of the element array, i.e. page offset + PAGE_HDR)."""
        d = self._data
        for i in range(count):
            eoff = base + i * LEAF_ELEM
            flags, pos, ksize, vsize = struct.unpack_from("<IIII", d, eoff)
            kstart = eoff + pos
            yield flags, bytes(d[kstart : kstart + ksize]), bytes(
                d[kstart + ksize : kstart + ksize + vsize]
            )

    def _iter_node(self, pgid: int) -> Iterator[tuple[int, bytes, bytes]]:
        """Depth-first key iteration of the B+tree rooted at page ``pgid``."""
        off, flags, count, _overflow = self._page(pgid)
        base = off + PAGE_HDR
        if flags & FLAG_LEAF:
            yield from self._iter_leaf_at(base, count)
        elif flags & FLAG_BRANCH:
            d = self._data
            for i in range(count):
                eoff = base + i * BRANCH_ELEM
                _pos, _ksize, child = struct.unpack_from("<IIQ", d, eoff)
                yield from self._iter_node(child)
        else:
            raise BoltError(f"page {pgid}: unexpected flags {flags:#x}")

    def _iter_bucket_value(
        self, value: bytes
    ) -> Iterator[tuple[int, bytes, bytes]]:
        """Iterate a nested bucket from its leaf value (header + optional
        inline page)."""
        root, _seq = struct.unpack_from("<QQ", value, 0)
        if root != 0:
            yield from self._iter_node(root)
            return
        # inline bucket: a pageless leaf page embedded after the header;
        # element positions are relative to each element's own start, so
        # iterating over the value slice directly is exact
        _pid, flags, count, _ov = struct.unpack_from("<QHHI", value, BUCKET_HDR)
        if not flags & FLAG_LEAF:
            raise BoltError("inline bucket without leaf flag")
        d = value[BUCKET_HDR:]
        for i in range(count):
            eoff = PAGE_HDR + i * LEAF_ELEM
            eflags, pos, ksize, vsize = struct.unpack_from("<IIII", d, eoff)
            kstart = eoff + pos
            yield eflags, d[kstart : kstart + ksize], d[
                kstart + ksize : kstart + ksize + vsize
            ]

    # -- public API -----------------------------------------------------------

    def buckets(self) -> list[bytes]:
        """Top-level bucket names."""
        return [
            k
            for flags, k, _v in self._iter_node(self.root_pgid)
            if flags & LEAF_BUCKET
        ]

    def walk_bucket(
        self, name: bytes
    ) -> Iterator[tuple[bytes, bytes | None, dict[bytes, bytes]]]:
        """Iterate a top-level bucket.

        Yields ``(key, value, {})`` for plain keys and
        ``(key, None, {subkey: subvalue})`` for nested buckets (the
        trivy-db package level).
        """
        for flags, k, v in self._iter_node(self.root_pgid):
            if k != name or not flags & LEAF_BUCKET:
                continue
            for sflags, sk, sv in self._iter_bucket_value(v):
                if sflags & LEAF_BUCKET:
                    sub = {
                        bytes(k2): bytes(v2)
                        for f2, k2, v2 in self._iter_bucket_value(sv)
                        if not f2 & LEAF_BUCKET
                    }
                    yield bytes(sk), None, sub
                else:
                    yield bytes(sk), bytes(sv), {}
            return


class BoltWriter:
    """Minimal bbolt writer producing files :class:`BoltDB` (and bbolt
    itself) can read: sequentially allocated pages, multi-level branch
    pages when needed, no freelist reuse. Keys must be pre-sorted per
    bucket for valid B+tree ordering."""

    def __init__(self, page_size: int = 4096):
        self.page_size = page_size
        self.pages: list[bytes] = []  # data pages, pgid = index + 4

    def _alloc(self, raw: bytes, flags: int, count: int) -> tuple[int, int]:
        """Store a page body; returns (pgid, overflow)."""
        body_cap = self.page_size - PAGE_HDR
        overflow = (
            0 if len(raw) <= body_cap else -(-(len(raw) - body_cap) // self.page_size)
        )
        pgid = 4 + len(self.pages)
        total = (1 + overflow) * self.page_size
        page = struct.pack("<QHHI", pgid, flags, count, overflow) + raw
        page += b"\x00" * (total - len(page))
        for i in range(0, total, self.page_size):
            self.pages.append(page[i : i + self.page_size])
        return pgid, overflow

    def _write_leaf(self, items: list[tuple[int, bytes, bytes]]) -> int:
        """One leaf page (caller splits batches; big values ride overflow
        pages)."""
        n = len(items)
        cursor = n * LEAF_ELEM
        elems = b""
        data = b""
        for i, (flags, k, v) in enumerate(items):
            rel = cursor - i * LEAF_ELEM
            elems += struct.pack("<IIII", flags, rel, len(k), len(v))
            data += k + v
            cursor += len(k) + len(v)
        pgid, _ = self._alloc(elems + data, FLAG_LEAF, n)
        return pgid

    def _write_tree(self, items: list[tuple[int, bytes, bytes]]) -> int:
        """Split items across leaves and build branches bottom-up."""
        if not items:
            return self._write_leaf([])
        body_cap = self.page_size - PAGE_HDR
        leaves: list[tuple[bytes, int]] = []  # (first key, pgid)
        batch: list[tuple[int, bytes, bytes]] = []
        used = 0
        for it in items:
            sz = LEAF_ELEM + len(it[1]) + len(it[2])
            # a single huge item gets its own page (+overflow)
            if batch and used + sz > body_cap:
                leaves.append((batch[0][1], self._write_leaf(batch)))
                batch, used = [], 0
            batch.append(it)
            used += sz
        if batch:
            leaves.append((batch[0][1], self._write_leaf(batch)))
        # build branch levels until a single root remains
        level = leaves
        while len(level) > 1:
            nxt: list[tuple[bytes, int]] = []
            bb: list[tuple[bytes, int]] = []
            bused = 0
            for key, pgid in level:
                sz = BRANCH_ELEM + len(key)
                if bb and bused + sz > body_cap:
                    nxt.append((bb[0][0], self._write_branch(bb)))
                    bb, bused = [], 0
                bb.append((key, pgid))
                bused += sz
            if bb:
                nxt.append((bb[0][0], self._write_branch(bb)))
            level = nxt
        return level[0][1]

    def _write_branch(self, children: list[tuple[bytes, int]]) -> int:
        n = len(children)
        cursor = n * BRANCH_ELEM
        elems = b""
        data = b""
        for i, (key, pgid) in enumerate(children):
            rel = cursor - i * BRANCH_ELEM
            elems += struct.pack("<IIQ", rel, len(key), pgid)
            data += key
            cursor += len(key)
        pgid, _ = self._alloc(elems + data, FLAG_BRANCH, n)
        return pgid

    def write(self, path: str, buckets: dict[bytes, dict]) -> None:
        """``buckets``: name -> {key: bytes-value | dict (nested bucket)}."""

        def bucket_value(content: dict) -> bytes:
            items: list[tuple[int, bytes, bytes]] = []
            for k in sorted(content):
                v = content[k]
                if isinstance(v, dict):
                    items.append((LEAF_BUCKET, k, bucket_value(v)))
                else:
                    items.append((0, k, v))
            root = self._write_tree(items)
            return struct.pack("<QQ", root, 0)

        top: list[tuple[int, bytes, bytes]] = []
        for name in sorted(buckets):
            top.append((LEAF_BUCKET, name, bucket_value(buckets[name])))
        root_pgid = self._write_tree(top)

        # freelist page (empty) and meta pages
        freelist_pgid = 4 + len(self.pages)
        self.pages.append(
            struct.pack("<QHHI", freelist_pgid, FLAG_FREELIST, 0, 0).ljust(
                self.page_size, b"\x00"
            )
        )
        high_water = 4 + len(self.pages)

        def meta_page(pgid: int, txid: int) -> bytes:
            body = struct.pack(
                "<IIII", MAGIC, VERSION, self.page_size, 0
            ) + struct.pack(
                "<QQQQQ", root_pgid, 0, freelist_pgid, high_water, txid
            )
            body += struct.pack("<Q", _fnv1a(body))
            page = struct.pack("<QHHI", pgid, FLAG_META, 0, 0) + body
            return page.ljust(self.page_size, b"\x00")

        with open(path, "wb") as f:
            f.write(meta_page(0, 0))
            f.write(meta_page(1, 1))
            # pages 2-3 reserved in real bbolt for the initial freelist and
            # an empty leaf; keep placeholders so pgids 4.. line up
            f.write(struct.pack("<QHHI", 2, FLAG_FREELIST, 0, 0).ljust(self.page_size, b"\x00"))
            f.write(struct.pack("<QHHI", 3, FLAG_LEAF, 0, 0).ljust(self.page_size, b"\x00"))
            for p in self.pages:
                f.write(p)
