"""Vulnerability database (ref: pkg/db + aquasecurity/trivy-db).

The reference distributes a bbolt DB as an OCI artifact with buckets
``"<family> <release>"`` (OS advisories) / ``"<eco>::<source>"`` (library
advisories) plus a ``vulnerability`` detail bucket. This build flattens the
same logical schema into immutable JSON shards loaded into hash indexes —
the host-side layout that feeds the batched device version-compare path
(advisory boundary versions encode once per load, packages join by name
host-side, comparisons run vectorized on device).

Directory layout::

    <db_dir>/metadata.json        {"Version": 2, "UpdatedAt": ..., "NextUpdate": ...}
    <db_dir>/advisories.json      {"<bucket>": {"<pkg>": [advisory, ...]}}
    <db_dir>/vulnerability.json   {"<vuln-id>": {detail}}

Both single files and ``advisories/<n>.json`` shard directories load.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any

from trivy_tpu import log

logger = log.logger("db")

SCHEMA_VERSION = 2


@dataclass
class Advisory:
    """One advisory row (trivy-db schema: OS rows carry FixedVersion,
    library rows carry VulnerableVersions/PatchedVersions ranges)."""

    vulnerability_id: str
    fixed_version: str = ""
    vulnerable_versions: list[str] = field(default_factory=list)
    patched_versions: list[str] = field(default_factory=list)
    arches: list[str] = field(default_factory=list)
    status: str = ""
    severity: str = ""  # per-distro severity override
    data_source: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Advisory":
        return cls(
            vulnerability_id=d.get("VulnerabilityID", ""),
            fixed_version=d.get("FixedVersion", ""),
            vulnerable_versions=list(d.get("VulnerableVersions", []) or []),
            patched_versions=list(d.get("PatchedVersions", []) or []),
            arches=list(d.get("Arches", []) or []),
            status=d.get("Status", ""),
            severity=d.get("Severity", ""),
            data_source=dict(d.get("DataSource", {}) or {}),
        )


class VulnDB:
    """Loaded advisory + detail indexes.

    Two storage modes share one API: eager (``buckets``/``details`` dicts,
    used by fixtures and tests) and lazy (a ``manifest.json`` maps bucket
    names to per-bucket shard files written by
    :func:`trivy_tpu.db.convert.convert_bolt`; details load per hash
    shard). Lazy mode keeps full-trivy-db startup constant-time — the
    bbolt-cursor equivalent of the reference (pkg/db/db.go).
    """

    def __init__(
        self,
        buckets: dict[str, dict[str, list[Advisory]]],
        details: dict[str, dict],
        metadata: dict | None = None,
    ):
        self.buckets = buckets
        self.details = details
        self.metadata = metadata or {}
        self.db_dir = ""  # source directory, when loaded from disk
        self.data_sources: dict[str, dict] = {}
        self._prefix_index: dict[str, list[str]] = {}
        self._merged_prefix: dict[str, dict[str, list[Advisory]]] = {}
        # lazy mode state
        self._manifest: dict[str, str] = {}
        self._lazy_loaded: set[str] = set()
        self._detail_shards = False
        self._detail_loaded: set[str] = set()

    # -- advisory lookup ----------------------------------------------------

    def _ensure_bucket(self, bucket: str) -> None:
        if bucket in self._lazy_loaded or bucket not in self._manifest:
            return
        path = os.path.join(self.db_dir, self._manifest[bucket])
        with open(path) as f:
            raw = json.load(f)
        source = self.data_sources.get(bucket)
        for bname, pkgs in raw.items():
            dst = self.buckets.setdefault(bname, {})
            for pkg, rows in pkgs.items():
                advs = [Advisory.from_dict(r) for r in rows]
                if source:
                    for a in advs:
                        if not a.data_source:
                            a.data_source = source
                dst.setdefault(pkg, []).extend(advs)
        self._lazy_loaded.add(bucket)

    def get_advisories(self, bucket: str, pkg_name: str) -> list[Advisory]:
        """Exact bucket lookup (OS path: '<family> <release>')."""
        self._ensure_bucket(bucket)
        return self.buckets.get(bucket, {}).get(pkg_name, [])

    def buckets_with_prefix(self, prefix: str) -> list[str]:
        """Library path: every data source under '<eco>::' (ref:
        pkg/detector/library/driver.go:115-142)."""
        if prefix not in self._prefix_index:
            names = set(b for b in self.buckets if b.startswith(prefix))
            names.update(b for b in self._manifest if b.startswith(prefix))
            self._prefix_index[prefix] = sorted(names)
        return self._prefix_index[prefix]

    def prefix_advisories(self, prefix: str) -> dict[str, list[Advisory]]:
        """Merged ``pkg -> advisories`` index across every bucket under a
        prefix, built once per prefix — one dict probe per package instead
        of a probe per (package x bucket), which matters when a real DB has
        many '<eco>::<source>' buckets (the bolt-cursor-prefix equivalent,
        ref: pkg/detector/library/driver.go:115-142)."""
        if prefix not in self._merged_prefix:
            merged: dict[str, list[Advisory]] = {}
            for bucket in self.buckets_with_prefix(prefix):
                self._ensure_bucket(bucket)
                for pkg, advs in self.buckets.get(bucket, {}).items():
                    merged.setdefault(pkg, []).extend(advs)
            self._merged_prefix[prefix] = merged
        return self._merged_prefix[prefix]

    def get_detail(self, vuln_id: str) -> dict:
        if self._detail_shards:
            from trivy_tpu.db.convert import detail_shard

            shard = detail_shard(vuln_id)
            if shard not in self._detail_loaded:
                self._detail_loaded.add(shard)
                path = os.path.join(self.db_dir, "vulnerability", f"{shard}.json")
                if os.path.exists(path):
                    with open(path) as f:
                        self.details.update(json.load(f))
        return self.details.get(vuln_id, {})

    # -- freshness (ref: pkg/db/db.go:98-140 NeedsUpdate/validate) ----------

    def next_update(self):
        """metadata NextUpdate as an aware datetime, or None."""
        import datetime

        raw = self.metadata.get("NextUpdate")
        if not raw:
            return None
        try:
            return datetime.datetime.fromisoformat(str(raw).replace("Z", "+00:00"))
        except ValueError:
            return None

    def is_stale(self, now=None) -> bool:
        """True when metadata says a newer DB should exist (NextUpdate in
        the past). A DB without metadata is never 'stale' — fixture DBs
        carry no freshness contract."""
        import datetime

        nu = self.next_update()
        if nu is None:
            return False
        now = now or datetime.datetime.now(datetime.timezone.utc)
        if nu.tzinfo is None:
            nu = nu.replace(tzinfo=datetime.timezone.utc)
        return nu < now

    # -- loading ------------------------------------------------------------

    @classmethod
    def load(cls, db_dir: str) -> "VulnDB":
        meta = {}
        meta_path = os.path.join(db_dir, "metadata.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("Version", SCHEMA_VERSION) != SCHEMA_VERSION:
                raise ValueError(
                    f"unsupported DB schema {meta.get('Version')}, want {SCHEMA_VERSION}"
                )
        buckets: dict[str, dict[str, list[Advisory]]] = {}

        def load_adv_file(path: str) -> None:
            with open(path) as f:
                raw = json.load(f)
            for bucket, pkgs in raw.items():
                dst = buckets.setdefault(bucket, {})
                for pkg, rows in pkgs.items():
                    dst.setdefault(pkg, []).extend(
                        Advisory.from_dict(r) for r in rows
                    )

        db = cls(buckets, {}, meta)
        db.db_dir = db_dir

        # data sources attach to advisory rows at bucket load
        ds_path = os.path.join(db_dir, "data-sources.json")
        if os.path.exists(ds_path):
            with open(ds_path) as f:
                db.data_sources = json.load(f)

        manifest_path = os.path.join(db_dir, "manifest.json")
        shard_dir = os.path.join(db_dir, "advisories")
        single = os.path.join(db_dir, "advisories.json")
        if os.path.exists(manifest_path):
            # lazy mode: buckets load on first access
            with open(manifest_path) as f:
                mf = json.load(f)
            db._manifest = mf.get("buckets", {})
            db._detail_shards = bool(mf.get("detail_shards"))
        else:
            if os.path.exists(single):
                load_adv_file(single)
            if os.path.isdir(shard_dir):
                for name in sorted(os.listdir(shard_dir)):
                    if name.endswith(".json"):
                        load_adv_file(os.path.join(shard_dir, name))

        vpath = os.path.join(db_dir, "vulnerability.json")
        if os.path.exists(vpath):
            with open(vpath) as f:
                db.details = json.load(f)
        elif os.path.isdir(os.path.join(db_dir, "vulnerability")):
            db._detail_shards = True
        logger.debug(
            "loaded DB: %d eager + %d lazy buckets, %d details",
            len(buckets), len(db._manifest), len(db.details),
        )
        return db


def load_default_db(db_repository: str | None, cache_dir: str | None) -> VulnDB | None:
    """DB resolution: explicit --db-repository dir, else <cache>/db.

    A real ``trivy.db`` bbolt file dropped into the DB dir (the file the
    reference's OCI download produces, ref: pkg/db/db.go:27-35) is
    converted to the flattened shard layout on first use and loaded from
    the conversion thereafter.
    """
    candidates = []
    if db_repository:
        candidates.append(db_repository)
    from trivy_tpu.cache.fs import default_cache_dir

    candidates.append(os.path.join(cache_dir or default_cache_dir(), "db"))
    for cand in candidates:
        bolt_path = os.path.join(cand, "trivy.db")
        flat_dir = os.path.join(cand, "flattened")
        if os.path.exists(bolt_path):
            # a corrupt/truncated trivy.db degrades to the next candidate
            # (or a no-DB scan), never a crashed scan
            try:
                if not os.path.exists(os.path.join(flat_dir, "manifest.json")) or (
                    os.path.getmtime(bolt_path)
                    > os.path.getmtime(os.path.join(flat_dir, "manifest.json"))
                ):
                    import glob
                    import shutil

                    from trivy_tpu.db.convert import convert_bolt

                    logger.info("flattening %s (first use)", bolt_path)
                    # stale scratch dirs from crashed prior runs (any pid)
                    for stale in glob.glob(f"{flat_dir}.tmp*") + glob.glob(
                        f"{flat_dir}.old*"
                    ):
                        shutil.rmtree(stale, ignore_errors=True)
                    # convert into a scratch dir, then swap: a crashed or
                    # concurrent conversion can't leave a half-written
                    # flattened dir that a later load trusts
                    tmp_dir = f"{flat_dir}.tmp{os.getpid()}"
                    os.makedirs(tmp_dir, exist_ok=True)
                    try:
                        convert_bolt(bolt_path, tmp_dir)
                    except Exception:
                        shutil.rmtree(tmp_dir, ignore_errors=True)
                        raise
                    old = f"{flat_dir}.old{os.getpid()}"
                    if os.path.exists(flat_dir):
                        os.rename(flat_dir, old)
                        shutil.rmtree(old, ignore_errors=True)
                    os.rename(tmp_dir, flat_dir)
                db = VulnDB.load(flat_dir)
            except Exception as e:
                logger.warning(
                    "cannot use advisory DB %s (%s: %s); continuing without it",
                    bolt_path, type(e).__name__, e,
                )
                continue
            if db.is_stale():
                logger.warning(
                    "advisory DB at %s is stale (NextUpdate %s has passed); "
                    "results may miss recent vulnerabilities",
                    bolt_path, db.metadata.get("NextUpdate"),
                )
            db.db_dir = flat_dir
            return db
        if os.path.isdir(cand) and (
            os.path.exists(os.path.join(cand, "advisories.json"))
            or os.path.isdir(os.path.join(cand, "advisories"))
            or os.path.exists(os.path.join(cand, "manifest.json"))
        ):
            db = VulnDB.load(cand)
            if db.is_stale():
                # a stale DB still scans — but silently missing the newest
                # advisories is worse than a loud warning
                # (ref: pkg/db/db.go NeedsUpdate NextUpdate check)
                logger.warning(
                    "advisory DB at %s is stale (NextUpdate %s has passed); "
                    "results may miss recent vulnerabilities",
                    cand, db.metadata.get("NextUpdate"),
                )
            db.db_dir = cand
            return db
    return None
