"""Multi-chip execution: device meshes, sharded kernels, collectives.

The reference's only parallelism is goroutine fan-out bounded by a weighted
semaphore plus optional client/server RPC offload (ref: SURVEY.md §2.9,
pkg/fanal/analyzer/analyzer.go:403-455, pkg/parallel/pipeline.go). The TPU
equivalent lives here: chunk batches shard over the mesh 'data' axis via
jax.sharding / shard_map, reductions ride ICI collectives (psum), and the
host-side feeder plays the role of the reference's worker pipeline.
"""

from trivy_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    get_mesh,
    pad_batch,
    sharded_match_fn,
)
