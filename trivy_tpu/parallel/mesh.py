"""Device mesh construction and sharded kernel wrappers.

Design (scaling-book recipe): pick a mesh, annotate shardings, let XLA insert
the collectives. Scan workloads here are data-parallel over the chunk-batch
axis — chunks shard across 'data', rule tables are tiny and replicated;
reductions (per-rule hit counts for telemetry) psum over 'data'. The 'model'
axis exists for kernels with a large table dimension (license n-gram corpus
shards, advisory-DB shards) that shard their lookup tables.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trivy_tpu import faults, log, obs
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import recorder as flight

logger = log.logger("parallel:mesh")

# per-device circuit breaker defaults: a device is excluded from dispatch
# after this many CONSECUTIVE failures, then re-probed on an exponential
# backoff schedule (one probe dispatch at a time; success closes, failure
# doubles the backoff)
BREAKER_THRESHOLD = 3
BREAKER_PROBE_BACKOFF = 1.0  # seconds until the first re-probe
BREAKER_MAX_BACKOFF = 60.0
# a half-open probe whose outcome is never reported (scan generator closed
# with the probe batch still in flight) expires after this long, so the
# device is not excluded forever on a process-cached breaker
BREAKER_PROBE_TIMEOUT = 60.0

# breaker state surfaces on the process-global registry so the scan
# server's GET /metrics (which appends this registry) shows open breakers
_BREAKER_OPEN = obs_metrics.REGISTRY.gauge(
    "trivy_tpu_device_breaker_open",
    "1 while the per-device dispatch circuit breaker is open",
    labelnames=("device",),
)
_DEVICE_FAILURES = obs_metrics.REGISTRY.counter(
    "trivy_tpu_device_failures_total",
    "Device dispatch/fetch failures observed by the breaker",
    labelnames=("device",),
)


def link_class(platform: str | None = None) -> str:
    """Coarse host→device link classification for the tuning topology
    fingerprint (``TuningConfig`` autotune records are keyed by device
    kind/count + this): ``host`` for CPU-backend virtual devices (one
    memory bus, no real link), ``tunnel`` when the axon remote-transfer
    tunnel is in play (a serialized ~10 MB/s link whose optimum knobs are
    nothing like local PCIe's), ``pcie`` otherwise. Override with
    ``TRIVY_TPU_LINK_CLASS`` when the heuristic misreads a deployment."""
    import os

    override = os.environ.get("TRIVY_TPU_LINK_CLASS", "")
    if override:
        return override
    if platform is None:
        platform = jax.devices()[0].platform
    if platform in ("cpu", "METAL"):
        return "host"
    if any(k.startswith("AXON_") for k in os.environ):
        return "tunnel"
    return "pcie"


class DevicesUnavailable(RuntimeError):
    """Every dispatch device is circuit-broken (or the device set is empty):
    the caller's last rung is the host fallback, not a retry."""


class CircuitBreaker:
    """Per-device dispatch circuit breaker.

    closed -> open after ``threshold`` consecutive failures; while open the
    device is excluded from :meth:`next_device`. After ``probe_backoff``
    seconds one probe dispatch is allowed (half-open): success closes the
    breaker, failure re-opens it with the backoff doubled (capped at
    ``max_backoff``). All transitions are logged and mirrored to the
    process-global metrics registry.
    """

    def __init__(
        self,
        n_devices: int,
        threshold: int = BREAKER_THRESHOLD,
        probe_backoff: float = BREAKER_PROBE_BACKOFF,
        max_backoff: float = BREAKER_MAX_BACKOFF,
        probe_timeout: float = BREAKER_PROBE_TIMEOUT,
        clock=time.monotonic,
        labels: list[str] | None = None,
    ):
        self.n = n_devices
        self.threshold = threshold
        self.probe_backoff = probe_backoff
        self.max_backoff = max_backoff
        self.probe_timeout = probe_timeout
        self.clock = clock
        self.labels = labels or [f"d{i}" for i in range(n_devices)]
        self._lock = threading.Lock()
        self._fails = [0] * n_devices  # consecutive failures
        self._open = [False] * n_devices
        self._open_until = [0.0] * n_devices  # next probe time while open
        self._backoff = [probe_backoff] * n_devices
        self._probing = [False] * n_devices  # one half-open probe at a time
        self._probe_at = [0.0] * n_devices  # when that probe was handed out
        # register a healthy (0) row per device up front: readers of the
        # breaker gauge — the admission controller's all-devices-open
        # early-shed, operators scraping /metrics — must see every device
        # the breaker covers, not only the ones that have ever failed.
        # setdefault, not set: breakers share the process-global gauge and
        # generic d<N> labels, so a second breaker's construction (e.g. a
        # new value-keyed shared scanner) must not wipe an open row and
        # un-shed an already-degraded fleet
        for lbl in self.labels:
            _BREAKER_OPEN.setdefault(0, device=lbl)

    def record_failure(self, i: int) -> None:
        _DEVICE_FAILURES.inc(device=self.labels[i])
        opened = 0
        with self._lock:
            self._fails[i] += 1
            if self._open[i]:
                if self._probing[i]:
                    # failed probe: re-open with doubled backoff
                    self._probing[i] = False
                    self._backoff[i] = min(
                        self._backoff[i] * 2, self.max_backoff
                    )
                    self._open_until[i] = self.clock() + self._backoff[i]
                    logger.warning(
                        "device %s probe failed; breaker re-opened for %.1fs",
                        self.labels[i], self._backoff[i],
                    )
                # else: a stale in-flight batch failing after the breaker
                # already opened — not a probe outcome, don't punish the
                # recovery schedule for it
            elif self._fails[i] >= self.threshold:
                self._open[i] = True
                self._open_until[i] = self.clock() + self._backoff[i]
                opened = self._fails[i]
                _BREAKER_OPEN.set(1, device=self.labels[i])
                logger.warning(
                    "device %s breaker OPEN after %d consecutive failures; "
                    "re-probing in %.1fs",
                    self.labels[i], self._fails[i], self._backoff[i],
                )
        if opened:
            flight.record(
                "breaker", f"device {self.labels[i]} OPEN",
                {"fails": opened},
            )
            flight.auto_emit("breaker-trip")

    def record_success(self, i: int) -> None:
        with self._lock:
            was_open = self._open[i]
            self._fails[i] = 0
            self._open[i] = False
            self._probing[i] = False
            self._backoff[i] = self.probe_backoff
        if was_open:
            _BREAKER_OPEN.set(0, device=self.labels[i])
            logger.info("device %s recovered; breaker closed", self.labels[i])
            flight.record("breaker", f"device {self.labels[i]} closed")

    def next_device(self, start: int) -> int | None:
        """First dispatchable device scanning round-robin from ``start``:
        closed devices always qualify; an open device qualifies only when
        its probe window has arrived and no probe is already in flight.
        Returns None when nothing is dispatchable."""
        now = self.clock()
        with self._lock:
            for off in range(self.n):
                i = (start + off) % self.n
                if not self._open[i]:
                    return i
                probe_free = (
                    not self._probing[i]
                    or now - self._probe_at[i] >= self.probe_timeout
                )
                if probe_free and now >= self._open_until[i]:
                    # probe-due open device: take it now — waiting for "no
                    # healthy device left" would mean a recovered device is
                    # never probed back in while any peer stays up
                    self._probing[i] = True
                    self._probe_at[i] = now
                    return i
            return None

    def is_open(self, i: int) -> bool:
        with self._lock:
            return self._open[i]

    def try_probe(self, i: int) -> bool:
        """Dispatch gate for callers bound to a FIXED device (the fleet
        coordinator's per-replica workers): True when ``i`` is closed, or
        open with its half-open probe due and unclaimed — in which case
        THIS call claims ``i``'s probe slot (and only ``i``'s; unlike
        :meth:`next_device`, no peer's slot is touched)."""
        now = self.clock()
        with self._lock:
            if not self._open[i]:
                return True
            probe_free = (
                not self._probing[i]
                or now - self._probe_at[i] >= self.probe_timeout
            )
            if probe_free and now >= self._open_until[i]:
                self._probing[i] = True
                self._probe_at[i] = now
                return True
            return False

    def open_devices(self) -> list[int]:
        with self._lock:
            return [i for i in range(self.n) if self._open[i]]

    def grow(self, label: str | None = None) -> int:
        """Append one closed slot (live replica join on an elastic fleet):
        the new device starts healthy with a fresh backoff ladder. Returns
        the new slot's index."""
        with self._lock:
            i = self.n
            self.n += 1
            self.labels.append(label or f"d{i}")
            self._fails.append(0)
            self._open.append(False)
            self._open_until.append(0.0)
            self._backoff.append(self.probe_backoff)
            self._probing.append(False)
            self._probe_at.append(0.0)
        _BREAKER_OPEN.setdefault(0, device=self.labels[i])
        return i

    def trip(self, i: int, reason: str = "") -> None:
        """Force slot ``i`` open NOW (out-of-band death verdict, e.g. the
        fleet telemetry poller observing consecutive dead scrapes) without
        burning the consecutive-failure count: the half-open probe ladder
        still governs recovery, so a replica that comes back is probed in
        on the normal schedule."""
        with self._lock:
            if self._open[i]:
                return
            self._fails[i] = max(self._fails[i], self.threshold)
            self._open[i] = True
            self._open_until[i] = self.clock() + self._backoff[i]
        _BREAKER_OPEN.set(1, device=self.labels[i])
        logger.warning(
            "device %s breaker TRIPPED%s; re-probing in %.1fs",
            self.labels[i], f" ({reason})" if reason else "",
            self._backoff[i],
        )
        flight.record(
            "breaker", f"device {self.labels[i]} OPEN",
            {"forced": True, "reason": reason},
        )
        flight.auto_emit("breaker-trip")

class DeviceBusyTracker:
    """Per-device busy-interval accounting for live utilization telemetry.

    A device is *busy* while at least one dispatched batch has not yet
    fetched: :meth:`begin` at placement, :meth:`end` when the caller
    reports the fetch outcome. Overlapping in-flight windows on one device
    merge into a single busy interval (dispatch is async and pipelined),
    so ``busy_seconds`` is wall time with work in flight — exactly the
    numerator of the sampler's per-interval busy fraction
    (Δbusy_seconds/Δt, the ``trivy_tpu_device_busy_ratio`` gauge).

    Leak shape: a batch dropped without an ``end`` (scan generator closed
    mid-flight) would pin the device busy; :meth:`end` tolerates the
    matching underflow and the sampler stops with the scan, so the error
    is bounded to that scan's final samples.
    """

    def __init__(self, n: int, clock=time.monotonic):
        self.n = max(1, n)
        self.clock = clock
        self._lock = threading.Lock()
        self._inflight = [0] * self.n
        self._busy = [0.0] * self.n
        self._since = [0.0] * self.n

    def begin(self, i: int | None) -> None:
        i = (i or 0) % self.n
        with self._lock:
            if self._inflight[i] == 0:
                self._since[i] = self.clock()
            self._inflight[i] += 1

    def end(self, i: int | None) -> None:
        i = (i or 0) % self.n
        with self._lock:
            if self._inflight[i] <= 0:
                return  # unmatched end (retry bookkeeping); never negative
            self._inflight[i] -= 1
            if self._inflight[i] == 0:
                self._busy[i] += self.clock() - self._since[i]

    def busy_seconds(self) -> list[float]:
        """Cumulative busy wall-time per device, including the currently
        open interval — monotonic, so samplers can safely differentiate."""
        now = self.clock()
        with self._lock:
            return [
                b + (now - s if f > 0 else 0.0)
                for b, s, f in zip(self._busy, self._since, self._inflight)
            ]

    def probe(self) -> dict[str, float]:
        """Telemetry-probe fragment: ``device.dN.busy_seconds_total``
        series (cumulative counters the sampler turns into busy ratios)."""
        return {
            f"device.d{i}.busy_seconds_total": s
            for i, s in enumerate(self.busy_seconds())
        }


try:  # jax >= 0.5 top-level spelling
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map


def get_mesh(n_devices: int | None = None, model: int = 1, devices=None) -> Mesh:
    """A ('data', 'model') mesh over the given (or available, or first n)
    devices. Pass `devices` explicitly when mixing platforms (e.g. virtual
    CPU devices provisioned for a dry run on a TPU host)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if n % model != 0:
        raise ValueError(f"{n} devices not divisible by model={model}")
    arr = np.array(devs).reshape(n // model, model)
    return Mesh(arr, ("data", "model"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Chunk batches: leading batch axis over 'data', bytes replicated."""
    return NamedSharding(mesh, P("data", None))


def pad_batch(chunks: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad the batch axis up to a multiple (padding chunks are all-zero
    bytes: no literal hashes to zero, so they produce no hits)."""
    b = chunks.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return chunks
    return np.concatenate([chunks, np.zeros((rem,) + chunks.shape[1:], chunks.dtype)])


def sharded_match_fn(match_fn, mesh: Mesh, rows_multiple: int = 1):
    """Shard a match kernel's batch axis over the mesh 'data' axis.

    Uses shard_map so the kernel (XLA graph or pallas_call) runs as-is on
    each device's local shard with zero communication; only the
    caller-visible output gather rides ICI. Batch size must be padded to a
    multiple of data_parallelism * rows_multiple (see :func:`pad_batch`).
    """
    fn = flight.instrument_jit(
        "mesh.sharded_match",
        _shard_map(
            match_fn, mesh=mesh, in_specs=(P("data", None),), out_specs=P("data", None)
        ),
    )

    def run(chunks: np.ndarray) -> jax.Array:
        return fn(jnp.asarray(chunks))

    run.data_parallelism = int(mesh.shape["data"]) * rows_multiple
    return run


def single_stream_match_fn(match_fn):
    """Uniform dispatch surface for the single-stream kernel paths (plain
    XLA graph fn, rows-multiple pallas wrapper, mesh-sharded shard_map).

    The secret scanner's transfer workers drive every dispatch flavor
    through the same ``run.dispatch(chunks) -> (async_result, device_idx)``
    API that :func:`round_robin_match_fn` exposes; this wrapper gives the
    one-stream paths that API (device index fixed at 0) and owns the
    ``device.dispatch`` fault-injection gate for them, so the per-batch
    retry ladder sees identical failure shapes on every path. Multiple
    worker threads may call ``dispatch`` concurrently: jax dispatch is
    async and thread-safe, which is exactly how transfers for batch N+1
    overlap the kernel for batch N on a single device.
    """

    def dispatch(chunks: np.ndarray):
        faults.check("device.dispatch", key="d0")
        return match_fn(chunks), 0

    def run(chunks: np.ndarray):
        return dispatch(chunks)[0]

    # deliberately no ``n_streams``: its presence is how callers (and
    # tests) distinguish real multi-device round-robin dispatch
    run.dispatch = dispatch
    return run


def round_robin_match_fn(
    match_fn, devices=None, rows_multiple: int = 1, breaker: CircuitBreaker | None = None
):
    """Multi-stream dispatch: whole batches round-robin across local devices.

    The mesh-sharded collective splits ONE batch across devices — every
    batch still rides a single host→device transfer stream. This wrapper
    instead sends each whole batch to the next device in turn, so the
    transfer for batch N+1 (device k) overlaps the kernel for batch N
    (device j): on multi-chip hosts the effective host→device link
    bandwidth multiplies by the device count. No collectives are involved;
    each dispatch is an independent per-device program (jit compiles one
    executable per placement), and callers fetch results in dispatch order
    exactly as with the single-device path. ``dispatch`` is thread-safe —
    the secret scanner runs one transfer-worker thread per device so the
    per-device host→device copies themselves overlap, not just the
    transfer-vs-kernel phases.

    Failure domain: a :class:`CircuitBreaker` (``run.breaker``) excludes a
    device from the rotation after K consecutive failures and re-probes it
    on a backoff schedule. Dispatch-time failures are recorded here;
    fetch-time outcomes are attributed by the caller via
    ``run.record_result(device, ok)`` — use ``run.dispatch(chunks)`` to get
    the ``(out, device)`` pair that makes attribution possible. When every
    device is open, dispatch raises :class:`DevicesUnavailable` so the
    caller can take its last rung (host fallback) instead of spinning.
    """
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("round_robin_match_fn needs at least one device")
    fn = flight.instrument_jit("mesh.round_robin_match", match_fn)
    lock = threading.Lock()
    state = {"next": 0}
    breaker = breaker or CircuitBreaker(len(devices))

    def dispatch(chunks: np.ndarray) -> tuple[jax.Array, int]:
        with lock:
            i = breaker.next_device(state["next"])
            if i is None:
                raise DevicesUnavailable(
                    f"all {len(devices)} dispatch devices are circuit-broken"
                )
            state["next"] = (i + 1) % len(devices)
        if rows_multiple > 1:
            chunks = pad_batch(chunks, rows_multiple)
        # per-stream span: each device stream gets its own trace track, so
        # a Perfetto view shows whether transfers actually interleave
        ctx = obs.current()
        try:
            faults.check("device.dispatch", key=f"d{i}")
            with ctx.span(f"mesh.d{i}.dispatch"):
                out = fn(jax.device_put(chunks, devices[i]))
        except Exception:
            breaker.record_failure(i)
            raise
        ctx.count(f"mesh.d{i}.batches")
        return out, i

    def run(chunks: np.ndarray) -> jax.Array:
        return dispatch(chunks)[0]

    def record_result(i: int, ok: bool) -> None:
        if ok:
            breaker.record_success(i)
        else:
            breaker.record_failure(i)

    run.dispatch = dispatch
    run.record_result = record_result
    run.breaker = breaker
    run.n_streams = len(devices)
    run.devices = devices
    return run


class StagedDispatch:
    """Place a batch on a device ONCE, then run several kernels against the
    resident rows — the fused-pass dispatch surface (prefilter + anchored
    match + license gram gate all read the same upload).

    Three placement flavors behind one API, mirroring the match-fn wrappers
    above:

    - ``mesh``: rows shard over 'data' via one sharded ``device_put``;
      stages are shard_map'd row-wise kernels.
    - ``devices`` (round-robin): whole batches to the next healthy device,
      per-device :class:`CircuitBreaker`, per-stage jit cached per device.
    - neither: default placement, device index fixed at 0.

    ``put`` owns batch-axis padding and the ``device.dispatch`` fault gate
    (one check per batch, exactly like the legacy ``dispatch``); ``run``
    launches a named stage asynchronously on the resident array. Fetch-time
    outcomes feed back through ``record_result`` as before.
    """

    def __init__(self, mesh=None, devices=None, rows_multiple: int = 1,
                 breaker: CircuitBreaker | None = None):
        self.mesh = mesh
        self.devices = list(devices) if devices is not None else None
        self.rows_multiple = max(1, rows_multiple)
        self._stages: dict = {}
        if mesh is not None:
            self.pad_to = int(mesh.shape["data"]) * self.rows_multiple
            self.n_streams = 1
            self.breaker = None
        elif self.devices:
            self.pad_to = self.rows_multiple
            self.n_streams = len(self.devices)
            self.breaker = breaker or CircuitBreaker(len(self.devices))
            self._lock = threading.Lock()
            self._next = 0
        else:
            self.pad_to = self.rows_multiple
            self.n_streams = 1
            self.breaker = None
        # live utilization telemetry: busy-interval accounting per dispatch
        # target (one slot on the mesh/default flavors, one per round-robin
        # device); the feed path's probe exposes it as busy_seconds counters
        self.busy = DeviceBusyTracker(self.n_streams)

    def add_stage(self, name: str, fn, out_axes: int = 2) -> None:
        """Register a row-wise kernel ``[B, C] -> [B, ...]``. ``out_axes``
        is the output rank (2 for per-rule masks, 1 for per-row flags) —
        the mesh flavor needs it for the shard_map out_specs."""
        if self.mesh is not None:
            spec_out = P("data", None) if out_axes == 2 else P("data")
            fn = _shard_map(
                fn, mesh=self.mesh, in_specs=(P("data", None),),
                out_specs=spec_out,
            )
        self._stages[name] = flight.instrument_jit(f"stage.{name}", fn)

    def has_stage(self, name: str) -> bool:
        return name in self._stages

    def stage_fn(self, name: str):
        """The raw jitted stage (pure, traceable) — bench/warm-up hook."""
        return self._stages[name]

    def put(self, chunks: np.ndarray):
        """Pad + place one batch; returns ``(resident_array, device_idx)``.
        Raises :class:`DevicesUnavailable` when every round-robin device is
        circuit-broken."""
        if self.pad_to > 1:
            chunks = pad_batch(chunks, self.pad_to)
        if self.mesh is not None:
            faults.check("device.dispatch", key="d0")
            dev = jax.device_put(chunks, batch_sharding(self.mesh))
            self.busy.begin(None)
            return dev, None
        if self.devices:
            with self._lock:
                i = self.breaker.next_device(self._next)
                if i is None:
                    raise DevicesUnavailable(
                        f"all {len(self.devices)} dispatch devices are "
                        f"circuit-broken"
                    )
                self._next = (i + 1) % len(self.devices)
            try:
                faults.check("device.dispatch", key=f"d{i}")
                with obs.current().span(f"mesh.d{i}.dispatch"):
                    dev = jax.device_put(chunks, self.devices[i])
            except Exception:
                self.breaker.record_failure(i)
                raise
            obs.current().count(f"mesh.d{i}.batches")
            self.busy.begin(i)
            return dev, i
        faults.check("device.dispatch", key="d0")
        dev = jax.device_put(chunks)
        self.busy.begin(None)
        return dev, None

    def put_parts(self, arrays: tuple):
        """Pad-free multi-array placement: ship a tuple of host arrays to
        ONE device as a unit — the compressed-slab wire frame (flat byte
        buffer + per-row offs/clen/mode), whose decompress stage expands
        them into the resident ``[B, C]`` rows the other stages read.
        Same breaker/fault/span ladder as :meth:`put`; the caller owns
        shape discipline (arrays are shipped exactly as given). The mesh
        flavor is unsupported — a flat wire buffer has no row axis to
        shard, so the scanner gates compression off under a mesh."""
        if self.mesh is not None:
            raise ValueError(
                "put_parts: compressed frames cannot shard over a mesh"
            )
        if self.devices:
            with self._lock:
                i = self.breaker.next_device(self._next)
                if i is None:
                    raise DevicesUnavailable(
                        f"all {len(self.devices)} dispatch devices are "
                        f"circuit-broken"
                    )
                self._next = (i + 1) % len(self.devices)
            try:
                faults.check("device.dispatch", key=f"d{i}")
                with obs.current().span(f"mesh.d{i}.dispatch"):
                    dev = tuple(
                        jax.device_put(a, self.devices[i]) for a in arrays
                    )
            except Exception:
                self.breaker.record_failure(i)
                raise
            obs.current().count(f"mesh.d{i}.batches")
            self.busy.begin(i)
            return dev, i
        faults.check("device.dispatch", key="d0")
        dev = tuple(jax.device_put(a) for a in arrays)
        self.busy.begin(None)
        return dev, None

    def run(self, name: str, dev, device_idx=None):
        """Launch stage ``name`` on an already-resident batch (async).
        ``dev`` may be a tuple (a :meth:`put_parts` frame) — the stage is
        then called with the parts as positional args."""
        if isinstance(dev, tuple):
            return self._stages[name](*dev)
        return self._stages[name](dev)

    def record_result(self, i, ok: bool) -> None:
        # the fetch outcome closes the batch's busy interval on every
        # flavor (i is None on mesh/default placement: slot 0)
        self.busy.end(i)
        if self.breaker is None or i is None:
            return
        if ok:
            self.breaker.record_success(i)
        else:
            self.breaker.record_failure(i)


def corpus_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Corpus fingerprint tables: leading shard axis over 'model', payload
    replicated across 'data'. Used to commit the license n-gram corpus
    (ops/ngram_score) to device memory once, HBM-resident across scans."""
    return NamedSharding(mesh, P(*(("model",) + (None,) * (ndim - 1))))


def sharded_score_fn(score_fn, mesh: Mesh):
    """Shard an n-gram corpus scoring kernel over the 2D mesh: text gram
    rows over 'data', corpus-fingerprint shards over 'model' (PAPER.md §7
    — the first user of the mesh 'model' axis). Each device scores its
    local row block against its local license slab with zero
    communication; out_specs reassemble the global [B, L] score pair.

    ``score_fn`` is :func:`trivy_tpu.ops.ngram_score.build_score_fn`'s
    (rows, keys, credit) -> (full_w, phrase_hits). Batch size must be a
    multiple of the mesh data parallelism (see ``run.data_parallelism``).
    """
    fn = flight.instrument_jit(
        "mesh.sharded_score",
        _shard_map(
            score_fn,
            mesh=mesh,
            in_specs=(
                P("data", None),  # gram rows [B/d, T]
                P("model", None),  # corpus keys [m, Ku] -> local [1, Ku]
                P("model", None, None),  # credit [m, Ku, 2*Ls]
            ),
            out_specs=(P("data", "model"), P("data", "model")),
        ),
    )

    def run(rows, keys, credit):
        return fn(jnp.asarray(rows), keys, credit)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def sharded_gate_fn(gate_fn, mesh: Mesh):
    """Shard the n-gram candidate gate: rows over 'data', corpus keys
    over 'model'; ``gate_fn`` must be built with ``psum_axis='model'``
    (ops/ngram_score.build_gate_fn) so per-shard intersection counts
    reduce to global counts over ICI."""
    fn = flight.instrument_jit(
        "mesh.sharded_gate",
        _shard_map(
            gate_fn,
            mesh=mesh,
            in_specs=(P("data", None), P("model", None)),
            out_specs=P("data"),
        ),
    )

    def run(rows, keys):
        return fn(jnp.asarray(rows), keys)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def sharded_bytes_gate_fn(gate_fn, mesh: Mesh):
    """Shard the raw-bytes shingle gate (ops/ngram_score
    build_bytes_gate_fn): uint8 text rows over 'data', the two shingle
    blooms replicated (they are corpus-global, not per-shard); the
    per-row outputs come back partitioned over 'data' only."""
    fn = flight.instrument_jit(
        "mesh.sharded_bytes_gate",
        _shard_map(
            gate_fn,
            mesh=mesh,
            in_specs=(P("data", None), P(), P()),
            out_specs=(P("data", None), P("data"), P("data")),
        ),
    )

    def run(rows, bloom8, bloom4):
        return fn(jnp.asarray(rows), bloom8, bloom4)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def sharded_bytes_score_fn(score_fn, mesh: Mesh):
    """Shard the raw-bytes scoring kernel (ops/ngram_score
    build_bytes_score_fn): uint8 rows over 'data', corpus shards over
    'model'. Score pairs reassemble as [B, m*Ls] like sharded_score_fn;
    the third output (per-row unique-gram count, corpus-independent and
    identical on every model shard) stays partitioned over 'data' only.
    """
    def body(rows, keys, credit):
        full_w, phrase, n_uniq = score_fn(rows, keys, credit)
        # n_uniq is replicated across 'model'; collapse it explicitly so
        # the out_spec P("data") is sound under shard_map's checker.
        n_uniq = jax.lax.pmax(n_uniq, axis_name="model")
        return full_w, phrase, n_uniq

    fn = flight.instrument_jit(
        "mesh.sharded_bytes_score",
        _shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P("data", None),  # uint8 text rows [B/d, W]
                P("model", None),  # corpus keys [m, Ku] -> local [1, Ku]
                P("model", None, None),  # credit [m, Ku, 2*Ls]
            ),
            out_specs=(P("data", "model"), P("data", "model"), P("data")),
        ),
    )

    def run(rows, keys, credit):
        return fn(jnp.asarray(rows), keys, credit)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def hit_counts_psum(match_fn, mesh: Mesh):
    """Per-rule global hit counts over a sharded batch, reduced with psum
    over ICI — the telemetry/all-gather path exercised by dryrun_multichip."""
    def step(chunks):  # local shard [B/d, C]
        hits = match_fn(chunks)  # [B/d, R] bool
        local = jnp.sum(hits.astype(jnp.int32), axis=0)  # [R]
        return jax.lax.psum(local, axis_name="data")

    return flight.instrument_jit(
        "mesh.hit_counts_psum",
        _shard_map(
            step,
            mesh=mesh,
            in_specs=(P("data", None),),
            out_specs=P(),
        ),
    )
