"""Device mesh construction and sharded kernel wrappers.

Design (scaling-book recipe): pick a mesh, annotate shardings, let XLA insert
the collectives. Scan workloads here are data-parallel over the chunk-batch
axis — chunks shard across 'data', rule tables are tiny and replicated;
reductions (per-rule hit counts for telemetry) psum over 'data'. The 'model'
axis exists for kernels with a large table dimension (license n-gram corpus
shards, advisory-DB shards) that shard their lookup tables.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trivy_tpu import obs

try:  # jax >= 0.5 top-level spelling
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map


def get_mesh(n_devices: int | None = None, model: int = 1, devices=None) -> Mesh:
    """A ('data', 'model') mesh over the given (or available, or first n)
    devices. Pass `devices` explicitly when mixing platforms (e.g. virtual
    CPU devices provisioned for a dry run on a TPU host)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if n % model != 0:
        raise ValueError(f"{n} devices not divisible by model={model}")
    arr = np.array(devs).reshape(n // model, model)
    return Mesh(arr, ("data", "model"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Chunk batches: leading batch axis over 'data', bytes replicated."""
    return NamedSharding(mesh, P("data", None))


def pad_batch(chunks: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad the batch axis up to a multiple (padding chunks are all-zero
    bytes: no literal hashes to zero, so they produce no hits)."""
    b = chunks.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return chunks
    return np.concatenate([chunks, np.zeros((rem,) + chunks.shape[1:], chunks.dtype)])


def sharded_match_fn(match_fn, mesh: Mesh, rows_multiple: int = 1):
    """Shard a match kernel's batch axis over the mesh 'data' axis.

    Uses shard_map so the kernel (XLA graph or pallas_call) runs as-is on
    each device's local shard with zero communication; only the
    caller-visible output gather rides ICI. Batch size must be padded to a
    multiple of data_parallelism * rows_multiple (see :func:`pad_batch`).
    """
    fn = jax.jit(
        _shard_map(
            match_fn, mesh=mesh, in_specs=(P("data", None),), out_specs=P("data", None)
        )
    )

    def run(chunks: np.ndarray) -> jax.Array:
        return fn(jnp.asarray(chunks))

    run.data_parallelism = int(mesh.shape["data"]) * rows_multiple
    return run


def round_robin_match_fn(match_fn, devices=None, rows_multiple: int = 1):
    """Multi-stream dispatch: whole batches round-robin across local devices.

    The mesh-sharded collective splits ONE batch across devices — every
    batch still rides a single host→device transfer stream. This wrapper
    instead sends each whole batch to the next device in turn, so the
    transfer for batch N+1 (device k) overlaps the kernel for batch N
    (device j): on multi-chip hosts the effective host→device link
    bandwidth multiplies by the device count. No collectives are involved;
    each dispatch is an independent per-device program (jit compiles one
    executable per placement), and callers fetch results in dispatch order
    exactly as with the single-device path.
    """
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("round_robin_match_fn needs at least one device")
    fn = jax.jit(match_fn)
    lock = threading.Lock()
    state = {"next": 0}

    def run(chunks: np.ndarray) -> jax.Array:
        with lock:
            i = state["next"]
            state["next"] = (i + 1) % len(devices)
        if rows_multiple > 1:
            chunks = pad_batch(chunks, rows_multiple)
        # per-stream span: each device stream gets its own trace track, so
        # a Perfetto view shows whether transfers actually interleave
        ctx = obs.current()
        with ctx.span(f"mesh.d{i}.dispatch"):
            out = fn(jax.device_put(chunks, devices[i]))
        ctx.count(f"mesh.d{i}.batches")
        return out

    run.n_streams = len(devices)
    run.devices = devices
    return run


def corpus_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Corpus fingerprint tables: leading shard axis over 'model', payload
    replicated across 'data'. Used to commit the license n-gram corpus
    (ops/ngram_score) to device memory once, HBM-resident across scans."""
    return NamedSharding(mesh, P(*(("model",) + (None,) * (ndim - 1))))


def sharded_score_fn(score_fn, mesh: Mesh):
    """Shard an n-gram corpus scoring kernel over the 2D mesh: text gram
    rows over 'data', corpus-fingerprint shards over 'model' (PAPER.md §7
    — the first user of the mesh 'model' axis). Each device scores its
    local row block against its local license slab with zero
    communication; out_specs reassemble the global [B, L] score pair.

    ``score_fn`` is :func:`trivy_tpu.ops.ngram_score.build_score_fn`'s
    (rows, keys, credit) -> (full_w, phrase_hits). Batch size must be a
    multiple of the mesh data parallelism (see ``run.data_parallelism``).
    """
    fn = jax.jit(
        _shard_map(
            score_fn,
            mesh=mesh,
            in_specs=(
                P("data", None),  # gram rows [B/d, T]
                P("model", None),  # corpus keys [m, Ku] -> local [1, Ku]
                P("model", None, None),  # credit [m, Ku, 2*Ls]
            ),
            out_specs=(P("data", "model"), P("data", "model")),
        )
    )

    def run(rows, keys, credit):
        return fn(jnp.asarray(rows), keys, credit)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def sharded_gate_fn(gate_fn, mesh: Mesh):
    """Shard the n-gram candidate gate: rows over 'data', corpus keys
    over 'model'; ``gate_fn`` must be built with ``psum_axis='model'``
    (ops/ngram_score.build_gate_fn) so per-shard intersection counts
    reduce to global counts over ICI."""
    fn = jax.jit(
        _shard_map(
            gate_fn,
            mesh=mesh,
            in_specs=(P("data", None), P("model", None)),
            out_specs=P("data"),
        )
    )

    def run(rows, keys):
        return fn(jnp.asarray(rows), keys)

    run.data_parallelism = int(mesh.shape["data"])
    return run


def hit_counts_psum(match_fn, mesh: Mesh):
    """Per-rule global hit counts over a sharded batch, reduced with psum
    over ICI — the telemetry/all-gather path exercised by dryrun_multichip."""
    def step(chunks):  # local shard [B/d, C]
        hits = match_fn(chunks)  # [B/d, R] bool
        local = jnp.sum(hits.astype(jnp.int32), axis=0)  # [R]
        return jax.lax.psum(local, axis_name="data")

    return jax.jit(
        _shard_map(
            step,
            mesh=mesh,
            in_specs=(P("data", None),),
            out_specs=P(),
        )
    )
