"""Client/server RPC surface (ref: rpc/scanner/service.proto,
rpc/cache/service.proto, pkg/rpc/server, pkg/rpc/client).

The reference speaks Twirp (protobuf-over-HTTP POST). This build keeps the
same service/route shape and split — client-side analysis pushing blobs via
the Cache service, server-side vulnerability detection via Scanner.Scan —
over JSON bodies (the wire format is private to this framework; the route
names stay Twirp-style so operators see familiar paths in logs).
"""

SCANNER_SCAN = "/twirp/trivy.scanner.v1.Scanner/Scan"
CACHE_PUT_ARTIFACT = "/twirp/trivy.cache.v1.Cache/PutArtifact"
CACHE_PUT_BLOB = "/twirp/trivy.cache.v1.Cache/PutBlob"
CACHE_MISSING_BLOBS = "/twirp/trivy.cache.v1.Cache/MissingBlobs"
CACHE_DELETE_BLOBS = "/twirp/trivy.cache.v1.Cache/DeleteBlobs"
HEALTHZ = "/healthz"
VERSION = "/version"
METRICS = "/metrics"

# live scan-progress API: GET /scan/<trace_id>/progress returns the
# monotonically non-decreasing progress snapshot of an in-flight (or
# recently finished) scan joined to that trace id
SCAN_PROGRESS_PREFIX = "/scan/"
SCAN_PROGRESS_SUFFIX = "/progress"


def scan_progress_path(trace_id: str) -> str:
    return f"{SCAN_PROGRESS_PREFIX}{trace_id}{SCAN_PROGRESS_SUFFIX}"


# async job API (admission-controlled servers): POST /scan/submit enqueues
# a Scanner.Scan request and returns a job id (the scan's trace id) plus
# its queue position; GET /scan/<job_id>/result polls it (202 while
# queued/running, 200 with the scan response once done, bounded
# retention); GET /scan/<job_id>/progress is the live-progress half
SCAN_SUBMIT = "/scan/submit"
SCAN_RESULT_SUFFIX = "/result"


def scan_result_path(job_id: str) -> str:
    return f"{SCAN_PROGRESS_PREFIX}{job_id}{SCAN_RESULT_SUFFIX}"


# elastic fleet live-join seam: POST /fleet/register with {"Host": addr}
# asks the coordinator embedded in this server to adopt a replica
# mid-sweep; 404 unless a coordinator installed its hook, 403 on a bad
# token, idempotent on duplicates
FLEET_REGISTER = "/fleet/register"

# the explicit inverse: POST /fleet/deregister with {"Host": addr} asks
# the coordinator to drain that replica out of rotation (queued shards
# hand back to survivors, in-flight attempts finish). Same 404/403/
# idempotency contract as register
FLEET_DEREGISTER = "/fleet/deregister"

# flight-recorder forensics pull: GET /debug/bundle returns this
# process's on-demand diagnostic bundle (ring dump, compile/HBM ledgers,
# verdict) as JSON; token-gated like the per-scan routes, 404 when the
# recorder is disabled
DEBUG_BUNDLE = "/debug/bundle"

# ref: pkg/flag/server_flags.go default token header
DEFAULT_TOKEN_HEADER = "Trivy-Token"
