"""Scan server (ref: pkg/rpc/server/listen.go, server.go).

Serves the Cache and Scanner services over HTTP with optional token-header
auth, /healthz + /version probes, and a Prometheus-text ``GET /metrics``
surface (scan counts, per-stage latency histograms fed from each scan's
trace context, cache hit/miss, dedup bytes, in-flight gauge). Every
Scanner.Scan request runs in its own trace context — concurrent scans
record into disjoint span tables — and long scans emit heartbeat progress
logs. Detection runs server-side against the server's cache + advisory DB;
analysis stays client-side (ref: pkg/commands/artifact/run.go:348-355
split).

With admission control enabled (:mod:`trivy_tpu.rpc.admission`,
``--max-concurrent-scans > 0``) the server becomes an overload-safe
multi-tenant front end: synchronous scans are budget-gated (shed with
429/503 + Retry-After instead of competing for HBM), ``POST /scan/submit``
+ ``GET /scan/<id>/result`` form the async job API (the existing progress
route is the live-poll half), and draining rejects queued jobs loudly.
"""

from __future__ import annotations

import hmac
import json
import os
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from trivy_tpu import log, obs, rpc
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import timeseries as obs_timeseries
from trivy_tpu.scanner import ScanOptions

logger = log.logger("rpc:server")

# progress-log cadence for long-running server scans
HEARTBEAT_SECS = 30.0

# finished scans keep their final progress snapshot for late pollers; the
# table is bounded so trace ids can't accumulate forever
FINISHED_PROGRESS_KEEP = 256


def _progress_wire(snap: dict) -> dict:
    """ScanProgress.snapshot() -> the PascalCase wire form of the progress
    API (one place, so the client helper and tests can't drift)."""
    doc = {
        "FilesWalked": snap["files_walked"],
        "BytesWalked": snap["bytes_walked"],
        "FilesScanned": snap["files_scanned"],
        "BytesScanned": snap["bytes_scanned"],
        "WalkComplete": snap["walk_complete"],
        "Done": snap["done"],
        "Ratio": snap["ratio"],
        "ElapsedSeconds": snap["elapsed_s"],
        "MBs": snap["mbs"],
    }
    if snap.get("eta_s") is not None:
        doc["ETASeconds"] = snap["eta_s"]
    return doc

# request-body ceiling; blobs are analysis metadata, not file contents, so
# 256 MiB is generous headroom while bounding a hostile Content-Length.
# Overridable via TRIVY_TPU_MAX_REQUEST_BYTES, validated once at server
# construction (garbage env kills boot, not the Nth request)
MAX_REQUEST_BYTES = 256 * 1024 * 1024
ENV_MAX_REQUEST_BYTES = "TRIVY_TPU_MAX_REQUEST_BYTES"

# biggest unread POST body worth draining to keep an HTTP/1.1 connection
# alive after an early reply (shed, 401, draining); larger bodies close
# the connection instead of being read just to keep a socket warm
DRAIN_BODY_MAX = 1 * 1024 * 1024


def _resolve_max_request_bytes() -> int:
    from trivy_tpu.rpc.admission import validate_count

    raw = os.environ.get(ENV_MAX_REQUEST_BYTES, "")
    if not raw:
        return MAX_REQUEST_BYTES
    v = validate_count(raw, ENV_MAX_REQUEST_BYTES)
    if v == 0:
        raise ValueError(f"{ENV_MAX_REQUEST_BYTES}: must be > 0, got {raw!r}")
    return v


class DBReloader:
    """Periodic advisory-DB hot swap with in-flight serialization
    (ref: pkg/rpc/server/listen.go:62-80 — the hourly updater waits for
    in-flight requests via paired WaitGroups; here one Condition carries
    both roles: requests wait while a swap runs, the swap waits for the
    in-flight count to drain)."""

    def __init__(self, server: "ScanServer", db_dir: str, interval: float = 3600.0):
        self.server = server
        self.db_dir = db_dir
        self.interval = interval
        self._cond = threading.Condition()
        self._inflight = 0
        self._updating = False
        self._stop = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.reload()
            except Exception as e:
                logger.warning("DB reload failed (keeping current DB): %s", e)

    def reload(self) -> None:
        """Load the DB fresh, then swap it in once no request is mid-scan."""
        from trivy_tpu.db import VulnDB

        new_db = VulnDB.load(self.db_dir)  # load OUTSIDE the lock
        new_db.db_dir = self.db_dir
        with self._cond:
            self._updating = True
            while self._inflight > 0:
                self._cond.wait()
            self.server.driver.vuln_client = new_db
            self._updating = False
            self._cond.notify_all()
        logger.info("advisory DB reloaded from %s", self.db_dir)

    def request_begin(self) -> None:
        with self._cond:
            while self._updating:
                self._cond.wait()
            self._inflight += 1

    def request_end(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()


class ServerMetrics:
    """The server's Prometheus registry plus its standard instruments."""

    def __init__(self):
        r = self.registry = obs_metrics.Registry()
        self.scans = r.counter(
            "trivy_tpu_scans_total", "Completed Scanner.Scan requests"
        )
        self.scan_seconds = r.histogram(
            "trivy_tpu_scan_seconds", "Scanner.Scan wall time",
            buckets=obs_metrics.SCAN_BUCKETS,
        )
        self.stage_seconds = r.histogram(
            "trivy_tpu_stage_seconds",
            "Per-pipeline-stage span latency, fed from scan trace contexts",
            labelnames=("stage",),
            buckets=obs_metrics.SCAN_BUCKETS,
        )
        self.requests = r.counter(
            "trivy_tpu_http_requests_total",
            "RPC requests by service method and status code",
            labelnames=("method", "code"),
        )
        self.request_seconds = r.histogram(
            "trivy_tpu_http_request_seconds", "RPC request wall time",
            labelnames=("method",),
        )
        self.in_flight = r.gauge(
            "trivy_tpu_requests_in_flight", "RPC requests currently executing"
        )
        self.cache_hits = r.counter(
            "trivy_tpu_cache_hits_total",
            "Blob IDs requested via MissingBlobs that were already cached",
        )
        self.cache_misses = r.counter(
            "trivy_tpu_cache_misses_total",
            "Blob IDs requested via MissingBlobs that were absent",
        )
        self.dedup_bytes = r.counter(
            "trivy_tpu_secret_dedup_bytes_total",
            "Corpus bytes resolved from the secret chunk-dedup hit cache",
        )
        # per-rule cost attribution, bounded to the TOP_K hottest rules of
        # each scan (label cardinality stays bounded; the full profile is
        # in the scan's Trace response / --profile-out)
        self.rule_gate_hits = r.counter(
            "trivy_tpu_rule_gate_hits_total",
            "Device prefilter hits by secret rule (top-K per scan)",
            labelnames=("rule",),
        )
        self.rule_confirm_seconds = r.counter(
            "trivy_tpu_rule_confirm_seconds_total",
            "Exact host confirmation wall time by rule (top-K per scan)",
            labelnames=("rule",),
        )
        self.rule_wasted_confirm_seconds = r.counter(
            "trivy_tpu_rule_wasted_confirm_seconds_total",
            "Confirmation time on device hits the host rejected "
            "(gate false positives), by rule (top-K per scan)",
            labelnames=("rule",),
        )

    def observe_scan(self, ctx, seconds: float) -> None:
        """Fold one finished scan's trace context into the registry.
        snapshot() is reservoir-bounded: per-stage histogram counts are
        exact up to obs.RESERVOIR spans per stage per scan and a uniform
        sample beyond."""
        from trivy_tpu.obs import profile as obs_profile

        self.scans.inc()
        self.scan_seconds.observe(seconds)
        for stage, durs in ctx.snapshot().items():
            for d in durs:
                self.stage_seconds.observe(d, stage=stage)
        self.dedup_bytes.inc(ctx.counters.get("secret.bytes_dedup_hit", 0))
        for rid, f in obs_profile.top_rules(ctx.merged_profile_dict()):
            self.rule_gate_hits.inc(f.get("gate_hits", 0), rule=rid)
            self.rule_confirm_seconds.inc(
                f.get("confirm_ms", 0.0) / 1e3, rule=rid
            )
            self.rule_wasted_confirm_seconds.inc(
                f.get("wasted_confirm_ms", 0.0) / 1e3, rule=rid
            )


class ScanServer:
    """Service implementation bound to a cache and a local driver."""

    def __init__(self, cache, vuln_client=None, admission=None):
        from trivy_tpu.rpc.admission import AdmissionController, resolve_admission
        from trivy_tpu.scanner.local_driver import LocalDriver

        self.cache = cache
        self.driver = LocalDriver(cache, vuln_client=vuln_client)
        # validate the telemetry cadence AND the request/admission limits
        # once at construction: garbage TRIVY_TPU_TELEMETRY_INTERVAL /
        # _MAX_REQUEST_BYTES / admission env must kill the server at boot
        # with a clear error, not every scan request with a 500
        self.telemetry_interval = obs_timeseries.default_interval()
        self.max_request_bytes = _resolve_max_request_bytes()
        self.reloader: DBReloader | None = None
        self.metrics = ServerMetrics()
        # admission control (trivy_tpu/rpc/admission.py): an explicit
        # AdmissionConfig wins, else env resolution; disabled configs
        # allocate NOTHING — no worker threads, no per-tenant state, no
        # admission metrics — so an unadmitted server is byte-identical
        # to one predating the controller
        cfg = admission if admission is not None else resolve_admission()
        self.admission = (
            AdmissionController(self, cfg).start() if cfg.enabled else None
        )
        self.started = time.time()
        # graceful-shutdown state: while draining, /healthz reports
        # "draining" (load balancers stop routing) and new RPC requests
        # get 503 + Retry-After; in-flight scans run to completion
        self.draining = False
        # elastic fleet live-join seam: a coordinator embedded in this
        # process installs its register_replica here; None keeps
        # POST /fleet/register a plain 404 with ZERO register state
        # (bench --smoke asserts it). An optional dedicated token gates
        # the seam independently of the scan token
        self.fleet_register_hook = None
        self.fleet_register_token = ""
        # the explicit inverse seam: a coordinator installs its
        # deregister_replica here; same 404-when-absent contract
        self.fleet_deregister_hook = None
        # live progress registry for GET /scan/<trace_id>/progress:
        # in-flight scans map trace id -> their ScanProgress; finished
        # scans keep a bounded table of final snapshots for late pollers
        self._progress_lock = threading.Lock()
        self._progress_active: dict[str, object] = {}
        self._progress_finished: OrderedDict[str, dict] = OrderedDict()

    # -- live progress registry ---------------------------------------------

    def _progress_register(self, trace_id: str, progress) -> None:
        with self._progress_lock:
            self._progress_active[trace_id] = progress

    def _progress_retire(self, trace_id: str) -> None:
        with self._progress_lock:
            prog = self._progress_active.pop(trace_id, None)
            if prog is None:
                return
            self._progress_finished[trace_id] = prog.snapshot()
            self._progress_finished.move_to_end(trace_id)
            while len(self._progress_finished) > FINISHED_PROGRESS_KEEP:
                self._progress_finished.popitem(last=False)

    def progress_snapshot(self, trace_id: str) -> dict | None:
        with self._progress_lock:
            prog = self._progress_active.get(trace_id)
            if prog is not None:
                return prog.snapshot()
            return self._progress_finished.get(trace_id)

    # -- service methods (JSON dict in/out) ---------------------------------

    def scan(self, req: dict, traceparent: str | None = None,
             trace_id: str | None = None, queue_wait_s: float | None = None,
             tenant: str | None = None) -> dict:
        options = ScanOptions(
            scanners=req.get("Options", {}).get("Scanners", ["vuln"]),
            list_all_pkgs=bool(req.get("Options", {}).get("ListAllPkgs")),
        )
        target = req.get("Target", "")
        # fleet shard execution: a request carrying a Shard block runs the
        # ANALYSIS of that shard on this replica (its own device + feed
        # path) and returns the resulting blobs — detection and the merge
        # through the applier stay on the coordinator. Rides the exact
        # same trace-join / progress-registry / sampler / admission
        # plumbing as a detection scan (the async job API works unchanged)
        shard = req.get("Shard")
        # per-request trace context: concurrent scans record into disjoint
        # tables (each handler thread carries its own contextvar value), and
        # the aggregates feed the shared /metrics registry afterwards. When
        # the client sent a traceparent header, this request JOINS that
        # trace — same trace id, root spans parented under the client's
        # rpc.scan span — instead of minting a fresh context. Async jobs
        # pass an explicit trace_id (their job id) so the progress/result
        # APIs share one key even when the submitter sent no traceparent
        joined = obs.parse_traceparent(traceparent)
        with obs.scan_context(
            name=f"server-scan:{target}",
            enabled=True,
            trace_id=joined[0] if joined else trace_id,
            parent_span_id=joined[1] if joined else None,
        ) as ctx:
            if queue_wait_s is not None:
                # the admission queue wait becomes a first-class span: it
                # rides --trace-out, folds into the stall verdict as the
                # `queue-bound` bucket, and ships back in the Trace block
                ctx.add("admission.queue_wait", queue_wait_s)
                ctx.count("admission.queued_ms", int(queue_wait_s * 1e3))
            if tenant is not None:
                ctx.count(f"admission.tenant.{tenant}")
            # live telemetry: one sampler per server-side scan (cadence via
            # TRIVY_TPU_TELEMETRY_INTERVAL, 0 disables) feeding the counter
            # tracks shipped back in the Trace block and the process gauges
            # on GET /metrics; the progress registry serves
            # GET /scan/<trace_id>/progress while this request runs.
            # A fleet shard job joins the COORDINATOR's trace id (so N
            # shards merge into one timeline) but registers progress under
            # its JOB id only: N concurrent shards share one trace id, and
            # registering it would let the first to finish retire (and
            # freeze) a sibling's live progress entry
            progress = ctx.progress()
            if shard is not None:
                progress_keys = [trace_id] if (
                    trace_id and trace_id != ctx.trace_id
                ) else []
            else:
                progress_keys = [ctx.trace_id]
            for key in progress_keys:
                self._progress_register(key, progress)
            # per-request sampler at the cadence validated ONCE at server
            # construction — a garbage TRIVY_TPU_TELEMETRY_INTERVAL fails
            # at boot, not as a 500 on the Nth scan request. (No tuning
            # block is exported here: the server half runs detection over
            # cached blobs, never the device feed, so it has no effective
            # knob set to honestly report — the client's export carries
            # its own.)
            sampler = obs_timeseries.start_sampler(
                ctx, self.telemetry_interval
            )
            try:
                with obs.heartbeat(
                    logger, f"scan of {target or '<unnamed>'}", HEARTBEAT_SECS
                ):
                    t0 = time.perf_counter()
                    if shard is not None:
                        from trivy_tpu.fleet import plan as fleet_plan

                        with ctx.span("server.shard"):
                            blobs = fleet_plan.execute_shard(
                                shard, self.cache
                            )
                        results, os_info = [], None
                    else:
                        with ctx.span("server.scan"):
                            results, os_info = self.driver.scan(
                                target,
                                req.get("ArtifactID", ""),
                                list(req.get("BlobIDs", [])),
                                options,
                            )
                    dt = time.perf_counter() - t0
                progress.finish()
            finally:
                # scan death stops the sampler exactly like completion —
                # the finished table then serves the last honest snapshot
                if sampler is not None:
                    sampler.stop()
                for key in progress_keys:
                    self._progress_retire(key)
            self.metrics.observe_scan(ctx, dt)
        if shard is not None:
            # shard responses carry blobs plus this replica's health delta
            # (skipped files, degradations) so the coordinator's merged
            # report sums SkippedFiles/Degraded exactly like a local scan
            resp = {"Blobs": blobs, "Health": ctx.health_snapshot()}
        else:
            resp = {
                "OS": os_info.to_dict() if os_info else None,
                "Results": [r.to_dict() for r in results],
            }
        if req.get("WantTrace"):
            from trivy_tpu.obs import export as obs_export

            # ship the span table back so the client's --trace-out emits
            # one merged timeline and its report folds in the server stalls
            resp["Trace"] = obs_export.context_doc(ctx)
        return resp

    def put_blob(self, req: dict) -> dict:
        self.cache.put_blob(req["DiffID"], req["BlobInfo"])
        return {}

    def put_artifact(self, req: dict) -> dict:
        self.cache.put_artifact(req["ArtifactID"], req["ArtifactInfo"])
        return {}

    def missing_blobs(self, req: dict) -> dict:
        blob_ids = list(req.get("BlobIDs", []))
        missing_artifact, missing = self.cache.missing_blobs(
            req.get("ArtifactID", ""), blob_ids
        )
        self.metrics.cache_hits.inc(len(blob_ids) - len(missing))
        self.metrics.cache_misses.inc(len(missing))
        return {"MissingArtifact": missing_artifact, "MissingBlobIDs": missing}

    def delete_blobs(self, req: dict) -> dict:
        delete = getattr(self.cache, "delete_blobs", None)
        if delete is not None:
            delete(list(req.get("BlobIDs", [])))
        return {}


_ROUTES = {
    rpc.SCANNER_SCAN: "scan",
    rpc.CACHE_PUT_BLOB: "put_blob",
    rpc.CACHE_PUT_ARTIFACT: "put_artifact",
    rpc.CACHE_MISSING_BLOBS: "missing_blobs",
    rpc.CACHE_DELETE_BLOBS: "delete_blobs",
}


def _make_handler(server: ScanServer, token: str, token_header: str):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through our logger
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _reply(self, code: int, payload: dict, headers: dict | None = None) -> None:
            import gzip as _gzip

            self._status = code
            self._drain_unread_body()
            body = json.dumps(payload).encode()
            accepts_gzip = "gzip" in self.headers.get("Accept-Encoding", "")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if self.close_connection:
                self.send_header("Connection", "close")
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            if accepts_gzip and len(body) > 1024:
                body = _gzip.compress(body)
                self.send_header("Content-Encoding", "gzip")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _drain_unread_body(self) -> None:
            """An early reply (shed, 401, draining, bad route) fires
            before ``_read_body``, leaving the POSTed body unread on the
            HTTP/1.1 keep-alive socket — where the next request parse
            would misread it as a request line and corrupt the
            connection. Sheds are the designed steady-state overload
            answer, so drain small bodies and keep the connection alive
            (the Retry-After retry reuses it); anything over
            :data:`DRAIN_BODY_MAX` closes instead."""
            if self.command != "POST" or getattr(
                self, "_body_consumed", False
            ):
                return
            try:
                length = int(self.headers.get("Content-Length", "0") or 0)
            except ValueError:
                length = -1
            if length == 0:
                return
            if 0 < length <= DRAIN_BODY_MAX:
                try:
                    self.rfile.read(length)
                    self._body_consumed = True
                    return
                except OSError:
                    pass
            self.close_connection = True

        def _token_ok(self) -> bool:
            """Constant-time token check shared by every authenticated
            route — one implementation, so the RPC POSTs and the per-scan
            GETs cannot drift apart. On a token-protected server, tenant
            tokens (admission control's token->tenant map) authenticate
            alongside the server token; every candidate is compared so
            timing reveals neither which token matched nor how much of
            the tenant table was walked. A server WITHOUT ``--token``
            stays open even with tenants configured — tenants alone buy
            fair scheduling (unmatched requests share the ``default``
            tenant), not authentication."""
            if not token:
                return True
            presented = self.headers.get(token_header, "")
            ok = hmac.compare_digest(
                presented.encode("latin-1", "replace"),
                token.encode("latin-1", "replace"),
            )
            if server.admission is not None:
                # the tenant walk runs unconditionally (no early exit on
                # a server-token hit) and is the SAME constant-time
                # matcher tenant_for uses, so auth and tenant resolution
                # cannot drift
                if server.admission.match_token(presented) is not None:
                    ok = True
            return ok

        def _reply_text(self, code: int, body: bytes, content_type: str) -> None:
            self._status = code
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == rpc.HEALTHZ:
                from trivy_tpu import __version__

                # liveness plus the numbers an operator checks first:
                # version, uptime, and the in-flight request count; while
                # draining, Status flips so load balancers stop routing.
                # Admission-controlled servers add their queue snapshot;
                # unadmitted servers keep the exact historical shape
                doc = {
                    "Status": "draining" if server.draining else "ok",
                    "Version": __version__,
                    "UptimeSeconds": round(time.time() - server.started, 1),
                    "InFlight": int(server.metrics.in_flight.value()),
                }
                if server.admission is not None:
                    doc["Admission"] = server.admission.doc()
                # flight-recorder forensics: the last error / degraded /
                # breaker-trip events (with timestamps) from the ring, so
                # one /healthz poll answers "what happened last" without
                # pulling a full bundle
                try:
                    from trivy_tpu.obs import recorder as _flight

                    doc.update(_flight.healthz_doc())
                except Exception:
                    pass
                self._reply(200, doc)
                return
            if self.path == rpc.VERSION:
                from trivy_tpu import __version__

                self._reply(200, {"Version": __version__})
                return
            if self.path == rpc.METRICS:
                # monitoring must outlive admission: this route (like
                # /healthz) deliberately skips the draining check and keeps
                # answering 200 through a drain — the fleet telemetry
                # poller keeps scoring a draining replica from live gauges
                # instead of misreading a refused scrape as replica death.
                # Drain state itself is a gauge so scrapers see it flip.
                server.metrics.registry.gauge(
                    "trivy_tpu_server_draining",
                    "1 while this server drains (sheds new work, keeps "
                    "answering monitoring probes)",
                ).set(1.0 if server.draining else 0.0)
                # server-scoped registry plus the process-global one, which
                # carries the failure-domain gauges (device breaker state,
                # cache degradation, degraded-scan count) — metric names
                # are disjoint between the two
                body = (
                    server.metrics.registry.render()
                    + obs_metrics.REGISTRY.render()
                )
                self._reply_text(200, body.encode(), obs_metrics.CONTENT_TYPE)
                return
            if self.path.startswith(rpc.SCAN_PROGRESS_PREFIX) and (
                self.path.endswith(rpc.SCAN_PROGRESS_SUFFIX)
            ):
                # unlike the aggregate /healthz and /metrics probes, this
                # route exposes per-scan activity keyed by trace id, so a
                # token-protected server requires the token here too (the
                # client helper already sends it). The token check comes
                # BEFORE the trace-id lookup and fails with a uniform 403
                # either way: an unauthenticated probe must not be able to
                # oracle which trace ids exist from a 403/404 split
                if not self._token_ok():
                    self._reply(403, {"error": "invalid token"})
                    return
                trace_id = self.path[
                    len(rpc.SCAN_PROGRESS_PREFIX): -len(rpc.SCAN_PROGRESS_SUFFIX)
                ]
                snap = server.progress_snapshot(trace_id)
                if snap is None:
                    self._reply(404, {"error": f"unknown trace id {trace_id}"})
                    return
                self._reply(200, {"TraceID": trace_id, **_progress_wire(snap)})
                return
            if self.path.startswith(rpc.SCAN_PROGRESS_PREFIX) and (
                self.path.endswith(rpc.SCAN_RESULT_SUFFIX)
            ):
                # async job result poll — same 403-before-lookup order as
                # the progress route (job ids are trace ids)
                if not self._token_ok():
                    self._reply(403, {"error": "invalid token"})
                    return
                if server.admission is None:
                    self._reply(404, {
                        "error": "async job API requires admission control "
                                 "(--max-concurrent-scans > 0)"
                    })
                    return
                job_id = self.path[
                    len(rpc.SCAN_PROGRESS_PREFIX): -len(rpc.SCAN_RESULT_SUFFIX)
                ]
                try:
                    code, payload, headers = server.admission.result(job_id)
                except Exception as e:
                    logger.warning("job result fetch %s failed: %s",
                                   job_id, e)
                    self._reply(500, {"error": str(e)})
                    return
                self._reply(code, payload, headers=headers or None)
                return
            if self.path == rpc.DEBUG_BUNDLE:
                # forensics pull: the fleet coordinator fetches a dead or
                # degraded replica's ring this way and merges it into its
                # own bundle. Token-gated like the per-scan routes (the
                # ring names scan targets); 404 with the recorder off —
                # the disabled path must keep allocating nothing
                if not self._token_ok():
                    self._reply(403, {"error": "invalid token"})
                    return
                from trivy_tpu.obs import recorder as _flight

                if not _flight.enabled():
                    self._reply(404, {"error": "flight recorder disabled"})
                    return
                try:
                    self._reply(200, _flight.build_bundle(reason="on-demand"))
                except Exception as e:
                    self._reply(500, {"error": str(e)})
                return
            self._reply(404, {"error": "not found"})

        def do_POST(self):
            # per-REQUEST flag on a per-CONNECTION handler instance:
            # keep-alive reuses the handler, so a stale True from the
            # previous request would skip the drain and desync the socket
            self._body_consumed = False
            if self.path == rpc.SCAN_SUBMIT:
                self._handle_submit()
                return
            if self.path == rpc.FLEET_REGISTER:
                self._handle_fleet_register()
                return
            if self.path == rpc.FLEET_DEREGISTER:
                self._handle_fleet_deregister()
                return
            method = _ROUTES.get(self.path)
            if method is None:
                self._reply(404, {"error": f"no such route: {self.path}"})
                return
            if server.draining:
                # the client's retry loop honors Retry-After on 503, so a
                # rolling restart redirects traffic without failed scans
                self._reply(
                    503, {"error": "server is draining"},
                    headers={"Retry-After": "1"},
                )
                return
            if not self._token_ok():
                self._reply(401, {"error": "invalid token"})
                return
            adm = server.admission
            tenant = None
            reply_headers = None
            m = server.metrics
            # in-flight covers the BODY READ too: a slow upload must keep
            # drain_and_shutdown waiting (the pre-admission clean-drain
            # guarantee), even though the admission slot is only acquired
            # after the body is fully read — N trickling uploads may pin
            # their connections, never the concurrency budget
            m.in_flight.inc()
            t0 = time.perf_counter()
            try:
                raw, body_err = self._read_body()
                # admission gate for synchronous scans: over-budget
                # requests shed with 429/503 + Retry-After instead of
                # competing for arena slabs and HBM (the client's
                # full-jitter backoff turns the Retry-After into a later
                # successful attempt)
                shed = None
                if adm is not None and method == "scan" \
                        and body_err is None:
                    from trivy_tpu.rpc.admission import SHED_STATUS

                    t_obj = adm.tenant_for(
                        self.headers.get(token_header, "")
                    )
                    reason = adm.try_acquire(t_obj)
                    if reason is not None:
                        ra = adm.retry_after()
                        shed = (SHED_STATUS[reason], {
                            "error": f"admission: {reason}",
                            "Tenant": t_obj.name,
                            "RetryAfterSeconds": ra,
                        })
                        reply_headers = {"Retry-After": str(ra)}
                    else:
                        tenant = t_obj
                if shed is not None:
                    # sheds ride the same request counter/histogram as
                    # admitted traffic — an operator computing error
                    # rates from requests_total must see the 429/503s
                    code, payload = shed
                else:
                    code, payload = self._dispatch(
                        method, tenant=tenant, raw=raw, err=body_err
                    )
            finally:
                # EVERY piece of request accounting (in-flight gauge,
                # request counter, latency histogram) finalizes BEFORE the
                # reply hits the wire: a client that reads its response
                # and immediately scrapes /metrics must see this request
                # completed — not a stale in-flight 1 or a missing count
                # from bookkeeping racing the socket write
                m.in_flight.dec()
                if tenant is not None:
                    adm.release(tenant)
            m.requests.inc(method=method, code=str(code))
            m.request_seconds.observe(
                time.perf_counter() - t0, method=method
            )
            self._reply(code, payload, headers=reply_headers)

        def _handle_fleet_register(self) -> None:
            """POST /fleet/register — the elastic fleet's live-join seam.
            404 unless a coordinator installed its hook (a plain replica
            server keeps zero register state); gated by the same
            ``_token_ok`` path as every authenticated route — or by the
            dedicated register token when one is set — answering 403 on a
            mismatch (the seam is an operator surface; a wrong token here
            is a misconfigured joiner, not an unauthenticated scan)."""
            self._handle_fleet_hook(
                server.fleet_register_hook, "fleet register",
                "fleet_register",
            )

        def _handle_fleet_deregister(self) -> None:
            """POST /fleet/deregister — the explicit inverse of register.
            Same 404/403/400 contract; the hook (the coordinator's
            ``deregister_replica``) reuses the drain hand-back path and is
            idempotent, so a leaver's retry ladder re-POSTing is safe.
            Deliberately NOT refused while draining: a coordinator server
            winding down must still let replicas leave cleanly."""
            self._handle_fleet_hook(
                server.fleet_deregister_hook, "fleet deregister",
                "fleet_deregister", allow_draining=True,
            )

        def _handle_fleet_hook(self, hook, label: str, method: str,
                               allow_draining: bool = False) -> None:
            if hook is None:
                self._reply(
                    404, {"error": "no fleet coordinator on this server"}
                )
                return
            if server.draining and not allow_draining:
                self._reply(
                    503, {"error": "server is draining"},
                    headers={"Retry-After": "1"},
                )
                return
            reg_token = server.fleet_register_token
            if reg_token:
                presented = self.headers.get(token_header, "")
                ok = hmac.compare_digest(
                    presented.encode("latin-1", "replace"),
                    reg_token.encode("latin-1", "replace"),
                )
            else:
                ok = self._token_ok()
            if not ok:
                self._reply(403, {"error": "invalid token"})
                return
            raw, err = self._read_body()
            if err is not None:
                self._reply(*err)
                return
            try:
                req = json.loads(raw or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            host = req.get("Host") if isinstance(req, dict) else None
            if not host or not isinstance(host, str):
                self._reply(400, {"error": "bad request: Host required"})
                return
            try:
                doc = hook(host)
            except Exception as e:
                # a refused join/leave (dead joiner, injected fault)
                # answers loudly and leaves the running fan-out untouched
                logger.warning("%s of %s refused: %s", label, host, e)
                self._reply(502, {"error": str(e)})
                return
            server.metrics.requests.inc(method=method, code="200")
            self._reply(200, doc)

        def _handle_submit(self) -> None:
            """POST /scan/submit — the async half of the job API."""
            if server.draining:
                self._reply(
                    503, {"error": "server is draining"},
                    headers={"Retry-After": "1"},
                )
                return
            if not self._token_ok():
                self._reply(401, {"error": "invalid token"})
                return
            if server.admission is None:
                self._reply(404, {
                    "error": "async job API requires admission control "
                             "(--max-concurrent-scans > 0)"
                })
                return
            raw, err = self._read_body()
            if err is not None:
                self._reply(*err)
                return
            try:
                req = json.loads(raw or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            if not isinstance(req, dict):
                # valid JSON but not an object ([1,2], "x", null) would
                # TypeError below and drop the connection instead of
                # answering — the _read_body contract is an HTTP error
                self._reply(400, {
                    "error": "bad request: body must be a JSON object"
                })
                return
            deadline_s = req.pop("DeadlineSeconds", None)
            if deadline_s is not None:
                try:
                    deadline_s = float(deadline_s)
                    if deadline_s <= 0:
                        raise ValueError
                except (TypeError, ValueError):
                    self._reply(400, {
                        "error": "DeadlineSeconds must be a number > 0"
                    })
                    return
            submit_key = req.pop("SubmitKey", None)
            tenant = server.admission.tenant_for(
                self.headers.get(token_header, "")
            )
            code, payload, headers = server.admission.submit(
                req, tenant, len(raw),
                traceparent=self.headers.get("traceparent"),
                deadline_s=deadline_s,
                submit_key=str(submit_key) if submit_key else None,
            )
            server.metrics.requests.inc(method="submit", code=str(code))
            self._reply(code, payload, headers=headers or None)

        def _read_body(self):
            """Bounded request-body read; returns (raw, None) or
            (None, (status, payload)) on malformed/oversized input —
            never raises, so every POST route (the sync dispatch AND the
            submit route) answers garbage with an HTTP error instead of
            a dropped connection."""
            limit = server.max_request_bytes
            try:
                length = int(self.headers.get("Content-Length", "0") or 0)
            except ValueError:
                return None, (400, {"error": "bad Content-Length"})
            if length < 0 or length > limit:
                return None, (413, {"error": "request too large"})
            try:
                raw = self.rfile.read(length)
            except OSError as e:
                # client reset mid-body; the stream position is now
                # undefined, so the connection can't be reused either
                self.close_connection = True
                self._body_consumed = True
                return None, (400, {"error": f"body read failed: {e}"})
            self._body_consumed = True
            if self.headers.get("Content-Encoding") == "gzip":
                import gzip as _gzip
                import io as _io

                try:
                    # stream-decompress with a cap: checking size after a
                    # full decompress would let a gzip bomb OOM the server
                    with _gzip.GzipFile(fileobj=_io.BytesIO(raw)) as gz:
                        raw = gz.read(limit + 1)
                except (OSError, EOFError) as e:  # BadGzipFile is OSError
                    return None, (400, {"error": f"bad gzip body: {e}"})
                if len(raw) > limit:
                    return None, (413, {"error": "request too large"})
            return raw, None

        def _dispatch(self, method, tenant=None, raw=None,
                      err=None) -> tuple[int, dict]:
            """Run one RPC method; returns (status, payload) and never
            raises — the reply and the request metrics are the caller's.
            The body is read by ``do_POST`` (before the admission gate)
            and passed in as ``raw``/``err``."""
            try:
                if err is not None:
                    return err
                req = json.loads(raw or b"{}")
                reloader = server.reloader
                if reloader is not None:
                    reloader.request_begin()
                try:
                    if method == "scan":
                        resp = server.scan(
                            req, traceparent=self.headers.get("traceparent"),
                            tenant=tenant.name if tenant else None,
                        )
                    else:
                        resp = getattr(server, method)(req)
                finally:
                    if reloader is not None:
                        reloader.request_end()
                return 200, resp
            except KeyError as e:
                return 400, {"error": f"bad request: {e}"}
            except Exception as e:
                logger.warning("rpc %s failed: %s", self.path, e)
                return 500, {"error": str(e)}

    return Handler


def start_server(
    host: str = "127.0.0.1",
    port: int = 0,
    cache=None,
    cache_dir: str | None = None,
    vuln_client=None,
    token: str = "",
    token_header: str = rpc.DEFAULT_TOKEN_HEADER,
    db_reload_dir: str | None = None,
    db_reload_interval: float = 3600.0,
    admission=None,
):
    """Start the server on a background thread; returns (httpd, actual_port).
    port=0 picks a free port — the reference's own client/server tests use
    exactly this in-process technique (ref: integration/client_server_test.go).
    With ``db_reload_dir``, an hourly worker hot-swaps the advisory DB
    (ref: listen.go:62-80). ``admission`` takes a resolved
    :class:`~trivy_tpu.rpc.admission.AdmissionConfig`; None resolves from
    the environment (admission stays off unless configured)."""
    if cache is None:
        from trivy_tpu.cache import new_cache

        cache = new_cache("fs", cache_dir)
    service = ScanServer(cache, vuln_client=vuln_client, admission=admission)
    if db_reload_dir:
        service.reloader = DBReloader(service, db_reload_dir, db_reload_interval)
        service.reloader.start()
    httpd = ThreadingHTTPServer(
        (host, port), _make_handler(service, token, token_header)
    )
    httpd.service = service  # the drain path and tests need the handle
    if service.admission is not None:
        # admission workers stop with the listener even on a bare
        # httpd.shutdown() (tests, abrupt teardown) — the graceful path
        # (drain_and_shutdown) already stopped them, and the controller's
        # shutdown is idempotent
        _orig_shutdown = httpd.shutdown

        def _shutdown_with_admission():
            service.admission.shutdown()
            _orig_shutdown()

        httpd.shutdown = _shutdown_with_admission
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]


# in-flight scans get this long to finish after SIGTERM before the
# listener closes under them
DRAIN_TIMEOUT = 30.0


def drain_and_shutdown(httpd, timeout: float = DRAIN_TIMEOUT,
                       poll: float = 0.05) -> int:
    """Graceful drain: flip /healthz to "draining" and 503 new RPCs (so
    load balancers and retrying clients move on), LOUDLY reject
    queued-but-unstarted admission jobs (their pollers get a terminal
    ``rejected`` status instead of a stranded 202), wait up to ``timeout``
    for in-flight requests and running jobs, then stop the listener.
    Returns the number of requests/jobs still in flight when the listener
    closed (0 = clean drain)."""
    service = httpd.service
    service.draining = True
    logger.info("draining: refusing new requests, waiting for in-flight")
    admission = service.admission
    if admission is not None:
        admission.reject_queued()

    def _in_flight() -> int:
        # in-flight HTTP requests (sync scans included) + async jobs on
        # worker threads; admission.running() would double-count sync
        # scans, which hold an HTTP request AND a budget slot
        n = int(service.metrics.in_flight.value())
        if admission is not None:
            n += admission.running_jobs()
        return n

    deadline = time.monotonic() + timeout
    while _in_flight() > 0 and time.monotonic() < deadline:
        time.sleep(poll)
    remaining = _in_flight()
    if remaining:
        logger.warning(
            "drain timeout after %.0fs: %d request(s)/job(s) still in "
            "flight", timeout, remaining,
        )
    else:
        logger.info("drained; shutting down")
    if admission is not None:
        admission.shutdown()
    httpd.shutdown()
    return remaining


def serve(host: str, port: int, cache_dir: str | None = None,
          token: str = "", token_header: str = rpc.DEFAULT_TOKEN_HEADER,
          db_repository: str | None = None,
          drain_timeout: float = DRAIN_TIMEOUT,
          admission=None) -> None:
    """Blocking server entrypoint for `trivy-tpu server`. SIGTERM (the
    orchestrator's stop signal) triggers a graceful drain: /healthz flips
    to "draining", queued admission jobs are rejected loudly, in-flight
    scans finish (bounded by ``drain_timeout``), then the listener
    closes."""
    import signal

    from trivy_tpu.db import load_default_db

    vuln_client = load_default_db(db_repository, cache_dir)
    if vuln_client is None:
        logger.warning("advisory DB not available; server scans skip vulns")
    httpd, actual = start_server(
        host, port, cache_dir=cache_dir, vuln_client=vuln_client,
        token=token, token_header=token_header,
        db_reload_dir=getattr(vuln_client, "db_dir", "") or None,
        admission=admission,
    )
    stop = threading.Event()

    def on_sigterm(signum, frame):
        logger.info("SIGTERM received; starting graceful drain")
        stop.set()

    signal.signal(signal.SIGTERM, on_sigterm)
    logger.info("listening on %s:%d", host, actual)
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    drain_and_shutdown(httpd, timeout=drain_timeout)
