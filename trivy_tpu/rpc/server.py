"""Scan server (ref: pkg/rpc/server/listen.go, server.go).

Serves the Cache and Scanner services over HTTP with optional token-header
auth and /healthz + /version probes. Detection runs server-side against the
server's cache + advisory DB; analysis stays client-side (ref:
pkg/commands/artifact/run.go:348-355 split).
"""

from __future__ import annotations

import hmac
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from trivy_tpu import log, rpc
from trivy_tpu.scanner import ScanOptions

logger = log.logger("rpc:server")

# request-body ceiling; blobs are analysis metadata, not file contents, so
# 256 MiB is generous headroom while bounding a hostile Content-Length
MAX_REQUEST_BYTES = 256 * 1024 * 1024


class DBReloader:
    """Periodic advisory-DB hot swap with in-flight serialization
    (ref: pkg/rpc/server/listen.go:62-80 — the hourly updater waits for
    in-flight requests via paired WaitGroups; here one Condition carries
    both roles: requests wait while a swap runs, the swap waits for the
    in-flight count to drain)."""

    def __init__(self, server: "ScanServer", db_dir: str, interval: float = 3600.0):
        self.server = server
        self.db_dir = db_dir
        self.interval = interval
        self._cond = threading.Condition()
        self._inflight = 0
        self._updating = False
        self._stop = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.reload()
            except Exception as e:
                logger.warning("DB reload failed (keeping current DB): %s", e)

    def reload(self) -> None:
        """Load the DB fresh, then swap it in once no request is mid-scan."""
        from trivy_tpu.db import VulnDB

        new_db = VulnDB.load(self.db_dir)  # load OUTSIDE the lock
        new_db.db_dir = self.db_dir
        with self._cond:
            self._updating = True
            while self._inflight > 0:
                self._cond.wait()
            self.server.driver.vuln_client = new_db
            self._updating = False
            self._cond.notify_all()
        logger.info("advisory DB reloaded from %s", self.db_dir)

    def request_begin(self) -> None:
        with self._cond:
            while self._updating:
                self._cond.wait()
            self._inflight += 1

    def request_end(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()


class ScanServer:
    """Service implementation bound to a cache and a local driver."""

    def __init__(self, cache, vuln_client=None):
        from trivy_tpu.scanner.local_driver import LocalDriver

        self.cache = cache
        self.driver = LocalDriver(cache, vuln_client=vuln_client)
        self.reloader: DBReloader | None = None

    # -- service methods (JSON dict in/out) ---------------------------------

    def scan(self, req: dict) -> dict:
        options = ScanOptions(
            scanners=req.get("Options", {}).get("Scanners", ["vuln"]),
            list_all_pkgs=bool(req.get("Options", {}).get("ListAllPkgs")),
        )
        results, os_info = self.driver.scan(
            req.get("Target", ""),
            req.get("ArtifactID", ""),
            list(req.get("BlobIDs", [])),
            options,
        )
        return {
            "OS": os_info.to_dict() if os_info else None,
            "Results": [r.to_dict() for r in results],
        }

    def put_blob(self, req: dict) -> dict:
        self.cache.put_blob(req["DiffID"], req["BlobInfo"])
        return {}

    def put_artifact(self, req: dict) -> dict:
        self.cache.put_artifact(req["ArtifactID"], req["ArtifactInfo"])
        return {}

    def missing_blobs(self, req: dict) -> dict:
        missing_artifact, missing = self.cache.missing_blobs(
            req.get("ArtifactID", ""), list(req.get("BlobIDs", []))
        )
        return {"MissingArtifact": missing_artifact, "MissingBlobIDs": missing}

    def delete_blobs(self, req: dict) -> dict:
        delete = getattr(self.cache, "delete_blobs", None)
        if delete is not None:
            delete(list(req.get("BlobIDs", [])))
        return {}


_ROUTES = {
    rpc.SCANNER_SCAN: "scan",
    rpc.CACHE_PUT_BLOB: "put_blob",
    rpc.CACHE_PUT_ARTIFACT: "put_artifact",
    rpc.CACHE_MISSING_BLOBS: "missing_blobs",
    rpc.CACHE_DELETE_BLOBS: "delete_blobs",
}


def _make_handler(server: ScanServer, token: str, token_header: str):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through our logger
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _reply(self, code: int, payload: dict) -> None:
            import gzip as _gzip

            body = json.dumps(payload).encode()
            accepts_gzip = "gzip" in self.headers.get("Accept-Encoding", "")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if accepts_gzip and len(body) > 1024:
                body = _gzip.compress(body)
                self.send_header("Content-Encoding", "gzip")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == rpc.HEALTHZ:
                # plain "ok" like the reference's healthz
                body = b"ok"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == rpc.VERSION:
                from trivy_tpu import __version__

                self._reply(200, {"Version": __version__})
                return
            self._reply(404, {"error": "not found"})

        def do_POST(self):
            method = _ROUTES.get(self.path)
            if method is None:
                self._reply(404, {"error": f"no such route: {self.path}"})
                return
            if token and not hmac.compare_digest(
                self.headers.get(token_header, "").encode("latin-1", "replace"),
                token.encode("latin-1", "replace"),
            ):
                self._reply(401, {"error": "invalid token"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length < 0 or length > MAX_REQUEST_BYTES:
                    self._reply(413, {"error": "request too large"})
                    return
                raw = self.rfile.read(length)
                if self.headers.get("Content-Encoding") == "gzip":
                    import gzip as _gzip
                    import io as _io

                    # stream-decompress with a cap: checking size after a
                    # full decompress would let a gzip bomb OOM the server
                    with _gzip.GzipFile(fileobj=_io.BytesIO(raw)) as gz:
                        raw = gz.read(MAX_REQUEST_BYTES + 1)
                    if len(raw) > MAX_REQUEST_BYTES:
                        self._reply(413, {"error": "request too large"})
                        return
                req = json.loads(raw or b"{}")
                reloader = server.reloader
                if reloader is not None:
                    reloader.request_begin()
                try:
                    resp = getattr(server, method)(req)
                finally:
                    if reloader is not None:
                        reloader.request_end()
                self._reply(200, resp)
            except KeyError as e:
                self._reply(400, {"error": f"bad request: {e}"})
            except Exception as e:
                logger.warning("rpc %s failed: %s", self.path, e)
                self._reply(500, {"error": str(e)})

    return Handler


def start_server(
    host: str = "127.0.0.1",
    port: int = 0,
    cache=None,
    cache_dir: str | None = None,
    vuln_client=None,
    token: str = "",
    token_header: str = rpc.DEFAULT_TOKEN_HEADER,
    db_reload_dir: str | None = None,
    db_reload_interval: float = 3600.0,
):
    """Start the server on a background thread; returns (httpd, actual_port).
    port=0 picks a free port — the reference's own client/server tests use
    exactly this in-process technique (ref: integration/client_server_test.go).
    With ``db_reload_dir``, an hourly worker hot-swaps the advisory DB
    (ref: listen.go:62-80)."""
    if cache is None:
        from trivy_tpu.cache import new_cache

        cache = new_cache("fs", cache_dir)
    service = ScanServer(cache, vuln_client=vuln_client)
    if db_reload_dir:
        service.reloader = DBReloader(service, db_reload_dir, db_reload_interval)
        service.reloader.start()
    httpd = ThreadingHTTPServer(
        (host, port), _make_handler(service, token, token_header)
    )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]


def serve(host: str, port: int, cache_dir: str | None = None,
          token: str = "", token_header: str = rpc.DEFAULT_TOKEN_HEADER,
          db_repository: str | None = None) -> None:
    """Blocking server entrypoint for `trivy-tpu server`."""
    from trivy_tpu.db import load_default_db

    vuln_client = load_default_db(db_repository, cache_dir)
    if vuln_client is None:
        logger.warning("advisory DB not available; server scans skip vulns")
    httpd, actual = start_server(
        host, port, cache_dir=cache_dir, vuln_client=vuln_client,
        token=token, token_header=token_header,
        db_reload_dir=getattr(vuln_client, "db_dir", "") or None,
    )
    logger.info("listening on %s:%d", host, actual)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        httpd.shutdown()
