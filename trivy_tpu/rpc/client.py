"""RPC client: remote cache + remote scan driver
(ref: pkg/rpc/client/client.go, pkg/cache/remote.go, pkg/rpc/retry.go).

The client analyzes locally, ships blobs to the server's cache, and asks the
server to run detection. Requests retry with exponential backoff on
connectivity errors and 5xx — the reference retries only on
twirp.Unavailable (ref: retry.go:17-41); connection refused / 502 / 503 /
504 map to the same class here. The backoff is full-jitter (a fleet of
clients retrying a recovering server must not synchronize into a thundering
herd), honors ``Retry-After`` on 503 and 429 (the server sends it while
draining or shedding over-budget/over-quota scans), and the whole retry
loop is capped by a wall-clock deadline — 10 retries × 5 s of zero-jitter
sleep used to stall a caller ~50 s. Read-only polls (progress, job
results) skip the ladder entirely and fail fast on :data:`POLL_TIMEOUT`.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.parse

from trivy_tpu import faults, log, obs, rpc
from trivy_tpu.scanner import ScanOptions
from trivy_tpu.types import OS, Result

logger = log.logger("rpc:client")

# cadence of the client-side progress join: while a remote Scanner.Scan is
# in flight and telemetry is on, the driver polls the server's progress API
# this often and folds the snapshot into the local scan's ScanProgress
PROGRESS_POLL_SECS = 1.0

MAX_RETRIES = 10  # ref: retry.go retry count
MAX_BACKOFF = 5.0  # per-sleep cap (jittered: actual sleep ~U(0, backoff))
RETRY_DEADLINE = 60.0  # total retry wall-clock cap per request
# 429 joins the retryable set: an admission-controlled server sheds
# over-quota tenants with 429 + Retry-After, and the same backoff that
# rides out a draining 503 turns that into a later successful attempt
_RETRYABLE_HTTP = {429, 502, 503, 504}
_RETRY_AFTER_HTTP = {429, 503}

# read-only polls (progress, job results) get a short timeout and NO
# retry ladder: a wedged server must fail a poll fast — the next tick (or
# the caller's own poll loop) retries anyway, and a poll inheriting the
# full 60 s RETRY_DEADLINE used to stall the --live line for a minute
POLL_TIMEOUT = 5.0


class RPCError(Exception):
    pass


class ConnectionPool:
    """Per-(scheme, host, port) pooled keep-alive HTTP connections.

    Every request used to open a fresh TCP connection
    (``urllib.request.urlopen``); the fleet coordinator's fan-out and
    result-poll loops made that per-request setup a measurable cost, so
    requests now ride bounded per-host keep-alive
    :class:`http.client.HTTPConnection` pools instead. Safety rules:

    - a connection is used by exactly one thread at a time (popped from
      the pool, returned only after the response body is fully read);
    - any socket-level failure invalidates the connection (closed and
      dropped, never re-pooled) — with one transparent retry on a FRESH
      connection when a *reused* connection fails before yielding a
      response (the server legitimately closed an idle keep-alive socket
      between requests; timeouts are excluded, they must surface);
    - a response carrying ``Connection: close`` is honored (read fully,
      then closed, not re-pooled) — shed replies with small bodies keep
      the connection alive because the server drains them, which is
      regression-tested client-side.
    """

    MAX_IDLE_PER_HOST = 4

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: dict[tuple, list] = {}
        self.created = 0
        self.reused = 0
        self.invalidated = 0

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "idle": sum(len(v) for v in self._idle.values()),
                "hosts": len([k for k, v in self._idle.items() if v]),
                "created": self.created,
                "reused": self.reused,
                "invalidated": self.invalidated,
            }

    def clear(self) -> None:
        with self._lock:
            conns = [c for v in self._idle.values() for c in v]
            self._idle.clear()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    # -- acquire / release ---------------------------------------------------

    def _acquire(self, key: tuple, timeout: float, fresh: bool = False):
        conn = None
        if not fresh:
            with self._lock:
                lst = self._idle.get(key)
                conn = lst.pop() if lst else None
                if conn is not None:
                    self.reused += 1
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn, True
        scheme, host, port = key
        cls = (
            http.client.HTTPSConnection
            if scheme == "https"
            else http.client.HTTPConnection
        )
        conn = cls(host, port, timeout=timeout)
        with self._lock:
            self.created += 1
        return conn, False

    def _release(self, key: tuple, conn) -> None:
        with self._lock:
            lst = self._idle.setdefault(key, [])
            if conn.sock is not None and len(lst) < self.MAX_IDLE_PER_HOST:
                lst.append(conn)
                return
        conn.close()

    def _discard(self, conn) -> None:
        with self._lock:
            self.invalidated += 1
        try:
            conn.close()
        except Exception:
            pass

    # -- one request ---------------------------------------------------------

    @staticmethod
    def _proxied(scheme: str, host: str) -> bool:
        """Does the environment route this host through an HTTP proxy?
        Pooled direct connections would silently bypass a mandatory
        egress proxy that the old ``urlopen`` path honored."""
        import urllib.request as _ur

        if scheme not in _ur.getproxies():
            return False
        try:
            return not _ur.proxy_bypass(host)
        except Exception:
            return True

    @staticmethod
    def _urllib_request(url: str, method: str, body: bytes | None,
                        headers: dict, timeout: float):
        """Legacy urllib path for proxied requests (keeps
        HTTP(S)_PROXY/no_proxy semantics; no pooling through proxies).
        Same ``(status, headers, data)`` contract as the pooled path —
        error statuses are returned, not raised."""
        import urllib.error as _ue
        import urllib.request as _ur

        req = _ur.Request(url, data=body, headers=headers, method=method)
        try:
            with _ur.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.headers, resp.read()
        except _ue.HTTPError as e:
            return e.code, e.headers, e.read() or b""

    def request(self, url: str, method: str, body: bytes | None,
                headers: dict, timeout: float):
        """One HTTP exchange over a pooled connection. Returns
        ``(status, headers message, body bytes)``; raises ``OSError`` /
        ``http.client.HTTPException`` on connectivity failures (the
        caller's retry ladder classifies them)."""
        parts = urllib.parse.urlsplit(url)
        port = parts.port or (443 if parts.scheme == "https" else 80)
        key = (parts.scheme, parts.hostname or "", port)
        if self._proxied(parts.scheme, parts.hostname or ""):
            return self._urllib_request(url, method, body, headers, timeout)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        force_fresh = False
        for _ in range(2):
            conn, reused = self._acquire(key, timeout, fresh=force_fresh)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (TimeoutError, http.client.HTTPException, OSError) as e:
                self._discard(conn)
                stale = reused and not isinstance(e, TimeoutError)
                if stale and not force_fresh:
                    # the server closed this keep-alive socket between
                    # requests; one transparent retry on a fresh
                    # connection (timeouts surface — retrying would
                    # silently double the caller's wait)
                    force_fresh = True
                    continue
                raise
            if resp.will_close:
                conn.close()
            else:
                self._release(key, conn)
            return resp.status, resp.headers, data
        raise http.client.HTTPException(f"{url}: pooled request failed")


_POOL = ConnectionPool()


def pool_stats() -> dict:
    """Live connection-pool counters (``bench --smoke`` asserts the pool
    stays empty on fleet-off local scans)."""
    return _POOL.stats()


def pool_clear() -> None:
    _POOL.clear()


def _request_headers(token: str, token_header: str,
                     gzip_body: bool) -> dict:
    headers = {
        "Content-Type": "application/json",
        "Accept-Encoding": "gzip",
        # distributed tracing: every request carries the active trace id
        # (and the caller's open span as parent) so the server joins the
        # client's trace instead of minting a fresh one, and server logs
        # correlate with client traces even when tracing is off
        "traceparent": obs.traceparent(),
    }
    if gzip_body:
        headers["Content-Encoding"] = "gzip"
    if token:
        headers[token_header] = token
    return headers


def _decode_body(headers, data: bytes) -> bytes:
    if headers.get("Content-Encoding") == "gzip":
        import gzip as _gzip

        return _gzip.decompress(data)
    return data


def _post(base: str, path: str, payload: dict, token: str, token_header: str,
          timeout: float, retries: int = MAX_RETRIES,
          deadline: float = RETRY_DEADLINE) -> dict:
    import gzip as _gzip

    url = base.rstrip("/") + path
    raw = json.dumps(payload).encode()
    # blobs compress extremely well (JSON metadata); gzip above 1 KiB
    # (ref: the server mux wraps handlers in gzip middleware)
    body = _gzip.compress(raw) if len(raw) > 1024 else raw
    backoff = 0.1
    start = time.monotonic()
    last: Exception | None = None
    for attempt in range(retries + 1):
        retry_after: float | None = None
        try:
            faults.check("rpc.post", key=path)
            status, rheaders, data = _POOL.request(
                url, "POST", body,
                _request_headers(token, token_header, body is not raw),
                timeout,
            )
            if status < 300:
                # strictly 2xx: redirects are NOT followed (a replica
                # address should point at the server, not a redirecting
                # LB) — a 3xx must surface as an RPCError below, never be
                # json-parsed as a success body
                try:
                    body_bytes = _decode_body(rheaders, data)
                except OSError as e:
                    # corrupt gzip payload (BadGzipFile is an OSError) is
                    # deterministic, not connectivity — re-POSTing through
                    # the jitter ladder would burn the whole deadline
                    raise RPCError(
                        f"{path}: bad response body: {e}"
                    ) from e
                return json.loads(body_bytes or b"{}")
            if status in _RETRYABLE_HTTP and attempt < retries:
                last = RPCError(f"{path}: HTTP {status}")
                if status in _RETRY_AFTER_HTTP:
                    # a draining/overloaded/shedding server says when to
                    # come back (admission sheds carry a drain-rate-derived
                    # Retry-After on both 503 and 429)
                    try:
                        ra = rheaders.get("Retry-After")
                        retry_after = float(ra) if ra else None
                    except (TypeError, ValueError):
                        retry_after = None
            else:
                try:
                    detail = json.loads(
                        _decode_body(rheaders, data) or b"{}"
                    ).get("error", "")
                except Exception:
                    detail = ""
                raise RPCError(f"{path}: HTTP {status} {detail}".strip())
        except (
            OSError, http.client.HTTPException,
            faults.InjectedFault,  # default-kind rpc.post injections retry too
        ) as e:
            if attempt >= retries:
                raise RPCError(f"{path}: {e}") from e
            last = e
        # full jitter: sleep ~U(0, backoff) so synchronized failures
        # desynchronize on the first retry. Retry-After is a server-directed
        # MINIMUM with jitter on top — sleeping it verbatim would
        # re-synchronize every client a draining server turned away
        if retry_after is not None:
            delay = retry_after + random.uniform(0.0, backoff)
        else:
            delay = random.uniform(0.0, backoff)
        backoff = min(backoff * 2, MAX_BACKOFF)
        remaining = deadline - (time.monotonic() - start)
        if remaining <= delay:
            raise RPCError(
                f"{path}: retry deadline ({deadline:.0f}s) exceeded: {last}"
            ) from last
        logger.debug(
            "retrying %s after %s (attempt %d, sleeping %.2fs)",
            path, last, attempt + 1, delay,
        )
        time.sleep(delay)
    raise RPCError(f"{path}: retries exhausted: {last}")


def _get_json(url: str, token: str, token_header: str, timeout: float,
              what: str) -> tuple[int, dict, dict]:
    """One read-only GET poll: (status, body, headers). No retry ladder
    and the short :data:`POLL_TIMEOUT`-style timeout — polls must fail
    fast, the caller's loop is the retry (pooled keep-alive still applies:
    a poll loop reuses one warm connection instead of a TCP handshake per
    tick)."""
    headers = {}
    if token:
        headers[token_header] = token
    try:
        status, rheaders, data = _POOL.request(
            url, "GET", None, headers, timeout
        )
    except (OSError, http.client.HTTPException) as e:
        raise RPCError(f"{what}: {e}") from e
    if status >= 300:  # polls expect 200/202; redirects are config errors
        raise RPCError(f"{what}: HTTP {status}")
    return (
        status,
        json.loads(_decode_body(rheaders, data) or b"{}"),
        dict(rheaders),
    )


def get_progress(server: str, trace_id: str, token: str = "",
                 token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                 timeout: float = POLL_TIMEOUT) -> dict:
    """One poll of the server's live progress API
    (``GET /scan/<trace_id>/progress``). Raises :class:`RPCError` on an
    unknown trace id or connectivity failure — deliberately no retry loop:
    progress polling is advisory and the next tick polls again anyway."""
    base = server if "://" in server else f"http://{server}"
    url = base.rstrip("/") + rpc.scan_progress_path(trace_id)
    _, doc, _ = _get_json(
        url, token, token_header, timeout, f"progress {trace_id}"
    )
    return doc


def get_metrics_text(server: str, token: str = "",
                     token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                     timeout: float = POLL_TIMEOUT) -> str:
    """One scrape of the server's ``GET /metrics`` exposition text. Unlike
    the JSON polls this returns the raw Prometheus text body (the caller
    parses it with :func:`trivy_tpu.obs.metrics.parse_text`) — same
    fail-fast discipline as :func:`get_progress`: no retry ladder, the
    telemetry tick loop IS the retry."""
    base = server if "://" in server else f"http://{server}"
    url = base.rstrip("/") + "/metrics"
    headers = {}
    if token:
        headers[token_header] = token
    try:
        status, rheaders, data = _POOL.request(
            url, "GET", None, headers, timeout
        )
    except (OSError, http.client.HTTPException) as e:
        raise RPCError(f"metrics scrape {server}: {e}") from e
    if status >= 300:
        raise RPCError(f"metrics scrape {server}: HTTP {status}")
    return (_decode_body(rheaders, data) or b"").decode(
        "utf-8", errors="replace"
    )


def get_result(server: str, job_id: str, token: str = "",
               token_header: str = rpc.DEFAULT_TOKEN_HEADER,
               timeout: float = POLL_TIMEOUT) -> dict:
    """One poll of the async job API (``GET /scan/<job_id>/result``).
    Returns the job document — ``Status`` is ``queued``/``running`` (the
    202 states, with ``QueuePosition``/``RetryAfterSeconds`` while
    queued) or a terminal ``done``/``failed``/``expired``/``rejected``.
    Same fail-fast discipline as :func:`get_progress`."""
    base = server if "://" in server else f"http://{server}"
    url = base.rstrip("/") + rpc.scan_result_path(job_id)
    _, doc, _ = _get_json(
        url, token, token_header, timeout, f"result {job_id}"
    )
    return doc


def get_healthz(server: str, token: str = "",
                token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                deadline: float = POLL_TIMEOUT) -> dict:
    """One ``GET /healthz`` probe — the coordinator's join-time liveness
    check for a registering replica. Fail-fast like every poll: a dead
    joiner must be refused within one probe, not after a retry ladder."""
    base = server if "://" in server else f"http://{server}"
    url = base.rstrip("/") + rpc.HEALTHZ
    _, doc, _ = _get_json(
        url, token, token_header, min(deadline, POLL_TIMEOUT),
        f"healthz {server}",
    )
    return doc


def post_register(server: str, host: str, token: str = "",
                  token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                  timeout: float = 30.0, retries: int = MAX_RETRIES,
                  deadline: float = RETRY_DEADLINE) -> dict:
    """Announce replica ``host`` to the coordinator at ``server``
    (``POST /fleet/register``). Rides the normal full-jitter retry
    ladder — the seam is idempotent server-side (a duplicate register
    answers ``Known: true``), so a retry after a lost 200 is safe."""
    return _post(
        server if "://" in server else f"http://{server}",
        rpc.FLEET_REGISTER, {"Host": host}, token, token_header,
        timeout, retries, deadline,
    )


def post_deregister(server: str, host: str, token: str = "",
                    token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                    timeout: float = 30.0, retries: int = MAX_RETRIES,
                    deadline: float = RETRY_DEADLINE) -> dict:
    """Withdraw replica ``host`` from the coordinator at ``server``
    (``POST /fleet/deregister``) — the explicit inverse of
    :func:`post_register`. Idempotent server-side (an unknown or
    already-draining host answers cleanly), so the retry ladder is safe
    here too."""
    return _post(
        server if "://" in server else f"http://{server}",
        rpc.FLEET_DEREGISTER, {"Host": host}, token, token_header,
        timeout, retries, deadline,
    )


def fetch_debug_bundle(server: str, token: str = "",
                       token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                       deadline: float = POLL_TIMEOUT) -> dict:
    """One ``GET /debug/bundle`` pull of a replica's flight-recorder
    bundle (ring dump, compile/HBM ledgers, verdict). Fail-fast like
    every poll: the coordinator calls this against a replica it just
    declared dead, so a hung pull must not stall the forensics path."""
    base = server if "://" in server else f"http://{server}"
    url = base.rstrip("/") + rpc.DEBUG_BUNDLE
    _, doc, _ = _get_json(
        url, token, token_header, min(deadline, POLL_TIMEOUT),
        f"debug bundle {server}",
    )
    return doc


class RemoteCache:
    """Cache facade backed by the server's Cache service
    (ref: pkg/cache/remote.go) — what client-side analysis writes to."""

    def __init__(self, server: str, token: str = "",
                 token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                 timeout: float = 30.0, retries: int = MAX_RETRIES,
                 deadline: float = RETRY_DEADLINE):
        self.base = server if "://" in server else f"http://{server}"
        self.token = token
        self.token_header = token_header
        self.timeout = timeout
        self.retries = retries
        self.deadline = deadline

    def _call(self, path: str, payload: dict) -> dict:
        return _post(self.base, path, payload, self.token, self.token_header,
                     self.timeout, self.retries, self.deadline)

    def put_blob(self, blob_id: str, blob: dict) -> None:
        self._call(rpc.CACHE_PUT_BLOB, {"DiffID": blob_id, "BlobInfo": blob})

    def put_artifact(self, artifact_id: str, info: dict) -> None:
        self._call(
            rpc.CACHE_PUT_ARTIFACT,
            {"ArtifactID": artifact_id, "ArtifactInfo": info},
        )

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        resp = self._call(
            rpc.CACHE_MISSING_BLOBS,
            {"ArtifactID": artifact_id, "BlobIDs": blob_ids},
        )
        return bool(resp.get("MissingArtifact")), list(resp.get("MissingBlobIDs", []))

    def delete_blobs(self, blob_ids: list[str]) -> None:
        self._call(rpc.CACHE_DELETE_BLOBS, {"BlobIDs": blob_ids})

    # local-read methods are not part of the remote surface
    def get_blob(self, blob_id: str):
        raise RPCError("RemoteCache has no local blob reads")


class RemoteDriver:
    """Scan driver that calls the server's Scanner service
    (ref: pkg/rpc/client/client.go:69-100)."""

    def __init__(self, server: str, token: str = "",
                 token_header: str = rpc.DEFAULT_TOKEN_HEADER,
                 timeout: float = 300.0, retries: int = MAX_RETRIES,
                 deadline: float = RETRY_DEADLINE):
        self.base = server if "://" in server else f"http://{server}"
        self.token = token or ""
        self.token_header = token_header
        self.timeout = timeout
        self.retries = retries
        self.deadline = deadline

    def progress(self, trace_id: str | None = None) -> dict:
        """Live progress of the remote scan joined to ``trace_id`` (the
        active trace by default) — the client half of the progress API."""
        return get_progress(
            self.base, trace_id or obs.current().trace_id,
            token=self.token, token_header=self.token_header,
        )

    def _scan_payload(self, target, artifact_id, blob_ids, options,
                     want_trace: bool) -> dict:
        return {
            "Target": target,
            "ArtifactID": artifact_id,
            "BlobIDs": blob_ids,
            "Options": {
                "Scanners": list(options.scanners),
                "ListAllPkgs": options.list_all_pkgs,
            },
            "WantTrace": want_trace,
        }

    # -- async job API (admission-controlled servers) -----------------------

    def submit(self, target: str, artifact_id: str, blob_ids: list[str],
               options: ScanOptions,
               deadline_s: float | None = None,
               shard: dict | None = None) -> dict:
        """Submit a scan to the server's admission queue
        (``POST /scan/submit``); returns the submit document (``JobID``,
        ``QueuePosition``, ...). Sheds (429/503 + Retry-After) ride the
        normal full-jitter retry loop, so a busy-but-draining queue turns
        into a later accepted submit, not an error. ``shard`` attaches a
        fleet shard spec: the server then runs that shard's ANALYSIS and
        the job result carries its ``Blobs`` instead of scan results."""
        import os as _os

        ctx = obs.current()
        payload = self._scan_payload(
            target, artifact_id, blob_ids, options, bool(ctx.enabled)
        )
        if shard is not None:
            payload["Shard"] = shard
        if deadline_s is not None:
            payload["DeadlineSeconds"] = deadline_s
        # submit is NOT idempotent on the wire (it enqueues); the key is
        # stable across this retry loop's attempts, so a retry after a
        # lost 202 returns the already-enqueued job instead of a twin
        # that would burn a budget slot nobody polls
        payload["SubmitKey"] = _os.urandom(8).hex()
        return _post(
            self.base, rpc.SCAN_SUBMIT, payload, self.token,
            self.token_header, self.timeout, self.retries, self.deadline,
        )

    def scan_shard(self, target: str, shard: dict,
                   options: ScanOptions) -> dict:
        """Synchronous fleet-shard execution (``Scanner.Scan`` with a
        ``Shard`` block) for replicas running without admission control /
        the async job API; returns the raw shard response
        (``Blobs``/``Health``/``Trace``)."""
        ctx = obs.current()
        payload = self._scan_payload(target, "", [], options,
                                     bool(ctx.enabled))
        payload["Shard"] = shard
        with ctx.span("rpc.scan"):
            return _post(
                self.base, rpc.SCANNER_SCAN, payload, self.token,
                self.token_header, self.timeout, self.retries,
                self.deadline,
            )

    def fetch_result(self, job_id: str) -> dict:
        """One fail-fast poll of a submitted job's result document."""
        return get_result(
            self.base, job_id, token=self.token,
            token_header=self.token_header,
        )

    def wait_result(self, job_id: str, timeout: float = 300.0,
                    poll: float = 0.25) -> dict:
        """Poll a job to a terminal state and return the scan response.
        Honors the server's queued-state ``RetryAfterSeconds`` as the
        poll cadence floor; raises :class:`RPCError` on ``failed``/
        ``expired``/``rejected`` jobs or when ``timeout`` elapses first."""
        deadline = time.monotonic() + timeout
        misses = 0
        while True:
            try:
                doc = self.fetch_result(job_id)
            except RPCError:
                # one transient blip (proxy restart, a single wedged
                # 5 s poll) must not abort a job that is still running
                # server-side and burning a budget slot; but a permanent
                # failure (unknown job id, dead server) should surface
                # after a few polls, not linger to the full timeout
                misses += 1
                if misses > 3 or time.monotonic() >= deadline:
                    raise
                time.sleep(min(poll, max(0.05,
                                         deadline - time.monotonic())))
                continue
            misses = 0
            status = doc.get("Status")
            if status == "done":
                return doc.get("Result") or {}
            if status in ("failed", "expired", "rejected"):
                raise RPCError(
                    f"job {job_id}: {status}: {doc.get('Error', '')}"
                )
            if time.monotonic() >= deadline:
                raise RPCError(
                    f"job {job_id}: still {status} after {timeout:.0f}s"
                )
            delay = poll
            if status == "queued" and doc.get("RetryAfterSeconds"):
                # the server knows its drain rate better than we do, but
                # a poll is cheap — cap the server's hint at 2 s so a
                # pessimistic estimate can't make a finished job linger
                delay = min(2.0, max(poll, float(doc["RetryAfterSeconds"])))
            time.sleep(min(delay, max(0.05, deadline - time.monotonic())))

    def scan_async(self, target: str, artifact_id: str,
                   blob_ids: list[str], options: ScanOptions,
                   deadline_s: float | None = None,
                   timeout: float = 300.0):
        """Submit + poll + parse: the async-shaped equivalent of
        :meth:`scan` for large artifacts against admission-controlled
        servers."""
        sub = self.submit(target, artifact_id, blob_ids, options,
                          deadline_s=deadline_s)
        resp = self.wait_result(sub["JobID"], timeout=timeout)
        ctx = obs.current()
        if ctx.enabled and resp.get("Trace"):
            ctx.ingest_remote(resp["Trace"])
        results = [Result.from_dict(r) for r in resp.get("Results", [])]
        os_info = OS.from_dict(resp["OS"]) if resp.get("OS") else None
        return results, os_info

    def _poll_progress(self, ctx, stop: threading.Event) -> None:
        """Background join of the server's live progress while the scan
        RPC is in flight: each snapshot folds into the local ScanProgress
        (its ``remote`` field), so ``--live`` and heartbeats can show the
        server side of a remote scan as it runs."""
        with obs.activate(ctx):
            while not stop.wait(PROGRESS_POLL_SECS):
                try:
                    snap = self.progress(ctx.trace_id)
                except Exception:
                    # advisory polling: ANY failure (scan not registered
                    # yet, a proxy's HTML error body breaking json.loads,
                    # a truncated read) skips this tick, never kills the
                    # poller for the rest of a long scan
                    continue
                ctx.progress().merge_remote(snap)

    def scan(self, target: str, artifact_id: str, blob_ids: list[str],
             options: ScanOptions):
        ctx = obs.current()
        # the rpc.scan span is the parent the server's trace joins under
        # (its id rides the traceparent header _post attaches); WantTrace
        # asks the server to return its span table, which merges into this
        # context so --trace-out/report cover both sides of the wire.
        # With telemetry attached (a sampler set ctx.timeseries), a poller
        # joins the server's live progress for the duration of the RPC.
        stop = threading.Event()
        poller = None
        if ctx.timeseries is not None:
            poller = threading.Thread(
                target=self._poll_progress, args=(ctx, stop), daemon=True,
                name="rpc-progress-poll",
            )
            poller.start()
        try:
            with ctx.span("rpc.scan"):
                resp = _post(
                    self.base,
                    rpc.SCANNER_SCAN,
                    self._scan_payload(
                        target, artifact_id, blob_ids, options,
                        bool(ctx.enabled),
                    ),
                    self.token,
                    self.token_header,
                    self.timeout,
                    self.retries,
                    self.deadline,
                )
        finally:
            if poller is not None:
                stop.set()
                poller.join(timeout=5.0)
        if ctx.enabled and resp.get("Trace"):
            ctx.ingest_remote(resp["Trace"])
        results = [Result.from_dict(r) for r in resp.get("Results", [])]
        os_info = OS.from_dict(resp["OS"]) if resp.get("OS") else None
        return results, os_info
